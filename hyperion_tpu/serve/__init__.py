"""Serving layer — continuous batching over the paged KV decode path.

The ROADMAP's north star is a system that serves heavy traffic;
`infer/generate.py` gives one process one prompt and one exit. This
package is the request path between those: an Orca-style
continuous-batching engine on a paged `[num_blocks, block_size]` KV
pool addressed through per-slot block tables (`engine`), the host-side
block manager + radix prefix cache that make a shared system prompt
prefill once and copy-on-write share thereafter (`blocks`), a bounded
admission queue with backpressure, deadlines, a prefill budget, and a
block-availability gate (`queue`), serving SLO + cache-pressure gauges
on the obs registry (`metrics`), a JSONL stdin/socket front-end +
client (`server`, `client`), and a deterministic Poisson load driver
with a shared-prefix workload mode (`loadgen`). Every request streams
its lifecycle (admitted → scheduled → prefill → first token →
finished, with per-phase wait/compute/transport totals) onto the obs
telemetry stream; `obs trace` (`obs/timeline.py`) turns that into
waterfalls, Chrome trace exports, and tail-latency attribution.
The request path is also crash-safe: an append-only request journal
(`journal`) write-ahead-logs every admission and emitted token so a
killed engine's supervised restart (`hyperion serve --supervise`, on
the shared `hyperion_tpu/supervisor.py` core) replays unfinished
requests to bit-identical completion, with a poison-pill rule for
requests that crash the engine repeatedly; SIGTERM drains gracefully,
and an overload brownout governor (`queue.BrownoutGovernor`) sheds
deadline-doomed work with hysteresis instead of collapsing.
`SERVING.md` documents the paged design, why recompile-free refill is
the whole game on TPU, the tracing event vocabulary, and the crash
recovery / drain / brownout semantics.

One process is one replica. The replica tier (`router`, `replica`)
multiplies it: `hyperion route --replicas N` spawns N engines as
supervised children (own socket/journal/telemetry/heartbeat each) and
dispatches with least-loaded scoring off the heartbeat payloads,
session/prefix affinity so each replica's radix cache keeps hitting,
heartbeat-gated ejection/readmission, and exactly-once failover —
token stream indices + seed-deterministic recompute let a request
whose replica died mid-stream finish on another replica without
duplicating a single token, while the dead replica's journal replays
sink-less on restart. `obs doctor <base-dir>` renders the fleet.
"""

from hyperion_tpu.serve.blocks import (  # noqa: F401
    BlockManager,
    RadixPrefixCache,
)
from hyperion_tpu.serve.engine import Engine, EngineConfig, TokenEvent  # noqa: F401
from hyperion_tpu.serve.journal import RequestJournal  # noqa: F401
from hyperion_tpu.serve.loadgen import LoadSpec, run_load  # noqa: F401
from hyperion_tpu.serve.metrics import ServeMetrics  # noqa: F401
from hyperion_tpu.serve.queue import (  # noqa: F401
    AdmissionQueue,
    BrownoutGovernor,
    Request,
)
from hyperion_tpu.serve.replica import ReplicaHandle  # noqa: F401
from hyperion_tpu.serve.router import Router, RouterPolicy  # noqa: F401
