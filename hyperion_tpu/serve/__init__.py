"""Serving layer — continuous batching over the KV-cache decode path.

The ROADMAP's north star is a system that serves heavy traffic;
`infer/generate.py` gives one process one prompt and one exit. This
package is the request path between those: an Orca-style
continuous-batching engine on a static-shape `[slots, max_len]` KV
cache (`engine`), a bounded admission queue with backpressure,
deadlines, and a prefill budget (`queue`), serving SLO gauges on the
obs registry (`metrics`), a JSONL stdin/socket front-end + client
(`server`, `client`), and a deterministic Poisson load driver
(`loadgen`). `SERVING.md` documents the static-shape slot design and
why recompile-free refill is the whole game on TPU.
"""

from hyperion_tpu.serve.engine import Engine, EngineConfig, TokenEvent  # noqa: F401
from hyperion_tpu.serve.loadgen import LoadSpec, run_load  # noqa: F401
from hyperion_tpu.serve.metrics import ServeMetrics  # noqa: F401
from hyperion_tpu.serve.queue import AdmissionQueue, Request  # noqa: F401
