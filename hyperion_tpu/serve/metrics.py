"""Serving gauges on the PR-1 obs registry — SLO numbers, not step numbers.

Training telemetry asks "how fast is the loop"; serving telemetry asks
"what does a user experience". The four canonical serving signals:

  * **TTFT** (time to first token) — submission → first emitted token,
    queue wait + prefill included. The interactive-feel number.
  * **TPOT** (time per output token) — inter-token gap during decode.
    The streaming-smoothness number.
  * **e2e latency** — submission → final token, p50/p95/p99.
  * **throughput + saturation** — aggregate tokens/sec, queue depth,
    slot occupancy, rejected/timed-out counts.

Everything lands in one `MetricsRegistry` (histograms carry
p50/p90/p95/p99 in every snapshot) and streams through the same tracer
records trainers use, so `obs summarize`, `obs doctor`, and `obs diff`
read serve runs with zero new parsers. The `tokens_per_s` gauge is
deliberately the SAME key the trainers publish: a serve run's
throughput rides every existing reader.
"""

from __future__ import annotations

from hyperion_tpu.obs.registry import MetricsRegistry
from hyperion_tpu.serve.queue import SLA_CLASSES
from hyperion_tpu.utils.clock import SYSTEM


class ServeMetrics:
    """Serving instruments over one registry; the engine is the only
    writer, any tracer snapshot is the reader."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 clock=SYSTEM):
        self.reg = registry or MetricsRegistry()
        self._clock = clock
        self._t0 = clock()
        self._tokens = 0
        self._prefix_lookups = 0
        self._prefix_hits = 0
        # pre-create the lifecycle counters: a drained run that never
        # rejected anything should snapshot rejected=0, not omit the
        # key (absent evidence reads as "unknown" downstream)
        for name in ("serve_accepted", "serve_rejected",
                     "serve_timed_out", "serve_completed", "serve_ticks",
                     "serve_prefix_lookups", "serve_prefix_hits",
                     "serve_prefill_tokens_saved", "serve_preempted",
                     "serve_cow_copies", "serve_blocks_evicted",
                     # crash-safety + overload (journal/drain/brownout)
                     "serve_shed", "serve_brownout_clamped",
                     "serve_replayed", "serve_poisoned",
                     "serve_journal_errors", "serve_dropped_sinks",
                     # SLO burn-rate alerting (obs/slo.py): a run that
                     # never alerted must snapshot raised=0, not omit it
                     "serve_alerts_raised", "serve_alerts_cleared",
                     # speculative decoding (serve/draft.py + the
                     # engine's spec tick): drafted = accepted+rejected
                     "serve_spec_drafted", "serve_spec_accepted",
                     "serve_spec_rejected",
                     # compile ledger (obs/ledger.py): pinned at zero
                     # by the obs diff gate — any value > 0 is a broken
                     # recompile-free invariant
                     "serve_recompiles",
                     # tiered KV (serve/hostcache.py): every radix walk
                     # lands in exactly one tier bucket — host when the
                     # spill tier restored anything, device when only
                     # HBM blocks matched, miss otherwise
                     "serve_tier_hits_device", "serve_tier_hits_host",
                     "serve_tier_miss", "serve_host_spilled_blocks",
                     "serve_host_restored_blocks", "serve_spill_bytes",
                     "serve_restore_bytes"):
            self.reg.counter(name)
        # per-SLO-class lifecycle counters: the isolation contract is
        # judged from these (batch sheds while interactive sheds stay
        # 0), so every class/key pair must render even when untouched
        for cls in SLA_CLASSES:
            for stem in ("serve_accepted", "serve_completed",
                         "serve_shed", "serve_brownout_clamped"):
                self.reg.counter(f"{stem}_{cls}")
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._tick_tokens = 0
        self._ticks = 0
        self._tier_lookups = 0
        self._tier_host_hits = 0
        self._restore_bytes = 0
        # 0/1 flag, pre-set so "never browned out" snapshots as 0
        self.reg.gauge("serve_brownout_active").set(0.0)
        self.reg.gauge("serve_alerts_active").set(0.0)
        # router-ordered batch brownout (the `class_brownout` control
        # verb), distinct from the local governor's flag
        self.reg.gauge("serve_class_brownout").set(0.0)

    # -------------------------------------------------- admission edge

    def on_accept(self, sla_class: str | None = None) -> None:
        self.reg.counter("serve_accepted").inc()
        if sla_class:
            self.reg.counter(f"serve_accepted_{sla_class}").inc()

    def on_reject(self, reason: str) -> None:
        self.reg.counter("serve_rejected").inc()
        self.reg.counter(f"serve_rejected_{reason}").inc()

    def on_timeout(self) -> None:
        self.reg.counter("serve_timed_out").inc()

    def on_recompile(self, n: int = 1) -> None:
        """Post-warmup jit-cache growth (compile ledger `check`): n new
        executables appeared after the baseline was pinned."""
        self.reg.counter("serve_recompiles").inc(n)

    # ------------------------------------------------- per-request SLOs

    def on_first_token(self, req, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        ttft_ms = (now - req.submitted_at) * 1e3
        self.reg.histogram("ttft_ms").observe(ttft_ms)
        # per-class TTFT is the isolation number: interactive's tail
        # must hold while batch absorbs the hostile load
        self.reg.histogram(f"ttft_{req.sla_class}_ms").observe(ttft_ms)

    def on_token_gap(self, gap_s: float, sla_class: str | None = None,
                     ) -> None:
        self.reg.histogram("tpot_ms").observe(gap_s * 1e3)
        if sla_class:
            self.reg.histogram(f"tpot_{sla_class}_ms").observe(gap_s * 1e3)

    def on_finish(self, req, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        self.reg.counter("serve_completed").inc()
        self.reg.counter(f"serve_completed_{req.sla_class}").inc()
        self.reg.histogram("e2e_ms").observe(
            (now - req.submitted_at) * 1e3)

    def on_client_write(self, dur_s: float) -> None:
        """One transport-sink write (engine `_emit`): the time a slow
        client charges to its own request."""
        self.reg.histogram("client_write_ms").observe(dur_s * 1e3)

    def on_phases(self, req) -> None:
        """Per-phase totals of a finished request (engine
        `_on_finished`), from the canonical `Request.phases_s` mapping.
        client_write is skipped: its histogram (`client_write_ms`)
        observes individual sink writes via `on_client_write`, not
        per-request totals."""
        for name, v in req.phases_s().items():
            if name != "client_write":
                self.reg.histogram(f"{name}_ms").observe(v * 1e3)

    # -------------------------------------------------- paged KV cache

    def on_prefix_lookup(self, prompt_tokens: int, cached_tokens: int) -> None:
        """One radix walk at admission: `cached_tokens` of the
        `prompt_tokens`-long prompt came from shared blocks instead of
        prefill compute. The hit-rate gauge is the fraction of lookups
        that reused ANYTHING; tokens-saved is the prefill work that
        never ran — the number that turns into TTFT under a shared
        system prompt."""
        self._prefix_lookups += 1
        self.reg.counter("serve_prefix_lookups").inc()
        if cached_tokens > 0:
            self._prefix_hits += 1
            self.reg.counter("serve_prefix_hits").inc()
            self.reg.counter("serve_prefill_tokens_saved").inc(cached_tokens)
        self.reg.gauge("serve_prefix_hit_rate").set(
            self._prefix_hits / self._prefix_lookups)

    # ------------------------------------------ tiered KV (hostcache)

    def on_tier_lookup(self, device_tokens: int, host_tokens: int) -> None:
        """Tier attribution for one radix walk (engine `_admit`): the
        host bucket means the spill tier restored at least one block
        this admission — the copy that replaced a re-prefill. The
        host-hit-rate gauge is host hits over ALL lookups: the fraction
        of admissions the host tier personally rescued."""
        self._tier_lookups += 1
        if host_tokens > 0:
            self._tier_host_hits += 1
            self.reg.counter("serve_tier_hits_host").inc()
        elif device_tokens > 0:
            self.reg.counter("serve_tier_hits_device").inc()
        else:
            self.reg.counter("serve_tier_miss").inc()
        self.reg.gauge("serve_tier_hit_rate_host").set(
            self._tier_host_hits / self._tier_lookups)

    def on_host_spill(self, nbytes: int) -> None:
        """One block demoted device -> host (radix eviction's spill)."""
        self.reg.counter("serve_host_spilled_blocks").inc()
        self.reg.counter("serve_spill_bytes").inc(nbytes)

    def on_host_restore(self, blocks: int, nbytes: int) -> None:
        """One admission promoted `blocks` spilled blocks host ->
        device. The bytes/s gauge is the windowed restore bandwidth —
        the H2D cost the tier pays instead of re-prefill compute."""
        self.reg.counter("serve_host_restored_blocks").inc(blocks)
        self.reg.counter("serve_restore_bytes").inc(nbytes)
        self._restore_bytes += nbytes
        elapsed = self._clock() - self._t0
        if elapsed > 0:
            self.reg.gauge("serve_restore_bytes_per_s").set(
                self._restore_bytes / elapsed)

    def observe_host_cache(self, occupancy_mb: float, chains: int) -> None:
        """Host-tier occupancy after a spill or restore — the memory
        ledger's host-side sibling of blocks_in_use."""
        self.reg.gauge("serve_host_cache_mb").set(occupancy_mb)
        self.reg.gauge("serve_host_cache_chains").set(chains)

    def on_preempt(self) -> None:
        self.reg.counter("serve_preempted").inc()

    # ------------------------------------- crash safety + overload (PR 8)

    def on_shed(self, sla_class: str | None = None) -> None:
        """Brownout shed one deadline-doomed queued request."""
        self.reg.counter("serve_shed").inc()
        if sla_class:
            self.reg.counter(f"serve_shed_{sla_class}").inc()

    def on_clamp(self, sla_class: str | None = None) -> None:
        """Brownout clamped a new admission's max_new_tokens."""
        self.reg.counter("serve_brownout_clamped").inc()
        if sla_class:
            self.reg.counter(f"serve_brownout_clamped_{sla_class}").inc()

    def set_brownout(self, active: bool) -> None:
        self.reg.gauge("serve_brownout_active").set(1.0 if active else 0.0)

    def set_class_brownout(self, active: bool) -> None:
        """Router-ordered batch-class brownout (the PR-13 control-verb
        channel) — tracked apart from the local governor so the
        exposition payload can say WHO degraded the batch tier."""
        self.reg.gauge("serve_class_brownout").set(1.0 if active else 0.0)

    def on_replay(self) -> None:
        """One journaled request re-admitted at recovery."""
        self.reg.counter("serve_replayed").inc()

    def on_poisoned(self) -> None:
        """One request quarantined by the crash-replay poison rule."""
        self.reg.counter("serve_poisoned").inc()

    def on_journal_error(self) -> None:
        self.reg.counter("serve_journal_errors").inc()

    def on_dropped_sink(self) -> None:
        """A client died mid-stream; its sink was dropped."""
        self.reg.counter("serve_dropped_sinks").inc()

    def on_cow(self) -> None:
        self.reg.counter("serve_cow_copies").inc()

    def on_evict(self, n: int) -> None:
        self.reg.counter("serve_blocks_evicted").inc(n)

    def observe_cache(self, blocks_in_use: int, blocks_free: int,
                      active_reqs: int, block_bytes: int) -> None:
        """Cache-pressure gauges, refreshed every step. blocks_in_use
        near capacity with preemptions counting up = `--num-blocks`
        undersized; hbm_per_req_mb is the honest per-request memory
        cost AFTER sharing — the number the slab design could never
        report below slots x max_len."""
        self.reg.gauge("serve_blocks_in_use").set(blocks_in_use)
        self.reg.gauge("serve_blocks_free").set(blocks_free)
        if active_reqs:
            self.reg.gauge("serve_hbm_per_req_mb").set(
                blocks_in_use * block_bytes / active_reqs / 2**20)

    # ------------------------------------------------------- loop state

    def count_tokens(self, n: int) -> None:
        """Delivered-token accounting — tick emissions AND the
        prefill-sampled first token of each request (TTFT's token)
        both flow through here, so tokens_per_s matches what clients
        actually received."""
        if n:
            self._tokens += n
            self.reg.counter("tokens").inc(n)

    def on_tick(self, dur_s: float, tokens_emitted: int,
                slot_ticks: int | None = None) -> None:
        self.reg.counter("serve_ticks").inc()
        self.reg.histogram("serve_tick_ms").observe(dur_s * 1e3)
        self.count_tokens(tokens_emitted)
        # effective tokens per SLOT-tick (one live slot in one tick):
        # decode emissions over slot-ticks, prefill firsts excluded.
        # The sequential tick's ceiling is exactly 1.0 — anything
        # above is speculation actually landing, which is why the
        # bench/diff gate reads this gauge and not raw throughput
        self._ticks += slot_ticks if slot_ticks is not None \
            else tokens_emitted
        self._tick_tokens += tokens_emitted
        if self._ticks:
            self.reg.gauge("serve_tokens_per_tick").set(
                self._tick_tokens / self._ticks)

    def on_spec(self, drafted: int, accepted: int) -> None:
        """One slot's verify outcome this tick: `drafted` proposals
        entered the window, `accepted` survived the longest-prefix
        rule. The correction token is NOT counted — it's a normal
        decode token the sequential tick would also have produced,
        so accept_rate measures pure draft quality."""
        self._spec_drafted += drafted
        self._spec_accepted += accepted
        self.reg.counter("serve_spec_drafted").inc(drafted)
        self.reg.counter("serve_spec_accepted").inc(accepted)
        self.reg.counter("serve_spec_rejected").inc(drafted - accepted)
        if self._spec_drafted:
            self.reg.gauge("serve_spec_accept_rate").set(
                self._spec_accepted / self._spec_drafted)

    def observe_state(self, queue_depth: int, slots_active: int,
                      n_slots: int) -> None:
        """Saturation gauges, refreshed every tick (cheap: three host
        floats). Occupancy near 1.0 with queue depth growing = scale
        out; occupancy low with rejections = prompt lengths exceed the
        cache, not capacity."""
        self.reg.gauge("queue_depth").set(queue_depth)
        self.reg.gauge("slots_active").set(slots_active)
        self.reg.gauge("slot_occupancy").set(
            slots_active / n_slots if n_slots else 0.0)
        elapsed = self._clock() - self._t0
        if elapsed > 0:
            # same key the trainers publish: every obs reader already
            # knows what tokens_per_s means
            self.reg.gauge("tokens_per_s").set(self._tokens / elapsed)

    # ---------------------------------------------------------- summary

    def summary(self) -> dict:
        """Host-side roll-up for the drain report / load generator."""
        snap = self.reg.snapshot()
        c, g, h = snap["counters"], snap["gauges"], snap["histograms"]
        total = (c.get("serve_accepted", 0) + c.get("serve_rejected", 0))
        return {
            "accepted": int(c.get("serve_accepted", 0)),
            "rejected": int(c.get("serve_rejected", 0)),
            "timed_out": int(c.get("serve_timed_out", 0)),
            "completed": int(c.get("serve_completed", 0)),
            "reject_rate": (c.get("serve_rejected", 0) / total
                            if total else 0.0),
            "tokens": int(c.get("tokens", 0)),
            "tokens_per_s": g.get("tokens_per_s"),
            "ttft_ms": h.get("ttft_ms", {"count": 0}),
            "tpot_ms": h.get("tpot_ms", {"count": 0}),
            "e2e_ms": h.get("e2e_ms", {"count": 0}),
            # per-phase tail attribution (on_phases/on_client_write)
            "queue_wait_ms": h.get("queue_wait_ms", {"count": 0}),
            "gate_wait_ms": h.get("gate_wait_ms", {"count": 0}),
            "prefill_ms": h.get("prefill_ms", {"count": 0}),
            "decode_ms": h.get("decode_ms", {"count": 0}),
            "preempt_replay_ms": h.get("preempt_replay_ms", {"count": 0}),
            "client_write_ms": h.get("client_write_ms", {"count": 0}),
            "ticks": int(c.get("serve_ticks", 0)),
            # paged-cache pressure (serve/blocks.py)
            "prefix_lookups": int(c.get("serve_prefix_lookups", 0)),
            "prefix_hits": int(c.get("serve_prefix_hits", 0)),
            "prefix_hit_rate": g.get("serve_prefix_hit_rate", 0.0),
            "prefill_tokens_saved": int(
                c.get("serve_prefill_tokens_saved", 0)),
            "preempted": int(c.get("serve_preempted", 0)),
            "cow_copies": int(c.get("serve_cow_copies", 0)),
            "blocks_evicted": int(c.get("serve_blocks_evicted", 0)),
            # tiered KV (serve/hostcache.py): the device/host/miss
            # split plus the spill tier's own traffic
            "tier_hits_device": int(c.get("serve_tier_hits_device", 0)),
            "tier_hits_host": int(c.get("serve_tier_hits_host", 0)),
            "tier_miss": int(c.get("serve_tier_miss", 0)),
            "tier_hit_rate_host": g.get("serve_tier_hit_rate_host", 0.0),
            "host_spilled_blocks": int(
                c.get("serve_host_spilled_blocks", 0)),
            "host_restored_blocks": int(
                c.get("serve_host_restored_blocks", 0)),
            "restore_bytes": int(c.get("serve_restore_bytes", 0)),
            "restore_bytes_per_s": g.get("serve_restore_bytes_per_s",
                                         0.0),
            "host_cache_mb": g.get("serve_host_cache_mb", 0.0),
            "blocks_in_use": g.get("serve_blocks_in_use"),
            "hbm_per_req_mb": g.get("serve_hbm_per_req_mb"),
            # crash safety + overload (journal/drain/brownout)
            "shed": int(c.get("serve_shed", 0)),
            "brownout_clamped": int(c.get("serve_brownout_clamped", 0)),
            "brownout_active": bool(g.get("serve_brownout_active", 0.0)),
            "class_brownout": bool(g.get("serve_class_brownout", 0.0)),
            # per-SLO-class isolation roll-up: the drill's verdict keys
            "by_class": {
                cls: {
                    "accepted": int(c.get(f"serve_accepted_{cls}", 0)),
                    "completed": int(c.get(f"serve_completed_{cls}", 0)),
                    "shed": int(c.get(f"serve_shed_{cls}", 0)),
                    "clamped": int(
                        c.get(f"serve_brownout_clamped_{cls}", 0)),
                    "ttft_ms": h.get(f"ttft_{cls}_ms", {"count": 0}),
                    "tpot_ms": h.get(f"tpot_{cls}_ms", {"count": 0}),
                } for cls in SLA_CLASSES},
            "replayed": int(c.get("serve_replayed", 0)),
            "poisoned": int(c.get("serve_poisoned", 0)),
            "journal_errors": int(c.get("serve_journal_errors", 0)),
            "dropped_sinks": int(c.get("serve_dropped_sinks", 0)),
            # SLO burn-rate alerting (obs/slo.py)
            "alerts_raised": int(c.get("serve_alerts_raised", 0)),
            "alerts_cleared": int(c.get("serve_alerts_cleared", 0)),
            "alerts_active": int(g.get("serve_alerts_active") or 0),
            # speculative decoding (serve/draft.py + the spec tick):
            # accept_rate is None on a spec-disabled run (nothing was
            # ever drafted), never a misleading 0.0
            "spec_drafted": int(c.get("serve_spec_drafted", 0)),
            "spec_accepted": int(c.get("serve_spec_accepted", 0)),
            "spec_rejected": int(c.get("serve_spec_rejected", 0)),
            "accept_rate": g.get("serve_spec_accept_rate"),
            "tokens_per_tick": g.get("serve_tokens_per_tick"),
            # compile ledger (obs/ledger.py): the zero-pinned diff gate
            "recompiles": int(c.get("serve_recompiles", 0)),
        }


class RouterMetrics:
    """Fleet-level instruments for the replica router (serve/router.py)
    — same registry/snapshot discipline as ServeMetrics, different
    questions: not "how fast is one engine" but "how evenly is the
    fleet loaded, how sticky is affinity, and how often did health
    ejection fire". Unlike ServeMetrics (single engine-thread writer),
    these instruments are hit from MANY relay threads concurrently, so
    every mutation takes the lock — a lost increment here would skew
    the fairness/scaleup numbers bench reads back from router_end."""

    def __init__(self, registry: MetricsRegistry | None = None):
        import threading

        self.reg = registry or MetricsRegistry()
        self._lock = threading.Lock()
        for name in ("route_dispatched", "route_redispatched",
                     "route_rejected", "route_completed",
                     "route_affinity_lookups", "route_affinity_hits",
                     "replica_ejections", "replica_readmits",
                     # SLO alerting: the router's OWN burn-rate alerts
                     # (obs/slo.py publishes with prefix="route") plus
                     # the fleet tally of alerts its replicas report on
                     # their heartbeats — both pre-created so 0 renders
                     "route_alerts_raised", "route_alerts_cleared",
                     "fleet_alerts_raised",
                     # the acting router (alert-driven control): every
                     # steer/scale/brownout decision is counted so a
                     # flapping policy is visible as a number, not vibes
                     "router_steers", "router_unsteers",
                     "router_scale_up", "router_scale_down",
                     "class_brownouts_ordered",
                     "class_brownouts_lifted",
                     # router crash safety: client streams resumed
                     # across a disconnect, WAL orphans recovered by a
                     # new router life, replicas adopted (taken over
                     # live, no respawn) from a previous life
                     "route_resumes", "route_orphans_recovered",
                     "route_adopted",
                     # cache-aware routing (serve/hostcache.py): the
                     # dispatch went to a replica ADVERTISING the
                     # request's prefix root on its heartbeat — prefix
                     # locality without a session id
                     "route_cache_steered"):
            self.reg.counter(name)
        self.reg.gauge("fleet_ready").set(0.0)
        self.reg.gauge("fleet_inflight").set(0.0)
        self.reg.gauge("fleet_alerts_active").set(0.0)
        self.reg.gauge("route_alerts_active").set(0.0)
        self.reg.gauge("fleet_steered").set(0.0)

    def on_dispatch(self, replica: int, affinity_hit: bool,
                    had_key: bool, cache_hit: bool = False) -> None:
        with self._lock:
            self.reg.counter("route_dispatched").inc()
            self.reg.counter(f"route_dispatched_replica_{replica}").inc()
            if cache_hit:
                self.reg.counter("route_cache_steered").inc()
            if had_key:
                lookups = self.reg.counter("route_affinity_lookups")
                hits = self.reg.counter("route_affinity_hits")
                lookups.inc()
                if affinity_hit:
                    hits.inc()
                self.reg.gauge("route_affinity_hit_rate").set(
                    hits.value / lookups.value)

    def on_redispatch(self, reason: str) -> None:
        with self._lock:
            self.reg.counter("route_redispatched").inc()
            self.reg.counter(f"route_redispatched_{reason}").inc()

    def on_reject(self, reason: str) -> None:
        with self._lock:
            self.reg.counter("route_rejected").inc()
            self.reg.counter(f"route_rejected_{reason}").inc()

    def on_complete(self) -> None:
        with self._lock:
            self.reg.counter("route_completed").inc()

    def on_eject(self) -> None:
        with self._lock:
            self.reg.counter("replica_ejections").inc()

    def on_readmit(self) -> None:
        with self._lock:
            self.reg.counter("replica_readmits").inc()

    def observe_fleet(self, ready: int, inflight: int,
                      alerts_active: int | None = None) -> None:
        with self._lock:
            self.reg.gauge("fleet_ready").set(ready)
            self.reg.gauge("fleet_inflight").set(inflight)
            if alerts_active is not None:
                self.reg.gauge("fleet_alerts_active").set(alerts_active)

    def on_steer(self, on: bool) -> None:
        """One steering transition: `on` = interactive traffic moved
        OFF a burning replica, False = hysteresis-clean reversal."""
        with self._lock:
            self.reg.counter(
                "router_steers" if on else "router_unsteers").inc()

    def on_scale(self, up: bool) -> None:
        with self._lock:
            self.reg.counter(
                "router_scale_up" if up else "router_scale_down").inc()

    def on_class_brownout(self, on: bool) -> None:
        with self._lock:
            self.reg.counter("class_brownouts_ordered" if on
                             else "class_brownouts_lifted").inc()

    def observe_steered(self, n: int) -> None:
        with self._lock:
            self.reg.gauge("fleet_steered").set(n)

    def on_resume(self) -> None:
        """One client resume verb answered (reconnect after a wire cut
        or a router death)."""
        with self._lock:
            self.reg.counter("route_resumes").inc()

    def on_failover_gap(self, gap_s: float) -> None:
        """One failover gap closed: seconds from detecting a replica
        death mid-stream to the first record the client saw from the
        replacement (connect retries against the restart included)."""
        with self._lock:
            self.reg.histogram("route_failover_gap_ms").observe(
                max(0.0, gap_s) * 1000.0)

    def on_orphans(self, n: int) -> None:
        """`n` orphaned dispatches recovered from a previous router
        life's WAL."""
        if n:
            with self._lock:
                self.reg.counter("route_orphans_recovered").inc(n)

    def on_adopt(self) -> None:
        """One still-live replica adopted from a previous router life
        (taken over from its heartbeat, not respawned)."""
        with self._lock:
            self.reg.counter("route_adopted").inc()

    def on_fleet_alerts(self, n_new: int) -> None:
        """`n_new` alert names appeared on replica heartbeats since the
        last monitor sweep (serve/router.py counts the transitions —
        this is the fleet-wide raise tally bench's serving_scale row
        reads back from router_end)."""
        if n_new:
            with self._lock:
                self.reg.counter("fleet_alerts_raised").inc(n_new)

    def summary(self) -> dict:
        with self._lock:
            snap = self.reg.snapshot()
            gaps = self.reg.histogram("route_failover_gap_ms")
            failover_gap_p99_ms = (round(gaps.percentile(99), 3)
                                   if gaps.summary()["count"] else 0.0)
        c, g = snap["counters"], snap["gauges"]
        share = {
            k.removeprefix("route_dispatched_replica_"): int(v)
            for k, v in c.items()
            if k.startswith("route_dispatched_replica_")
        }
        return {
            "dispatched": int(c.get("route_dispatched", 0)),
            "redispatched": int(c.get("route_redispatched", 0)),
            "rejected": int(c.get("route_rejected", 0)),
            "completed": int(c.get("route_completed", 0)),
            "affinity_lookups": int(c.get("route_affinity_lookups", 0)),
            "affinity_hits": int(c.get("route_affinity_hits", 0)),
            "affinity_hit_rate": g.get("route_affinity_hit_rate"),
            "cache_steered": int(c.get("route_cache_steered", 0)),
            "ejections": int(c.get("replica_ejections", 0)),
            "readmits": int(c.get("replica_readmits", 0)),
            "per_replica_dispatched": share,
            # SLO alerting: router-local raises + the fleet tally of
            # replica-reported alerts (both ride router_end)
            "alerts_raised": int(c.get("route_alerts_raised", 0)),
            "fleet_alerts_raised": int(c.get("fleet_alerts_raised", 0)),
            "fleet_alerts_active": int(g.get("fleet_alerts_active") or 0),
            # the acting router: control decisions taken this run
            "steers": int(c.get("router_steers", 0)),
            "unsteers": int(c.get("router_unsteers", 0)),
            "scale_up": int(c.get("router_scale_up", 0)),
            "scale_down": int(c.get("router_scale_down", 0)),
            "class_brownouts": int(c.get("class_brownouts_ordered", 0)),
            "steered_now": int(g.get("fleet_steered") or 0),
            # router crash safety (rides router_end for bench/doctor)
            "resumes": int(c.get("route_resumes", 0)),
            "orphans_recovered": int(c.get("route_orphans_recovered", 0)),
            "adopted": int(c.get("route_adopted", 0)),
            # failover-gap tail (ms): 0.0 when no failover fired, so
            # the bench `serving_scale` row and the diff gate stay live
            # on healthy runs instead of going missing
            "failover_gap_p99_ms": failover_gap_p99_ms,
        }
