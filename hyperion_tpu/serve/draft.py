"""Draft sources for the engine's speculative tick — self-drafting
n-gram lookup, behind an interface a real draft model can implement.

The serve engine (`serve/engine.py`) drafts up to k tokens per active
slot each tick and verifies them all in ONE batched target forward.
What proposes those tokens is pluggable: `DraftSource.propose` maps a
slot's visible context (prompt + everything generated so far) to k
candidate next tokens. Correctness never depends on the proposals —
the verify pass accepts exactly the longest prefix the target itself
would have produced (`infer/speculative.accept_draft`), so a bad draft
costs speed, never tokens. That makes the interface safe to fill with
anything cheap.

`NgramDraft` is the no-second-checkpoint baseline (prompt-lookup /
suffix-matching decoding): find the most recent earlier occurrence of
the current context suffix and propose the tokens that followed it.
Pure host-side numpy over the per-slot token lists the engine already
keeps — the `[S, k]` proposal array ships with the tick like the block
table, so drafting adds zero device work and can never trace a jit.
It wins exactly when decoding revisits its own context — system-prompt
boilerplate, quoted input, code idioms, and the repetitive spans
(lists, loops) where sequential decoding wastes the most ticks.

A future tiny-model drafter implements the same `propose` (keyed by
`slot` so it can keep per-slot state across ticks) and plugs in behind
`--draft` without touching the engine.
"""

from __future__ import annotations

import numpy as np


class DraftSource:
    """Interface the engine calls once per active slot per tick.

    `propose(slot, prompt_ids, generated, k)` returns k int32 token
    proposals for the slot whose visible context is `prompt_ids`
    (np.ndarray) followed by `generated` (host list of emitted token
    ints). Proposals are verified — never trusted — so any return
    value is safe; garbage just decays the tick to one token. `slot`
    identifies the lane so stateful drafters can cache per-slot work
    (the engine reuses slot indices after a request frees, so keying
    on slot alone is only valid within one request's residency —
    derive identity from the context if state must outlive it).
    """

    def propose(self, slot: int, prompt_ids: np.ndarray,
                generated: list[int], k: int) -> np.ndarray:
        raise NotImplementedError


class NgramDraft(DraftSource):
    """Self-drafting suffix lookup: propose the continuation of the
    most recent earlier occurrence of the context's current suffix,
    longest suffix (up to `max_ngram` tokens) first."""

    def __init__(self, max_ngram: int = 3):
        if max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {max_ngram}")
        self.max_ngram = max_ngram

    def propose(self, slot: int, prompt_ids: np.ndarray,
                generated: list[int], k: int) -> np.ndarray:
        ctx = np.asarray(prompt_ids, np.int32)
        if generated:
            ctx = np.concatenate(
                [ctx, np.asarray(generated, np.int32)])
        n_ctx = int(ctx.shape[0])
        # fallback proposal: repeat the last token — free to verify,
        # and exactly right whenever decoding has entered a 1-cycle
        out = np.full((k,), int(ctx[-1]) if n_ctx else 0, np.int32)
        for n in range(min(self.max_ngram, n_ctx - 1), 0, -1):
            pat = ctx[n_ctx - n:]
            wins = np.lib.stride_tricks.sliding_window_view(ctx, n)
            # candidate starts, excluding the suffix matching itself
            hits = np.flatnonzero((wins[:-1] == pat).all(axis=1))
            if hits.size == 0:
                continue
            src = int(hits[-1]) + n  # most recent occurrence wins
            cont = ctx[src:src + k]
            out[:cont.shape[0]] = cont
            break
        return out
