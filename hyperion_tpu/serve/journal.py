"""Request journal — the write-ahead log that makes `hyperion serve`
crash-safe.

An engine crash without a journal silently loses every queued and
in-flight request: the client hangs, the supervisor restarts an empty
server, and nobody can say what was owed. The journal closes that gap
with an append-only JSONL file recording, per request, exactly what a
restart needs to finish the job:

    {"k":"admit","id":...,"prompt_ids":[...],"max_new_tokens":N,
     "temperature":t,"top_k":k,"top_p":p,"seed":s,"deadline_s":d}
    {"k":"tok","id":...,"tok":N}        one per emitted token
    {"k":"done","id":...,"reason":...}  terminal (eos/budget/timeout/shed)
    {"k":"replay","id":...,"n":K}       appended at recovery, per resume
    {"k":"poisoned","id":...,"n":K}     quarantined by the poison rule
    {"k":"close"}                       clean shutdown — replay nothing

A speculative decode tick (``--spec-k``) can accept several tokens in
one engine step; each still lands as its own `tok` record, in emission
order, before its sink write — the format and the ordering contract
below are tick-shape agnostic, so recovery neither knows nor cares
whether a token came from a sequential or a multi-token tick.

Recovery (`recover()`) replays the file: a request with an `admit` but
no terminal record is *pending* — it is handed back to the engine with
its already-emitted tokens riding along, and resumes through the same
recompute path pool-exhaustion preemption uses (re-prefill prompt +
generated; PR 6): at temperature 0 the continuation is bit-identical
to the run that never crashed, and seeded sampling resumes exactly too
because the PRNG key folds the absolute position, not the wall clock.

**Ordering contract** (why the client stream never duplicates): every
token is journaled *before* its sink write, and every append is
`flush()`ed to the kernel before the sink runs — so any token a client
ever received survives a process kill in the journal, and recovery
never re-computes (hence never re-delivers) a delivered token.
`fsync` is batched (`fsync_every` tokens; admits/terminals sync
eagerly) — a *machine* crash can lose up to one batch window, which
degrades to at-least-once for that window; a *process* crash (the
failure mode the supervisor handles) loses nothing.

**Poison rule**: each recovery appends a `replay` mark per resumed
request. A request found pending with `max_replays` marks already on
file has now crashed the engine that many times in a row — it is
quarantined with a `poisoned` record instead of re-admitted, so one
adversarial request cannot crash-loop the whole replica. Unrelated
crashes do inflate innocent bystanders' counts, which is the
conservative direction: a request that was merely *present* for
`max_replays` crashes is cheap to re-submit, an engine that never
comes up is not.

IO failures degrade, never crash: an append that raises (disk full,
`journal_io_fail@p=X` chaos) disables the journal and records the
error; the engine keeps serving with durability lost, and stamps a
`journal_io_error` event so `obs doctor` can say so.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Callable

import numpy as np

MAX_REPLAYS_DEFAULT = 2


class RequestJournal:
    """Append-only request WAL with batched fsync and crash recovery.

    Single-writer by design (the engine thread owns token/terminal
    appends; `admit` is called under the queue's submit path but the
    file object's `write` is atomic enough for whole small lines and
    every record is self-contained — a torn *final* line is expected
    and tolerated by the reader)."""

    def __init__(self, path: str | Path, *, fsync_every: int = 16,
                 fault: Callable[[str], None] | None = None):
        self.path = Path(path)
        self.fsync_every = max(1, int(fsync_every))
        self._fault = fault
        self._f = None
        self._unsynced = 0
        # admits arrive on front-end threads while the engine thread
        # appends tokens: whole-line appends must never interleave
        self._lock = threading.Lock()
        self.enabled = True
        self.error: str | None = None
        self.clean_closed = False

    # ------------------------------------------------------------ write

    def _append(self, rec: dict, sync: bool) -> None:
        if not self.enabled:
            return
        try:
            with self._lock:
                if self._fault is not None:
                    self._fault("journal_append")
                if self._f is None:
                    self.path.parent.mkdir(parents=True, exist_ok=True)
                    self._f = self.path.open("a", encoding="utf-8")
                self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
                # flush to the KERNEL on every append: a SIGKILL'd
                # process loses user-space buffers, not kernel ones —
                # this line is what makes journal-before-sink mean
                # "delivered implies durable" under process kills
                self._f.flush()
                self._unsynced += 1
                if sync or self._unsynced >= self.fsync_every:
                    os.fsync(self._f.fileno())
                    self._unsynced = 0
        except OSError as e:
            # durability degrades; the serve loop must not die of it
            self.enabled = False
            self.error = str(e)

    def admit(self, req) -> None:
        """Record an accepted request — durable before its first token
        can reference it. Sampling params and the seed ride along so a
        replay reconstructs the identical PRNG stream."""
        rec = {
            "k": "admit", "id": req.id,
            "prompt_ids": np.asarray(req.prompt_ids).tolist(),
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k), "top_p": float(req.top_p),
            "seed": int(req.seed),
            "deadline_s": (float(req.deadline_s)
                           if req.deadline_s is not None else None),
        }
        if getattr(req, "trace", None):
            # the fleet hop context survives a crash with the request,
            # so a replayed request's events still join the fleet trace
            rec["trace"] = req.trace
        self._append(rec, sync=True)

    def token(self, rid: str, tok: int) -> None:
        self._append({"k": "tok", "id": rid, "tok": int(tok)}, sync=False)

    def finish(self, rid: str, reason: str) -> None:
        self._append({"k": "done", "id": rid, "reason": reason}, sync=True)

    def close_clean(self) -> None:
        """Clean-shutdown marker: a restart after this replays nothing
        (and asserts nothing was owed)."""
        self._append({"k": "close"}, sync=True)
        self.clean_closed = True
        self.close()

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._f.close()
            except OSError:
                pass
            self._f = None

    # ------------------------------------------------------------ compact

    def _compact(self, live_ids: set[str], clean: bool = False) -> bool:
        """Rewrite the journal keeping only the current life's records
        of `live_ids`, byte-exactly, when more than half the records on
        file are dead weight (terminal requests, settled pre-close
        history, torn lines). Atomic: the kept lines land in a sibling
        temp file that `os.replace`s the journal, so a crash mid-compact
        leaves either the old file or the new one, never a torn hybrid.
        Called at recovery — supervised restarts otherwise grow the WAL
        forever with requests nobody will ever replay again."""
        try:
            with self._lock:
                if self._f is not None:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                    self._f.close()
                    self._f = None
                try:
                    lines = self.path.read_text(
                        encoding="utf-8").splitlines(keepends=True)
                except OSError:
                    return False
                keep: list[str] = []
                total = 0
                for line in lines:
                    s = line.strip()
                    if not s:
                        continue
                    total += 1
                    try:
                        rec = json.loads(s)
                    except json.JSONDecodeError:
                        continue  # torn line: never worth carrying over
                    if not isinstance(rec, dict):
                        continue
                    if rec.get("k") == "close":
                        # settled history: everything before a close is
                        # done with — a compact starts the file at the
                        # current life
                        keep.clear()
                        continue
                    if rec.get("id") in live_ids:
                        keep.append(line if line.endswith("\n")
                                    else line + "\n")
                if clean and not keep:
                    # a clean-closed file compacts to just the close
                    # marker: "nothing owed because cleanly shut down"
                    # must stay distinguishable from "no journal at all"
                    keep = ['{"k":"close"}\n']
                if total == 0 or (total - len(keep)) * 2 <= total:
                    return False
                tmp = self.path.with_name(self.path.name + ".compact")
                with tmp.open("w", encoding="utf-8") as f:
                    f.writelines(keep)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
                return True
        except OSError as e:
            # compaction is an optimization: failing it degrades to the
            # old ever-growing file, never to a lost journal
            self.error = str(e)
            return False

    # ------------------------------------------------------------- read

    def _parse(self) -> tuple[dict, list[str], bool]:
        """(state_by_id, admit_order, clean) from the file as it
        stands. A torn final line (the record a killed process never
        finished) is skipped silently; a torn middle line is counted
        but must not abort recovery — every record is independent."""
        state: dict[str, dict] = {}
        order: list[str] = []
        clean = False
        try:
            lines = self.path.read_text(encoding="utf-8").splitlines()
        except OSError:
            return {}, [], False
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn write — the crash signature itself
            if not isinstance(rec, dict):
                continue
            k = rec.get("k")
            if k == "close":
                # clean shutdown: everything before it is settled
                # history. Drop it — a later life reusing a request id
                # must not inherit the old life's done marker (which
                # would silently skip its replay) or its token list
                # (which would corrupt the resume payload).
                state.clear()
                order.clear()
                clean = True
                continue
            clean = False  # records after a close: a new serving life
            rid = rec.get("id")
            if not rid:
                continue
            st = state.setdefault(
                rid, {"admit": None, "tokens": [], "done": None,
                      "replays": 0, "poisoned": False})
            if k == "admit":
                if st["admit"] is None:
                    order.append(rid)
                st["admit"] = rec
            elif k == "tok" and rec.get("tok") is not None:
                st["tokens"].append(int(rec["tok"]))
            elif k == "done":
                st["done"] = rec.get("reason") or "done"
            elif k == "replay":
                st["replays"] = max(st["replays"], int(rec.get("n") or 0))
            elif k == "poisoned":
                st["poisoned"] = True
        return state, order, clean

    def recover(self, *, max_replays: int = MAX_REPLAYS_DEFAULT,
                eos_id: int | None = None):
        """Read the journal and mark this recovery on it.

        Returns `(resume, finished, poisoned, clean)`:
          * `resume`   — Requests (admit order) still owed work; each
            carries its journaled tokens (the recompute-resume payload)
            and a `replay` mark has been appended for it.
          * `finished` — Requests whose output was already complete
            (budget reached / eos emitted) but whose terminal record
            was lost to the crash: nothing to compute, the caller just
            owes the client a `done`.
          * `poisoned` — Requests quarantined by the poison rule
            (`max_replays` prior replays, still unfinished); a
            `poisoned` record has been appended so later recoveries
            skip them permanently.
          * `clean`    — the file ends in a clean close (resume and
            poisoned are then necessarily empty).
        """
        from hyperion_tpu.serve.queue import Request

        state, order, clean = self._parse()
        resume: list = []
        finished: list = []
        poisoned: list = []
        for rid in order:
            st = state[rid]
            if st["done"] is not None or st["poisoned"] or clean:
                continue
            a = st["admit"]
            req = Request(
                prompt_ids=np.asarray(a["prompt_ids"], np.int32),
                max_new_tokens=int(a["max_new_tokens"]),
                id=rid,
                temperature=float(a.get("temperature") or 0.0),
                top_k=int(a.get("top_k") or 0),
                top_p=float(a.get("top_p") if a.get("top_p") is not None
                            else 1.0),
                seed=int(a.get("seed") or 0),
                # the original wall deadline died with the old process;
                # a replayed request gets its deadline re-anchored to
                # re-admission — a second chance, not a free pass
                deadline_s=a.get("deadline_s"),
                trace=(a["trace"] if isinstance(a.get("trace"), dict)
                       else None),
            )
            req.tokens = list(st["tokens"])
            req.replays = st["replays"]
            complete = (
                len(req.tokens) >= req.max_new_tokens
                or (eos_id is not None and req.tokens
                    and req.tokens[-1] == eos_id)
            )
            if complete:
                finished.append(req)
                self.finish(rid, "recovered_complete")
            elif st["replays"] >= max_replays:
                poisoned.append(req)
                self._append({"k": "poisoned", "id": rid,
                              "n": st["replays"]}, sync=True)
            else:
                req.replays += 1
                self._append({"k": "replay", "id": rid,
                              "n": req.replays}, sync=True)
                resume.append(req)
        # compact AFTER the recovery marks: terminal requests (including
        # the finish/poisoned records just appended) drop out; the
        # resumed requests' admit/tok/replay history survives byte-exact
        self._compact({req.id for req in resume}, clean=clean)
        return resume, finished, poisoned, clean

    def pending_count(self) -> int:
        """Unfinished admitted requests on file right now (reader-side
        convenience for tests and the drain assertion: a cleanly
        drained journal owes nothing)."""
        state, order, clean = self._parse()
        if clean:
            return 0
        return sum(1 for rid in order
                   if state[rid]["done"] is None
                   and not state[rid]["poisoned"])
