"""Paged KV-cache bookkeeping — block manager + radix prefix cache.

The slot engine's original cache was a `[S, L]` slab: every request
owned `L` cache rows from admission to finish, so HBM burn was
proportional to the *longest possible* request, and two requests with
the same system prompt each prefilled it from scratch. PagedAttention
(Kwon et al., SOSP '23) is the standard fix: carve the cache into
fixed-size **blocks**, give each sequence a **block table** (logical
position -> physical block), and let the host hand blocks out
on demand. Memory then tracks *actual* tokens, and a block whose
contents two sequences agree on can simply appear in both tables.

This module is the host half — pure bookkeeping, no jax:

  * `BlockManager` — the physical pool: free list, per-block reference
    counts, all-or-nothing allocation, admission *reservations* (the
    scheduler's worst-case earmark), and fail-loud double-free checks.
    Physical block 0 is the **null block**: never allocated, it is the
    write target the device code routes masked/inactive lanes to, so
    garbage always has somewhere harmless to land.
  * `RadixPrefixCache` — a trie over *full* blocks of token ids. A
    finished-prefilling request registers its prompt's full blocks;
    a later request whose prompt starts with the same tokens walks the
    trie and shares those blocks instead of re-prefilling them
    (refcount++, zero device work). The trie holds one reference of
    its own per block, so cached prefixes survive their original
    request — until pool pressure evicts them, LRU-leaf first.
  * `fork_alloc` — copy-on-write fork of a sequence's allocation:
    full blocks are shared (immutable by construction — writers only
    ever append into their exclusive tail), the partially-filled tail
    block is copied into a fresh block the fork owns. The caller is
    responsible for the device-side block copy; this returns the
    (src, dst) pairs to apply.

Why sharing is safe: K/V at position p depend only on the token ids at
positions 0..p (RoPE is absolute, attention is causal), so any two
sequences with identical prefixes have bit-identical K/V for the
shared span. Only *full* blocks enter the trie, and full blocks are
never written again (writes always append at the sequence frontier,
which lives in the exclusive tail block) — shared memory is immutable
memory, and the only copy the design ever needs is the partial-tail
copy at fork/extension time.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Iterable

import numpy as np

NULL_BLOCK = 0  # reserved physical block: masked/inactive lanes write here


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to cover `tokens` positions."""
    return -(-tokens // block_size)


class BlockError(RuntimeError):
    """Bookkeeping violation (double free / unref of an unallocated
    block). Raised loudly: a silent refcount bug corrupts user-visible
    K/V, so the property test treats this as the tripwire."""


class BlockManager:
    """Fixed-size block pool: free list + refcounts + reservations.

    `num_blocks` counts physical blocks INCLUDING the reserved null
    block 0, matching the device pool's leading dimension; `capacity`
    (= num_blocks - 1) is what is actually allocatable. Allocation is
    all-or-nothing and deterministic (ascending ids), so a seeded test
    run maps to one exact block layout.

    Refcount protocol: `alloc` returns blocks at refcount 1 owned by
    the caller; every additional holder (a sharing sequence, the radix
    trie) `incref`s; `decref` at refcount 1 frees the block back to the
    pool. `reserve`/`release` track admission-time worst-case earmarks
    so the scheduler can promise growth room without allocating it yet.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is the reserved "
                             f"null block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # pop() yields ascending ids: 1, 2, 3, ... (deterministic runs)
        self._free = list(range(num_blocks - 1, NULL_BLOCK, -1))
        self._ref: dict[int, int] = {}
        self.reserved = 0  # worst-case blocks promised but not yet allocated

    # ------------------------------------------------------------ state

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.capacity - self.num_free

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    # ------------------------------------------------------- allocation

    def alloc(self, n: int) -> list[int] | None:
        """n fresh blocks at refcount 1, or None (all-or-nothing: a
        partial grant would have to be unwound by every caller)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, blocks: Iterable[int]) -> None:
        for b in blocks:
            if b not in self._ref:
                raise BlockError(f"incref of unallocated block {b}")
            self._ref[b] += 1

    def decref(self, blocks: Iterable[int]) -> None:
        for b in blocks:
            ref = self._ref.get(b)
            if ref is None:
                raise BlockError(f"free of unallocated block {b} "
                                 "(double free?)")
            if ref == 1:
                del self._ref[b]
                self._free.append(b)
            else:
                self._ref[b] = ref - 1

    # ----------------------------------------------------- reservations

    def reserve(self, n: int) -> None:
        self.reserved += n

    def release(self, n: int) -> None:
        self.reserved = max(0, self.reserved - n)

    def check(self) -> None:
        """Invariant audit (tests): every tracked block is allocated
        exactly once, free+used == capacity, refcounts positive."""
        if len(self._free) != len(set(self._free)):
            raise BlockError("free list holds duplicates")
        overlap = set(self._free) & set(self._ref)
        if overlap:
            raise BlockError(f"blocks both free and referenced: {overlap}")
        if NULL_BLOCK in self._ref or NULL_BLOCK in self._free:
            raise BlockError("null block entered circulation")
        if len(self._free) + len(self._ref) != self.capacity:
            raise BlockError(
                f"leak: {len(self._free)} free + {len(self._ref)} used "
                f"!= capacity {self.capacity}")
        if any(r < 1 for r in self._ref.values()):
            raise BlockError("non-positive refcount")


@dataclasses.dataclass
class SeqAlloc:
    """One sequence's view of the pool: its block chain in logical
    order, how much of its admission-time reservation is still
    unclaimed, and its admission order (preemption picks the
    youngest)."""

    blocks: list[int]
    n_shared: int = 0        # leading blocks also held by the radix trie
    reserved: int = 0        # worst-case blocks promised, not yet claimed
    order: int = 0           # admission sequence number
    n_filled: int = 0        # tokens written so far (the write frontier)


def fork_alloc(
    mgr: BlockManager, seq: SeqAlloc, n_filled: int,
) -> tuple[SeqAlloc | None, list[tuple[int, int]]]:
    """Copy-on-write fork of `seq` at `n_filled` tokens — the generic
    sequence-level fork primitive (beam search / parallel sampling /
    the property suite's fork model). The engine's admission-time COW
    is the trie-mediated special case of the same protocol
    (`RadixPrefixCache.lookup().cow_src` + the engine's copy jit).

    Full blocks are shared (incref — immutable, nobody writes them
    again); the partially-filled tail block, which `seq` WILL keep
    writing, is copied into a fresh block the fork owns exclusively.
    Returns (fork, copies) where `copies` is the [(src, dst)] list the
    caller must apply on device, or (None, []) when the pool cannot
    supply the tail copy."""
    bs = mgr.block_size
    n_full = n_filled // bs
    tail = n_filled - n_full * bs
    shared = seq.blocks[:n_full]
    copies: list[tuple[int, int]] = []
    new_blocks = list(shared)
    if tail:
        dst = mgr.alloc(1)
        if dst is None:
            return None, []
        copies.append((seq.blocks[n_full], dst[0]))
        new_blocks.append(dst[0])
    mgr.incref(shared)
    return SeqAlloc(blocks=new_blocks, n_shared=len(shared)), copies


# ---------------------------------------------------------------- radix


@dataclasses.dataclass(eq=False)
class _Node:
    tokens: tuple[int, ...]          # the block_size token ids this block holds
    block: int
    parent: "_Node | None"
    children: dict[tuple[int, ...], "_Node"] = dataclasses.field(
        default_factory=dict)
    last_used: int = 0


@dataclasses.dataclass
class PrefixMatch:
    """Result of a prefix-cache walk.

    `blocks` are full shared blocks (the caller increfs them when it
    commits); `tokens` counts cached positions including the COW
    extension; `cow_src` (when set) is a trie block whose first
    `tokens - len(blocks)*block_size` ids extend the match mid-block —
    the caller copies it and owns the copy."""

    blocks: list[int]
    tokens: int
    cow_src: int | None = None


class RadixPrefixCache:
    """Trie over full token blocks -> retained physical blocks.

    Nodes hold one manager reference each, so a cached chain outlives
    the request that built it; `evict` walks it back LRU-leaf-first
    under pool pressure. The children of a node are keyed by their full
    `block_size`-token chunk; longest-common-prefix against a child is
    the copy-on-write *extension*: a new prompt that diverges mid-block
    still reuses the agreeing positions via one block copy."""

    def __init__(self, mgr: BlockManager, spill=None):
        self.mgr = mgr
        self.root = _Node(tokens=(), block=NULL_BLOCK, parent=None)
        self._nodes: list[_Node] = []
        self._clock = itertools.count(1)
        # the host-tier seam (serve/hostcache.py): when set, `evict`
        # hands each dying chain's full token key + physical block to
        # the callback BEFORE the decref frees it — demotion instead of
        # deletion. `clear` never spills (shutdown/tests drop holds,
        # they don't demote), and a block some sequence still shares
        # (refcount > 1) isn't dying, so it never spills either.
        self.spill = spill

    # ------------------------------------------------------------ reads

    def __len__(self) -> int:
        return len(self._nodes)

    def evictable(self) -> int:
        """Blocks only the trie still holds (refcount 1) — what `evict`
        could free right now. A node at refcount 1 cannot have a child
        at refcount > 1 (sharers hold the whole chain), so this count
        is cascade-accurate, not just leaf-accurate."""
        return sum(1 for n in self._nodes
                   if self.mgr.refcount(n.block) == 1)

    def lookup(self, tokens: np.ndarray, limit: int) -> PrefixMatch:
        """Longest cached prefix of `tokens`, capped at `limit` matched
        positions (callers pass len-1: at least one token must remain
        to prefill, because the first sampled token needs the last
        prompt position's logits)."""
        bs = self.mgr.block_size
        node = self.root
        blocks: list[int] = []
        pos = 0
        toks = [int(t) for t in tokens]
        while pos + bs <= limit:
            child = node.children.get(tuple(toks[pos:pos + bs]))
            if child is None:
                break
            blocks.append(child.block)
            child.last_used = next(self._clock)
            node = child
            pos += bs
        # copy-on-write extension: the longest mid-block agreement with
        # any child buys `m` more cached positions for one block copy
        cap = min(limit - pos, bs)
        best_m, best_src = 0, None
        if cap > 0:
            want = toks[pos:pos + cap]
            for child in node.children.values():
                m = 0
                for a, b in zip(child.tokens, want):
                    if a != b:
                        break
                    m += 1
                if m > best_m:
                    best_m, best_src = m, child.block
                    if m == cap:
                        break
        if best_m > 0:
            child_touch = best_src  # touched via its block below
            for child in node.children.values():
                if child.block == child_touch:
                    child.last_used = next(self._clock)
                    break
            return PrefixMatch(blocks=blocks, tokens=pos + best_m,
                               cow_src=best_src)
        return PrefixMatch(blocks=blocks, tokens=pos)

    # ----------------------------------------------------------- writes

    def insert(self, tokens: np.ndarray, blocks: list[int]) -> int:
        """Register a prompt's full-block chain. `blocks` is the
        sequence's chain in logical order; only chunks whose every
        position is a prompt token are inserted (tail positions get
        generated tokens appended later — those blocks stay private).
        Chunks already present keep the incumbent node (first writer
        wins; the duplicate block stays private to its sequence).
        Returns the number of new nodes created."""
        bs = self.mgr.block_size
        n_full = min(len(tokens) // bs, len(blocks))
        node = self.root
        created = 0
        toks = [int(t) for t in tokens]
        for c in range(n_full):
            chunk = tuple(toks[c * bs:(c + 1) * bs])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(tokens=chunk, block=blocks[c], parent=node)
                node.children[chunk] = child
                self._nodes.append(child)
                self.mgr.incref([blocks[c]])  # the trie's own hold
                created += 1
            child.last_used = next(self._clock)
            node = child
        return created

    def evict(self, n: int) -> int:
        """Free up to `n` blocks by dropping least-recently-used leaves
        nobody else references; an evicted leaf may expose its parent
        for the next pass. Returns blocks actually freed."""
        freed = 0
        while freed < n:
            victim: _Node | None = None
            for node in self._nodes:
                if node.children or self.mgr.refcount(node.block) != 1:
                    continue
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                break
            self._drop(victim, spill=True)
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every trie hold (tests / shutdown). Returns blocks whose
        last reference was the trie's."""
        freed = 0
        # leaves-first: repeatedly drop nodes without children
        while self._nodes:
            progress = False
            for node in list(self._nodes):
                if node.children:
                    continue
                if self.mgr.refcount(node.block) == 1:
                    freed += 1
                self._drop(node)
                progress = True
            if not progress:  # pragma: no cover — cycle-free by construction
                break
        return freed

    def chain_tokens(self, node: _Node) -> tuple[int, ...]:
        """The full token prefix a node's block completes — root..node
        inclusive, reconstructed by walking parents. This is the host
        tier's chain key: `tokens[-block_size:]` are the node's own."""
        parts: list[tuple[int, ...]] = []
        n: _Node | None = node
        while n is not None and n.tokens:
            parts.append(n.tokens)
            n = n.parent
        return tuple(t for chunk in reversed(parts) for t in chunk)

    def _drop(self, node: _Node, spill: bool = False) -> None:
        if spill and self.spill is not None \
                and self.mgr.refcount(node.block) == 1:
            # the block's K/V still sit in the device pool until the
            # decref below recycles it — spill reads them out NOW
            self.spill(self.chain_tokens(node), node.block)
        parent = node.parent
        if parent is not None:
            parent.children.pop(node.tokens, None)
        self._nodes.remove(node)
        self.mgr.decref([node.block])
