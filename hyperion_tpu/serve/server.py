"""JSONL serving front-end — `hyperion serve --ckpt ...`.

Two transports over one wire protocol, one JSON object per line:

  * **stdin/stdout** (default): requests read from stdin, token events
    streamed to stdout, clean drain on EOF. Pipes compose — the smoke
    script (`scripts/serve_smoke.sh`) and any shell harness drive the
    full engine without sockets.
  * **local unix socket** (`--socket PATH`): a threaded acceptor;
    each connection submits requests and receives exactly its own
    requests' events back (`serve/client.py` is the matching client).
    Local-only by design: this repo's zero-egress rule means the
    network story stops at the socket file.

Request line:
    {"id": "r1", "prompt": "text", "max_new_tokens": 32,
     "temperature": 0.0, "top_k": 0, "top_p": 1.0, "seed": 0,
     "deadline_s": 5.0, "class": "interactive", "tenant": "team-a"}
`class` is the SLO class (`interactive` default | `batch` — the tier
that absorbs sheds/clamps/preemption first under pressure); `tenant`
is a free-form attribution label `obs doctor` uses to name a hostile
workload.
`prompt_ids` (a raw int list) substitutes for `prompt` when no
tokenizer is loaded. Every response line carries the request id:
    {"id": "r1", "event": "token", "token": 17, "text": "..."}
    {"id": "r1", "event": "done", "n_tokens": 32, "text": "..."}
    {"id": "r1", "event": "rejected"|"timed_out", "reason": "..."}
    {"id": null, "event": "error", "error": "..."}   (unparseable line)

A client cut off mid-stream reconnects and sends the resume verb —
    {"kind": "resume", "request_id": "r1", "next_index": 7,
     "request": {...the original request line...}}
— and receives the REST of the stream (tokens with index >= 7, then
the terminal line) under the original id: seed-deterministic recompute
plus stream-index dedup, the same exactly-once contract the router's
crash failover rides (serve/client.py auto-sends this).

The engine loop always runs on the main thread; transports only
submit into the admission queue (thread-safe) and own their reply
channels via per-request sinks. Telemetry rides the same opt-in
HYPERION_TELEMETRY stream as every other entry point, with `serve`
phase heartbeats so `obs doctor` can tell a hung server from a
drained one.

Crash safety (SERVING.md "Crash recovery and drain"): `--journal`
write-ahead-logs every admission and token so a restart replays
unfinished requests bit-identically; `--supervise` wraps the server in
the shared restart core (journal replay + poison-pill quarantine +
heartbeat hang detection), logging to stderr because stdout IS the
wire; SIGTERM/SIGINT drain gracefully under `--drain-timeout`; and
`--brownout` sheds deadline-doomed queued work / clamps budgets under
overload instead of collapsing.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import threading
import time


def event_record(ev, tok=None) -> dict:
    """TokenEvent -> one wire record."""
    req = ev.request
    if ev.kind == "done":
        # journal recovery found the output already complete: the
        # client is owed only the terminal line the crash swallowed
        return {"id": req.id, "event": "done",
                "n_tokens": len(req.tokens), "recovered": True}
    if ev.kind != "token":
        return {"id": req.id, "event": ev.kind, "reason": ev.reason}
    # `i` is the token's index in the request's stream (the engine
    # appends before the sink runs, so the newest token is the last):
    # the router's failover dedup keys on it — a re-dispatched request
    # recomputes the identical seeded stream and the router forwards
    # only indices the client has not seen
    rec: dict = {"id": req.id, "event": "token", "token": ev.token,
                 "i": len(req.tokens) - 1}
    if tok is not None and ev.token is not None:
        try:
            rec["text"] = tok.decode([ev.token])
        except Exception:  # noqa: BLE001 — a weird id must not kill the stream
            pass
    if ev.finished:
        done: dict = {"id": req.id, "event": "done",
                      "n_tokens": len(req.tokens)}
        if req.first_token_at is not None and req.submitted_at:
            # replica-attributed TTFT: the engine-side share of the
            # client's observed TTFT — loadgen subtracts it to isolate
            # router overhead (fleet tracing, SERVING.md)
            done["ttft_ms"] = round(
                (req.first_token_at - req.submitted_at) * 1000.0, 3)
        if tok is not None:
            eos = getattr(tok, "eos_id", None)
            done["text"] = tok.decode(
                [t for t in req.tokens if t != eos])
        rec = [rec, done]  # token line, then the terminal line
    return rec


def parse_request_line(line: str, tok=None, defaults: dict | None = None):
    """One wire line -> Request, or an error record. Unknown keys are
    ignored (forward compatibility beats strictness on a line
    protocol)."""
    from hyperion_tpu.serve.queue import Request

    defaults = defaults or {}
    try:
        doc = json.loads(line)
    except json.JSONDecodeError as e:
        return {"id": None, "event": "error", "error": f"bad json: {e}"}
    if not isinstance(doc, dict):
        return {"id": None, "event": "error",
                "error": "request line must be a JSON object"}
    if "prompt_ids" in doc:
        ids = doc["prompt_ids"]
    elif "prompt" in doc:
        if tok is None:
            return {"id": doc.get("id"), "event": "error",
                    "error": "text prompt needs a tokenizer "
                             "(--tokenizer-dir); send prompt_ids"}
        ids = tok.encode(str(doc["prompt"]))
    else:
        return {"id": doc.get("id"), "event": "error",
                "error": "request needs 'prompt' or 'prompt_ids'"}
    try:
        return Request(
            prompt_ids=ids,
            id=str(doc.get("id", "")),
            max_new_tokens=int(doc.get("max_new_tokens",
                                       defaults.get("max_new_tokens", 32))),
            temperature=float(doc.get("temperature", 0.0)),
            top_k=int(doc.get("top_k", 0)),
            top_p=float(doc.get("top_p", 1.0)),
            seed=int(doc.get("seed", 0)),
            deadline_s=(float(doc["deadline_s"])
                        if doc.get("deadline_s") is not None else None),
            sla_class=str(doc.get("class", "interactive")),
            tenant=(str(doc["tenant"])
                    if doc.get("tenant") is not None else None),
            # fleet hop context (router-stamped): inherited by every
            # request_* event this request emits, so a cross-process
            # trace can join this replica's phases to the dispatch
            trace=(doc["trace"] if isinstance(doc.get("trace"), dict)
                   else None),
        )
    except (TypeError, ValueError) as e:
        return {"id": doc.get("id"), "event": "error",
                "error": f"bad request field: {e}"}


# ------------------------------------------------------ stream resume
#
# The wire protocol's third verb (after request lines and the implicit
# EOF drain): a client that lost its connection mid-stream reconnects
# and sends
#     {"kind": "resume", "request_id": RID, "next_index": N,
#      "request": {...the original request line...}}
# and gets the rest of RID's stream — tokens with index >= N, then the
# terminal line — under the original id. The answer leans on the same
# two invariants the router's crash failover proved (PR 9): temp-0
# decoding is seed-deterministic (resubmitting the carried request
# recomputes the IDENTICAL token stream, with the radix prefix cache
# making the re-prefill cheap), and stream indices make delivery
# dedupable (the resume sink drops everything below `next_index`).
# The recompute runs under a suffixed wire id so the engine/journal
# never see the same id twice (PR 9's never-go-back journal-hygiene
# rule); the sink rewrites it back before the client sees a byte.

_RESUME_SEQ = itertools.count(1)


def maybe_resume_doc(line: str) -> dict | None:
    """Parse `line` as a resume verb, or None (a plain request)."""
    if '"resume"' not in line:
        return None
    try:
        doc = json.loads(line)
    except json.JSONDecodeError:
        return None
    if isinstance(doc, dict) and doc.get("kind") == "resume":
        return doc
    return None


def resume_sink(writer, tok, rid: str, next_index: int):
    """Sink for a resume recompute: drop already-delivered indices,
    rewrite the suffixed wire id back to the client's."""
    def sink(ev):
        recs = event_record(ev, tok)
        recs = recs if isinstance(recs, list) else [recs]
        out = []
        for r in recs:
            if r.get("event") == "token":
                i = r.get("i")
                if isinstance(i, int) and i < next_index:
                    continue  # the client already holds it
            r = dict(r)
            r["id"] = rid
            out.append(r)
        if out:
            writer.write(out)
    return sink


def submit_resume(engine, doc: dict, writer, tok=None,
                  defaults: dict | None = None):
    """Answer one resume verb: resubmit the carried request under a
    fresh wire id with a dedup-filtering sink. Returns the submitted
    Request (for the transport's half-close bookkeeping) or None when
    the verb was rejected on the spot."""
    rid = str(doc.get("request_id") or "")
    try:
        next_index = max(0, int(doc.get("next_index", 0)))
    except (TypeError, ValueError):
        next_index = 0
    carried = doc.get("request")
    if not rid or not isinstance(carried, dict):
        writer.write({"id": rid or None, "event": "rejected",
                      "reason": "unknown_request"})
        return None
    carried = dict(carried)
    carried["id"] = f"{rid}~r{next(_RESUME_SEQ)}"
    parsed = parse_request_line(
        json.dumps(carried, separators=(",", ":")), tok, defaults)
    if isinstance(parsed, dict):  # error record
        parsed["id"] = rid
        engine.reject_unparsed(rid, parsed.get("error") or "")
        writer.write(parsed)
        return None
    parsed.sink = resume_sink(writer, tok, rid, next_index)
    engine.tracer.event("stream_resume", request=rid,
                        wire_id=parsed.id, next_index=next_index)
    engine.submit(parsed)
    return parsed


class _LineWriter:
    """Locked JSONL writer — transports interleave whole lines, never
    partial ones. Accepts text or binary files (socket wfile is
    binary)."""

    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()

    def write(self, rec) -> None:
        recs = rec if isinstance(rec, list) else [rec]
        with self._lock:
            for r in recs:
                line = json.dumps(r, separators=(",", ":")) + "\n"
                try:
                    self._f.write(line)
                except TypeError:
                    self._f.write(line.encode("utf-8"))
            self._f.flush()


def serve_jsonl(engine, infile, outfile, tok=None,
                defaults: dict | None = None,
                drain=None, drain_timeout_s: float = 30.0,
                hard_stop=None) -> dict:
    """stdin/stdout (or any file-pair) mode: a reader thread feeds the
    queue; the engine loop drains on EOF. `drain` (a threading.Event)
    is the graceful-shutdown signal — SIGTERM/SIGINT set it in `main`
    — flipping the engine to draining (queue closed, in-flight work
    finishes under `drain_timeout_s`); `hard_stop` aborts immediately
    (second signal). Returns the engine summary."""
    out = _LineWriter(outfile)
    eof = threading.Event()

    def sink(ev):
        out.write(event_record(ev, tok))

    # journal recovery first: requests a previous life owed resume at
    # the head of the queue, streaming to the same stdout the crashed
    # process was using (the supervisor shares the pipe across
    # restarts, so the client sees one continuous stream)
    engine.replay_pending(sink)

    def reader():
        try:
            for line in infile:
                try:
                    line = line.strip()
                    if not line:
                        continue
                    if (rdoc := maybe_resume_doc(line)) is not None:
                        submit_resume(engine, rdoc, out, tok, defaults)
                        continue
                    parsed = parse_request_line(line, tok, defaults)
                    if isinstance(parsed, dict):  # error record
                        engine.reject_unparsed(parsed.get("id"),
                                               parsed.get("error") or "")
                        out.write(parsed)
                        continue
                    parsed.sink = sink
                    engine.submit(parsed)
                except Exception as e:  # noqa: BLE001
                    # nothing a client sends (or a dead stdout raises
                    # back) may kill the reader — and certainly never
                    # the engine thread, which this loop never touches
                    engine.reject_unparsed(None, repr(e))
        finally:
            eof.set()

    def should_stop():
        if drain is not None and drain.is_set():
            engine.begin_drain(drain_timeout_s)  # idempotent
        return hard_stop is not None and hard_stop.is_set()

    t = threading.Thread(target=reader, name="serve-stdin", daemon=True)
    t.start()
    summary = engine.run(should_stop=should_stop, drain_when=eof.is_set)
    t.join(timeout=5)
    return summary


def prepare_socket_path(socket_path: str, bind=None):
    """Make `socket_path` bindable: a socket file that survived a
    crash (SIGKILL unlinks nothing) would fail the bind forever — the
    exact restart loop the serve supervisor runs. Probe it first: a
    connection REFUSED means no listener owns it (stale — unlink); a
    successful connect means a live server does (refuse loudly instead
    of yanking a working deployment's socket out from under it). The
    probe discipline itself lives in obs/export.py (jax-free, shared
    with the exposition sockets, flock-serialized against sibling
    restarts) — this is the serve-transport entry point. Pass the bind
    as `bind() -> server` so it happens inside the lock; returns the
    bound server."""
    from hyperion_tpu.obs.export import (
        prepare_socket_path as _prepare,
    )

    return _prepare(socket_path, owner="live server", bind=bind)


def serve_socket(engine, socket_path: str, tok=None,
                 defaults: dict | None = None,
                 should_stop=None, ready=None,
                 drain=None, drain_timeout_s: float = 30.0,
                 hard_stop=None) -> dict:
    """Unix-socket mode: threaded acceptor submits, engine loop (this
    thread) decodes. Each connection gets exactly its own requests'
    events. `ready` (an optional threading.Event) is set once the
    socket is listening — tests wait on it instead of polling. `drain`
    flips graceful shutdown like the stdin transport; journal-replayed
    requests have no surviving connection, so their continuations run
    sink-less (the journal still records them — a reconnecting client
    re-submits and hits the radix cache)."""
    import os
    import socketserver

    engine.replay_pending(None)

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            writer = _LineWriter(self.wfile)
            pending: list = []
            for raw in self.rfile:
                try:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line:
                        continue
                    if (rdoc := maybe_resume_doc(line)) is not None:
                        resumed = submit_resume(engine, rdoc, writer,
                                                tok, defaults)
                        if resumed is not None:
                            pending.append(resumed)
                        continue
                    parsed = parse_request_line(line, tok, defaults)
                    if isinstance(parsed, dict):
                        engine.reject_unparsed(parsed.get("id"),
                                               parsed.get("error") or "")
                        writer.write(parsed)
                        continue
                    parsed.sink = lambda ev: writer.write(
                        event_record(ev, tok))
                    pending.append(parsed)
                    engine.submit(parsed)
                except Exception as e:  # noqa: BLE001 — a hostile or
                    # half-dead connection is its own problem, never
                    # the engine's
                    engine.reject_unparsed(None, repr(e))
                    break
            for req in pending:  # connection half-closed: finish streams
                req.done.wait(timeout=600)

    class Server(socketserver.ThreadingMixIn,
                 socketserver.UnixStreamServer):
        daemon_threads = True
        allow_reuse_address = True

        def handle_error(self, request, client_address):
            # a client that died mid-handshake/stream: evidence, not a
            # stack trace on stderr and never a server death
            engine.tracer.event("client_error",
                                client=str(client_address))

    srv = prepare_socket_path(
        socket_path, bind=lambda: Server(socket_path, Handler))
    acceptor = threading.Thread(target=srv.serve_forever,
                                name="serve-accept", daemon=True)
    acceptor.start()
    if ready is not None:
        ready.set()

    def _stop():
        if drain is not None and drain.is_set():
            engine.begin_drain(drain_timeout_s)  # idempotent
        if hard_stop is not None and hard_stop.is_set():
            return True  # second signal: stop now, journal holds the rest
        return bool(should_stop and should_stop())

    try:
        summary = engine.run(
            should_stop=_stop,
            # a socket server idles between connections; only an
            # explicit stop (or the drain signal) drains it
            drain_when=lambda: bool(should_stop and should_stop()),
        )
    finally:
        srv.shutdown()
        srv.server_close()
        try:
            os.unlink(socket_path)
        except OSError:
            pass
    return summary


# ---------------------------------------------------------------- CLI


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hyperion serve",
        description="continuous-batching inference server over a "
                    "gathered Llama export (stdin/JSONL by default, "
                    "--socket for a local unix socket)",
    )
    p.add_argument("--ckpt", required=True,
                   help="gathered-export .npz (written by the trainers)")
    p.add_argument("--tokenizer-dir", default="data/tokenizer")
    p.add_argument("--no-tokenizer", action="store_true",
                   help="serve raw prompt_ids only (no text encode/"
                        "decode; eos disabled unless --eos-id)")
    p.add_argument("--max-len", type=int, default=256,
                   help="per-slot KV-cache length: prompt + "
                        "max_new_tokens must fit (also the admission "
                        "bound)")
    p.add_argument("--slots", type=int, default=4,
                   help="concurrent requests decoded per tick (the "
                        "static batch dimension)")
    p.add_argument("--block-size", type=int, default=16,
                   help="tokens per KV-cache block (serve/blocks.py): "
                        "smaller = finer memory granularity and more "
                        "prefix-sharing opportunities, larger = smaller "
                        "block tables; need not divide max_len (the "
                        "table rounds up to whole blocks)")
    p.add_argument("--paged-attn", choices=("gather", "pallas"),
                   default="gather",
                   help="paged-cache read strategy: 'gather' copies "
                        "each slot's whole block chain into a "
                        "contiguous view every tick; 'pallas' walks "
                        "the block table in-kernel and reads the KV "
                        "pools in place (ops/pallas/paged_attention, "
                        "interpret-mode off-TPU). Streams stay "
                        "deterministic; memory ledger shows the saved "
                        "copy as kv_gather_bytes_per_tick=0")
    p.add_argument("--num-blocks", type=int, default=0,
                   help="KV pool size in blocks incl. the null block "
                        "(0 = auto: slots x ceil(max_len/block_size) + 1, "
                        "the static-slab equivalent); smaller values "
                        "oversubscribe HBM and lean on prefix sharing + "
                        "preemption")
    p.add_argument("--prefix-cache", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="radix prefix reuse: prompts sharing a prefix "
                        "with an earlier request skip its prefill and "
                        "share the cached blocks (--no-prefix-cache to "
                        "disable)")
    p.add_argument("--host-cache-mb", type=int, default=0,
                   help="tiered KV (serve/hostcache.py): host-RAM spill "
                        "tier for the radix cache in MB (0 = off). "
                        "Evicted prefix chains demote to host buffers "
                        "under this LRU budget and restore with one H2D "
                        "copy per block on a rehit instead of a "
                        "re-prefill; the store serializes next to the "
                        "journal on drain, so spilled chains survive a "
                        "restart. Needs --prefix-cache")
    p.add_argument("--queue-capacity", type=int, default=64,
                   help="admission queue bound; beyond it requests are "
                        "rejected with reason queue_full")
    p.add_argument("--prefill-budget", type=int, default=512,
                   help="prompt tokens admitted per scheduling round — "
                        "caps how long one giant prompt can stall "
                        "in-flight decode ticks")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="chunked prefill: prompts longer than this are "
                        "split into fixed-size chunks interleaved with "
                        "decode ticks, so one giant prompt can't spike "
                        "co-running slots' TTFT (0 = off). One static "
                        "chunk shape = exactly one extra executable; "
                        "temp-0 output stays bit-identical")
    p.add_argument("--max-new-default", type=int, default=32,
                   help="max_new_tokens when a request omits it")
    # ---- SLO classes (serve/queue.py) ----
    p.add_argument("--interactive-weight", type=int, default=3,
                   help="weighted-fair admission: interactive slots per "
                        "round-robin cycle (vs --batch-weight)")
    p.add_argument("--batch-weight", type=int, default=1,
                   help="weighted-fair admission: batch slots per "
                        "round-robin cycle")
    p.add_argument("--batch-capacity", type=int, default=0,
                   help="separate queue bound for class=batch requests "
                        "(0 = share --queue-capacity); a batch flood "
                        "then rejects batch, never interactive")
    p.add_argument("--batch-deadline-s", type=float, default=0.0,
                   help="default admission deadline for class=batch "
                        "requests that omit deadline_s (0 = none)")
    # ---- speculative decoding (serve/draft.py) ----
    p.add_argument("--spec-k", type=int, default=0,
                   help="speculative decoding: draft tokens verified "
                        "per slot per tick (0 = off). Each tick then "
                        "emits 1..k+1 tokens per slot for ONE target "
                        "forward; temp-0 output is bit-identical to "
                        "sequential decode, so this is pure speed. "
                        "Needs --draft; lower it (or disable) if "
                        "`obs doctor` reports draft misprediction")
    p.add_argument("--draft", choices=("ngram", "off"), default="off",
                   help="draft source for --spec-k: 'ngram' = "
                        "self-drafting suffix lookup over each slot's "
                        "prompt + generated tokens (no second "
                        "checkpoint); 'off' disables speculation")
    p.add_argument("--eos-id", type=int, default=None,
                   help="override the eos token id (default: the "
                        "tokenizer's)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="listen on a local unix socket instead of "
                        "stdin/stdout")
    p.add_argument("--warmup-lens", default="8,32",
                   help="comma-separated prompt lengths to pre-compile "
                        "prefill buckets for (the tick always warms)")
    p.add_argument("--heartbeat-every", type=int, default=25,
                   help="serve-phase heartbeat cadence in ticks (see "
                        "`obs doctor`)")
    p.add_argument("--chaos", default="",
                   help="deterministic fault plan (testing/chaos.py): "
                        "stall@tick=N:SECS, slow_client@tick=N:SECS, "
                        "kill@tick=N, crash@tick=N, journal_io_fail@p=X, "
                        "poison_request@id=ID, ... — serve-loop drills "
                        "(tick faults fire once per supervisor lineage)")
    # ---- crash safety: journal + supervised restarts + drain ----
    p.add_argument("--journal", default="", metavar="PATH",
                   help="append-only request journal (JSONL WAL): every "
                        "admission and emitted token is recorded so a "
                        "crashed engine's restart REPLAYS unfinished "
                        "requests to bit-identical completion "
                        "(serve/journal.py); --supervise defaults this "
                        "to data/serve_journal.jsonl")
    p.add_argument("--supervise", action="store_true",
                   help="run the server as a supervised subprocess: on "
                        "a crash, consult `obs doctor`, restart with "
                        "backoff, and replay the request journal; a "
                        "request that crashes the engine repeatedly is "
                        "quarantined (request_poisoned) instead of "
                        "crash-looping")
    p.add_argument("--max-restarts", type=int, default=2,
                   help="--supervise: restarts before giving up with "
                        "exit 3")
    p.add_argument("--hang-timeout", type=float, default=120.0,
                   help="--supervise: SIGKILL a child whose heartbeat "
                        "goes stale this many seconds (0 = off; needs "
                        "telemetry for the heartbeat file)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="SIGTERM/SIGINT: seconds granted to in-flight "
                        "and already-queued requests before a hard "
                        "stop; new submissions reject with reason "
                        "'draining' immediately (a second signal stops "
                        "now). A fully drained journal is marked clean "
                        "— the next start replays nothing")
    # ---- overload brownout ----
    p.add_argument("--brownout", action="store_true",
                   help="degrade gracefully under overload: when queue "
                        "depth or queue-wait p95 crosses its watermark, "
                        "shed queued requests whose deadline is already "
                        "unmeetable (reject reason 'shed_deadline') and "
                        "optionally clamp max_new_tokens for new "
                        "admissions; exits with hysteresis at half the "
                        "watermark so it never flaps")
    p.add_argument("--brownout-depth", type=int, default=0,
                   help="queue-depth enter watermark (0 = 3/4 of "
                        "--queue-capacity); exit at half of it")
    p.add_argument("--brownout-wait-s", type=float, default=0.0,
                   help="queue-wait p95 enter watermark in seconds "
                        "(0 = depth watermark only)")
    p.add_argument("--brownout-clamp", type=int, default=0,
                   help="while browned out, clamp each new admission's "
                        "max_new_tokens to this (0 = shed only); "
                        "recorded on the journal so replays honor it")
    # ---- SLO burn-rate alerting (obs/slo.py) ----
    p.add_argument("--slo-ttft-p99-ms", type=float, default=0.0,
                   help="SLO target: windowed TTFT p99 must stay under "
                        "this many ms (0 = target off). Breaching it in "
                        "BOTH burn windows raises an `alert_raised` "
                        "event + an `alerts` heartbeat field; clearing "
                        "needs both windows back under 90%% of target")
    p.add_argument("--slo-reject-rate", type=float, default=0.0,
                   help="SLO target: windowed rejected/(accepted+"
                        "rejected) budget (e.g. 0.05; 0 = off)")
    p.add_argument("--slo-availability", type=float, default=0.0,
                   help="SLO target: windowed completed/(completed+"
                        "rejected+timed_out) floor (e.g. 0.99; 0 = off)")
    p.add_argument("--slo-fast-s", type=float, default=0.0,
                   help="fast burn window in seconds (0 = 60): 'is it "
                        "bad right now'")
    p.add_argument("--slo-slow-s", type=float, default=0.0,
                   help="slow burn window in seconds (0 = 600): 'has "
                        "it been bad long enough to matter' — also the "
                        "alert's clearing memory")
    return p


DEFAULT_JOURNAL = "data/serve_journal.jsonl"


def _strip_supervise_flags(argv: list[str]) -> list[str]:
    from hyperion_tpu.supervisor import strip_flags

    return strip_flags(argv, {"--supervise"},
                       {"--max-restarts", "--hang-timeout"})


def _env_telemetry_path() -> str | None:
    """The stream path the CHILD's `from_env` will resolve — computed
    jax-free so the supervisor parent can find the heartbeat file and
    the doctor's run dir without importing the serving stack."""
    import os

    val = os.environ.get("HYPERION_TELEMETRY", "")
    if val in ("", "0"):
        return None
    return "data/telemetry.jsonl" if val == "1" else val


def supervise_serve(argv: list[str], args) -> int:
    """`hyperion serve --supervise`: the crash loop around the serving
    child — the shared supervisor core (hyperion_tpu/supervisor.py)
    with the serve policy: any crash restarts with backoff (the child
    replays its request journal on the way up), a heartbeat gone stale
    past --hang-timeout gets the child SIGKILLed (a wedged engine never
    exits by itself), and `obs doctor` is consulted for the verdict the
    operator reads. The parent never touches jax — it must stay alive
    when the child is wedged inside a dead backend."""
    from pathlib import Path

    from hyperion_tpu.supervisor import (
        Decision,
        heartbeat_watchdog,
        run_child,
        supervise_loop,
    )

    def log(msg: str) -> None:
        # stderr, always: the children's stdout is the client's JSONL
        # wire stream and must never carry supervisor chatter
        print(msg, file=sys.stderr, flush=True)

    tele = _env_telemetry_path()
    hb_path = str(Path(tele).parent / "heartbeat.json") if tele else None
    runner = run_child
    if args.hang_timeout > 0 and hb_path:
        runner = heartbeat_watchdog(hb_path, args.hang_timeout, log=log)

    def decide(rc: int) -> Decision:
        verdict = None
        if tele is not None:
            try:
                from hyperion_tpu.obs.doctor import diagnose

                # the stream file itself, not its directory: the env
                # var may name anything, not just telemetry.jsonl
                verdict = diagnose(tele).get("verdict")
            except Exception as e:  # noqa: BLE001 — triage is advisory
                log(f"[serve-supervisor] doctor consult failed: {e}")
        log(f"[serve-supervisor] child exit {rc}; doctor verdict: "
            f"{verdict or 'unavailable'}; restarting with journal "
            "replay")
        return Decision.restart()

    child_argv = _strip_supervise_flags(argv)
    if "--journal" not in " ".join(child_argv):
        # replay is the whole point of a supervised restart: default
        # the WAL on and pin the path so every child shares it
        child_argv += ["--journal", args.journal or DEFAULT_JOURNAL]
    child = [sys.executable, "-m", "hyperion_tpu.cli.main", "serve",
             *child_argv]
    return supervise_loop(child, decide=decide,
                          max_restarts=args.max_restarts,
                          run_child=runner, label="serve-supervisor",
                          log=log)


def main(argv=None) -> int:
    import os
    import signal

    argv = sys.argv[1:] if argv is None else list(argv)
    args = build_parser().parse_args(argv)
    if args.supervise:
        return supervise_serve(argv, args)

    from hyperion_tpu.checkpoint.io import load_gathered
    from hyperion_tpu.infer.generate import model_from_npz
    from hyperion_tpu.obs import heartbeat as obs_heartbeat
    from hyperion_tpu.obs import trace as obs_trace
    from hyperion_tpu.serve.engine import Engine, EngineConfig
    from hyperion_tpu.serve.journal import RequestJournal

    tok = None
    if not args.no_tokenizer:
        from hyperion_tpu.data.bpe import ByteBPE

        tok = ByteBPE.load(args.tokenizer_dir)

    attempt = int(os.environ.get("HYPERION_ATTEMPT", "0") or 0)
    # under a router, each replica stamps its index onto every record
    # (the tracer's proc field) and heartbeat — the fleet doctor and
    # the timeline's replica tags read it back
    replica = os.environ.get("HYPERION_REPLICA", "")
    replica_idx = int(replica) if replica.isdigit() else None
    run_tag = f"serve_r{replica_idx}" if replica_idx is not None \
        else "serve"
    tracer = obs_trace.from_env(
        "data/telemetry.jsonl", run=f"{run_tag}_{int(time.time())}",
        proc=replica_idx)
    hb = obs_heartbeat.Heartbeat.for_tracer(
        tracer, every=args.heartbeat_every,
        static=({"attempt": attempt, "replica": replica_idx}
                if replica_idx is not None else {"attempt": attempt}))
    hb.pulse(phase="load")
    journal = None
    chaos = None
    if args.chaos:
        from hyperion_tpu.testing import chaos as chaos_mod
        from pathlib import Path

        # state file next to the journal (or the stream): tick faults
        # fire once per supervisor LINEAGE, so a restarted child does
        # not re-die at the already-fired tick — the same contract the
        # trainer drills rely on
        state_dir = None
        if args.journal:
            state_dir = Path(args.journal).parent
        elif _env_telemetry_path():
            state_dir = Path(_env_telemetry_path()).parent
        chaos = chaos_mod.activate(
            args.chaos,
            state_path=(state_dir / "serve_chaos_state.json"
                        if state_dir is not None else None))
    if args.journal:
        journal = RequestJournal(
            args.journal,
            fault=chaos.journal_io if chaos is not None else None)

    with tracer.span("load") as ld:
        params = load_gathered(args.ckpt)
        model, cached = model_from_npz(params, args.max_len)
        ld.set(ckpt=args.ckpt, cached=cached)
    if not cached:
        print("hyperion serve needs a Llama (KV-cache) export — "
              "TransformerLM/MoE recompute decode has no slot cache "
              "to batch over", file=sys.stderr)
        tracer.close()
        return 2

    if args.paged_attn != "gather":
        # same architecture + params, different paged-read strategy —
        # a config-only swap, so every engine jit keeps its signature
        import dataclasses as _dc

        from hyperion_tpu.models.llama import Llama

        model = Llama(_dc.replace(model.cfg, paged_attn_impl=args.paged_attn))

    eos_id = args.eos_id
    if eos_id is None and tok is not None:
        eos_id = tok.eos_id
    # the host tier's persistence dir rides the journal's recovery
    # path: next to the WAL when one exists, next to the telemetry
    # stream otherwise, nowhere (in-memory tier only) when neither
    host_cache_dir = ""
    if args.host_cache_mb > 0:
        from pathlib import Path as _Path

        if args.journal:
            host_cache_dir = str(_Path(args.journal).parent / "hostcache")
        elif _env_telemetry_path():
            host_cache_dir = str(
                _Path(_env_telemetry_path()).parent / "hostcache")
    engine = Engine(
        model, {"params": params},
        EngineConfig(
            slots=args.slots, max_len=args.max_len, eos_id=eos_id,
            queue_capacity=args.queue_capacity,
            prefill_budget=args.prefill_budget,
            prefill_chunk=args.prefill_chunk,
            interactive_weight=args.interactive_weight,
            batch_weight=args.batch_weight,
            batch_capacity=args.batch_capacity,
            batch_deadline_s=args.batch_deadline_s,
            block_size=args.block_size, num_blocks=args.num_blocks,
            prefix_cache=args.prefix_cache,
            host_cache_mb=args.host_cache_mb,
            host_cache_dir=host_cache_dir,
            spec_k=args.spec_k, draft=args.draft,
            brownout=args.brownout,
            brownout_depth=args.brownout_depth,
            brownout_wait_s=args.brownout_wait_s,
            brownout_clamp=args.brownout_clamp,
            slo_ttft_p99_ms=args.slo_ttft_p99_ms,
            slo_reject_rate=args.slo_reject_rate,
            slo_availability=args.slo_availability,
            slo_fast_s=args.slo_fast_s,
            slo_slow_s=args.slo_slow_s,
        ),
        tracer=tracer, heartbeat=hb, chaos=chaos, journal=journal,
        flight_path=(hb.path.parent / "flight.json" if hb.enabled
                     else None),
    )
    hb.pulse(phase="warmup")
    warm = [int(x) for x in args.warmup_lens.split(",") if x.strip()]
    engine.warmup(warm or None)

    # live exposition socket (obs/export.py): obs.sock next to the
    # heartbeat file, answering one JSON snapshot per connection off
    # the metrics the engine already keeps — `obs top` polls it. Rides
    # the heartbeat's enablement: no telemetry, no live plane.
    exporter = None
    if hb.enabled:
        from hyperion_tpu.obs.export import (
            MetricsExporter,
            exposition_path,
        )

        exporter = MetricsExporter(exposition_path(hb.path),
                                   engine.exposition,
                                   label="serve-obs",
                                   control_fn=engine.control).start()

    # graceful drain: first SIGTERM/SIGINT closes the queue and lets
    # in-flight work finish under --drain-timeout; a second one stops
    # hard (unfinished work stays journaled for the next life)
    drain_evt = threading.Event()
    hard_evt = threading.Event()

    def _on_signal(signum, frame):
        if drain_evt.is_set():
            hard_evt.set()
        else:
            print(f"[serve] signal {signum}: draining (timeout "
                  f"{args.drain_timeout:.0f}s; signal again to stop "
                  "now)", file=sys.stderr)
            # spill the flight record NOW: if the drain never finishes
            # (hard stop, wedged device) the post-mortem still has the
            # final ticks. Host-only dict/file work — signal-safe
            # enough for a post-mortem artifact.
            try:
                engine.flight_spill("sigterm", signum=int(signum))
            except Exception:  # noqa: BLE001 — never die in a handler
                pass
        drain_evt.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not the main thread (embedded use): no signal drain

    defaults = {"max_new_tokens": args.max_new_default}
    try:
        if args.socket:
            print(f"[serve] listening on {args.socket} "
                  f"({args.slots} slots, max_len {args.max_len})",
                  file=sys.stderr)
            serve_socket(engine, args.socket, tok, defaults,
                         drain=drain_evt,
                         drain_timeout_s=args.drain_timeout,
                         hard_stop=hard_evt)
        else:
            serve_jsonl(engine, sys.stdin, sys.stdout, tok, defaults,
                        drain=drain_evt,
                        drain_timeout_s=args.drain_timeout,
                        hard_stop=hard_evt)
    except KeyboardInterrupt:
        pass
    finally:
        if exporter is not None:
            exporter.close()
        if journal is not None:
            if engine.idle:
                # fully drained: mark the WAL clean so the next start
                # replays nothing — the drain-exits-0 contract
                journal.close_clean()
            else:
                journal.close()
                print(f"[serve] {len(engine.queue) + engine.n_active} "
                      "request(s) still owed — journaled for replay at "
                      "the next start", file=sys.stderr)
        tracer.close()
        if tracer.enabled:
            # every request's lifecycle (queue/gate/prefill/decode/
            # client-write, with client-write timed around the sink
            # calls this process just made) is on the stream — point at
            # the consumer instead of making the operator remember it
            print(f"[serve] request traces at {tracer.path} — inspect "
                  f"with `python -m hyperion_tpu.cli.main obs trace "
                  f"{tracer.path}`",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
