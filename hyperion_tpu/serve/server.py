"""JSONL serving front-end — `hyperion serve --ckpt ...`.

Two transports over one wire protocol, one JSON object per line:

  * **stdin/stdout** (default): requests read from stdin, token events
    streamed to stdout, clean drain on EOF. Pipes compose — the smoke
    script (`scripts/serve_smoke.sh`) and any shell harness drive the
    full engine without sockets.
  * **local unix socket** (`--socket PATH`): a threaded acceptor;
    each connection submits requests and receives exactly its own
    requests' events back (`serve/client.py` is the matching client).
    Local-only by design: this repo's zero-egress rule means the
    network story stops at the socket file.

Request line:
    {"id": "r1", "prompt": "text", "max_new_tokens": 32,
     "temperature": 0.0, "top_k": 0, "top_p": 1.0, "seed": 0,
     "deadline_s": 5.0}
`prompt_ids` (a raw int list) substitutes for `prompt` when no
tokenizer is loaded. Every response line carries the request id:
    {"id": "r1", "event": "token", "token": 17, "text": "..."}
    {"id": "r1", "event": "done", "n_tokens": 32, "text": "..."}
    {"id": "r1", "event": "rejected"|"timed_out", "reason": "..."}
    {"id": null, "event": "error", "error": "..."}   (unparseable line)

The engine loop always runs on the main thread; transports only
submit into the admission queue (thread-safe) and own their reply
channels via per-request sinks. Telemetry rides the same opt-in
HYPERION_TELEMETRY stream as every other entry point, with `serve`
phase heartbeats so `obs doctor` can tell a hung server from a
drained one.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def event_record(ev, tok=None) -> dict:
    """TokenEvent -> one wire record."""
    req = ev.request
    if ev.kind != "token":
        return {"id": req.id, "event": ev.kind, "reason": ev.reason}
    rec: dict = {"id": req.id, "event": "token", "token": ev.token}
    if tok is not None and ev.token is not None:
        try:
            rec["text"] = tok.decode([ev.token])
        except Exception:  # noqa: BLE001 — a weird id must not kill the stream
            pass
    if ev.finished:
        done: dict = {"id": req.id, "event": "done",
                      "n_tokens": len(req.tokens)}
        if tok is not None:
            eos = getattr(tok, "eos_id", None)
            done["text"] = tok.decode(
                [t for t in req.tokens if t != eos])
        rec = [rec, done]  # token line, then the terminal line
    return rec


def parse_request_line(line: str, tok=None, defaults: dict | None = None):
    """One wire line -> Request, or an error record. Unknown keys are
    ignored (forward compatibility beats strictness on a line
    protocol)."""
    from hyperion_tpu.serve.queue import Request

    defaults = defaults or {}
    try:
        doc = json.loads(line)
    except json.JSONDecodeError as e:
        return {"id": None, "event": "error", "error": f"bad json: {e}"}
    if not isinstance(doc, dict):
        return {"id": None, "event": "error",
                "error": "request line must be a JSON object"}
    if "prompt_ids" in doc:
        ids = doc["prompt_ids"]
    elif "prompt" in doc:
        if tok is None:
            return {"id": doc.get("id"), "event": "error",
                    "error": "text prompt needs a tokenizer "
                             "(--tokenizer-dir); send prompt_ids"}
        ids = tok.encode(str(doc["prompt"]))
    else:
        return {"id": doc.get("id"), "event": "error",
                "error": "request needs 'prompt' or 'prompt_ids'"}
    try:
        return Request(
            prompt_ids=ids,
            id=str(doc.get("id", "")),
            max_new_tokens=int(doc.get("max_new_tokens",
                                       defaults.get("max_new_tokens", 32))),
            temperature=float(doc.get("temperature", 0.0)),
            top_k=int(doc.get("top_k", 0)),
            top_p=float(doc.get("top_p", 1.0)),
            seed=int(doc.get("seed", 0)),
            deadline_s=(float(doc["deadline_s"])
                        if doc.get("deadline_s") is not None else None),
        )
    except (TypeError, ValueError) as e:
        return {"id": doc.get("id"), "event": "error",
                "error": f"bad request field: {e}"}


class _LineWriter:
    """Locked JSONL writer — transports interleave whole lines, never
    partial ones. Accepts text or binary files (socket wfile is
    binary)."""

    def __init__(self, f):
        self._f = f
        self._lock = threading.Lock()

    def write(self, rec) -> None:
        recs = rec if isinstance(rec, list) else [rec]
        with self._lock:
            for r in recs:
                line = json.dumps(r, separators=(",", ":")) + "\n"
                try:
                    self._f.write(line)
                except TypeError:
                    self._f.write(line.encode("utf-8"))
            self._f.flush()


def serve_jsonl(engine, infile, outfile, tok=None,
                defaults: dict | None = None) -> dict:
    """stdin/stdout (or any file-pair) mode: a reader thread feeds the
    queue; the engine loop drains on EOF. Returns the engine summary."""
    out = _LineWriter(outfile)
    eof = threading.Event()

    def sink(ev):
        out.write(event_record(ev, tok))

    def reader():
        try:
            for line in infile:
                line = line.strip()
                if not line:
                    continue
                parsed = parse_request_line(line, tok, defaults)
                if isinstance(parsed, dict):  # error record
                    out.write(parsed)
                    continue
                parsed.sink = sink
                engine.submit(parsed)
        finally:
            eof.set()

    t = threading.Thread(target=reader, name="serve-stdin", daemon=True)
    t.start()
    summary = engine.run(drain_when=eof.is_set)
    t.join(timeout=5)
    return summary


def serve_socket(engine, socket_path: str, tok=None,
                 defaults: dict | None = None,
                 should_stop=None, ready=None) -> dict:
    """Unix-socket mode: threaded acceptor submits, engine loop (this
    thread) decodes. Each connection gets exactly its own requests'
    events. `ready` (an optional threading.Event) is set once the
    socket is listening — tests wait on it instead of polling."""
    import os
    import socketserver

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            writer = _LineWriter(self.wfile)
            pending: list = []
            for raw in self.rfile:
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    continue
                parsed = parse_request_line(line, tok, defaults)
                if isinstance(parsed, dict):
                    writer.write(parsed)
                    continue
                parsed.sink = lambda ev: writer.write(event_record(ev, tok))
                pending.append(parsed)
                engine.submit(parsed)
            for req in pending:  # connection half-closed: finish streams
                req.done.wait(timeout=600)

    class Server(socketserver.ThreadingMixIn,
                 socketserver.UnixStreamServer):
        daemon_threads = True
        allow_reuse_address = True

    try:
        os.unlink(socket_path)
    except OSError:
        pass
    srv = Server(socket_path, Handler)
    acceptor = threading.Thread(target=srv.serve_forever,
                                name="serve-accept", daemon=True)
    acceptor.start()
    if ready is not None:
        ready.set()
    try:
        summary = engine.run(
            should_stop=should_stop,
            # a socket server idles between connections; only an
            # explicit stop drains it
            drain_when=(should_stop or (lambda: False)),
        )
    finally:
        srv.shutdown()
        srv.server_close()
        try:
            os.unlink(socket_path)
        except OSError:
            pass
    return summary


# ---------------------------------------------------------------- CLI


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hyperion serve",
        description="continuous-batching inference server over a "
                    "gathered Llama export (stdin/JSONL by default, "
                    "--socket for a local unix socket)",
    )
    p.add_argument("--ckpt", required=True,
                   help="gathered-export .npz (written by the trainers)")
    p.add_argument("--tokenizer-dir", default="data/tokenizer")
    p.add_argument("--no-tokenizer", action="store_true",
                   help="serve raw prompt_ids only (no text encode/"
                        "decode; eos disabled unless --eos-id)")
    p.add_argument("--max-len", type=int, default=256,
                   help="per-slot KV-cache length: prompt + "
                        "max_new_tokens must fit (also the admission "
                        "bound)")
    p.add_argument("--slots", type=int, default=4,
                   help="concurrent requests decoded per tick (the "
                        "static batch dimension)")
    p.add_argument("--block-size", type=int, default=16,
                   help="tokens per KV-cache block (serve/blocks.py): "
                        "smaller = finer memory granularity and more "
                        "prefix-sharing opportunities, larger = smaller "
                        "block tables; need not divide max_len (the "
                        "table rounds up to whole blocks)")
    p.add_argument("--num-blocks", type=int, default=0,
                   help="KV pool size in blocks incl. the null block "
                        "(0 = auto: slots x ceil(max_len/block_size) + 1, "
                        "the static-slab equivalent); smaller values "
                        "oversubscribe HBM and lean on prefix sharing + "
                        "preemption")
    p.add_argument("--prefix-cache", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="radix prefix reuse: prompts sharing a prefix "
                        "with an earlier request skip its prefill and "
                        "share the cached blocks (--no-prefix-cache to "
                        "disable)")
    p.add_argument("--queue-capacity", type=int, default=64,
                   help="admission queue bound; beyond it requests are "
                        "rejected with reason queue_full")
    p.add_argument("--prefill-budget", type=int, default=512,
                   help="prompt tokens admitted per scheduling round — "
                        "caps how long one giant prompt can stall "
                        "in-flight decode ticks")
    p.add_argument("--max-new-default", type=int, default=32,
                   help="max_new_tokens when a request omits it")
    p.add_argument("--eos-id", type=int, default=None,
                   help="override the eos token id (default: the "
                        "tokenizer's)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="listen on a local unix socket instead of "
                        "stdin/stdout")
    p.add_argument("--warmup-lens", default="8,32",
                   help="comma-separated prompt lengths to pre-compile "
                        "prefill buckets for (the tick always warms)")
    p.add_argument("--heartbeat-every", type=int, default=25,
                   help="serve-phase heartbeat cadence in ticks (see "
                        "`obs doctor`)")
    p.add_argument("--chaos", default="",
                   help="deterministic fault plan (testing/chaos.py): "
                        "stall@tick=N:SECS, slow_client@tick=N:SECS, "
                        "kill@tick=N, ... — serve-loop drills")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from hyperion_tpu.checkpoint.io import load_gathered
    from hyperion_tpu.infer.generate import model_from_npz
    from hyperion_tpu.obs import heartbeat as obs_heartbeat
    from hyperion_tpu.obs import trace as obs_trace
    from hyperion_tpu.serve.engine import Engine, EngineConfig

    tok = None
    if not args.no_tokenizer:
        from hyperion_tpu.data.bpe import ByteBPE

        tok = ByteBPE.load(args.tokenizer_dir)

    tracer = obs_trace.from_env(
        "data/telemetry.jsonl", run=f"serve_{int(time.time())}")
    hb = obs_heartbeat.Heartbeat.for_tracer(tracer,
                                            every=args.heartbeat_every)
    hb.pulse(phase="load")
    chaos = None
    if args.chaos:
        from hyperion_tpu.testing import chaos as chaos_mod

        chaos = chaos_mod.activate(args.chaos)

    with tracer.span("load") as ld:
        params = load_gathered(args.ckpt)
        model, cached = model_from_npz(params, args.max_len)
        ld.set(ckpt=args.ckpt, cached=cached)
    if not cached:
        print("hyperion serve needs a Llama (KV-cache) export — "
              "TransformerLM/MoE recompute decode has no slot cache "
              "to batch over", file=sys.stderr)
        tracer.close()
        return 2

    eos_id = args.eos_id
    if eos_id is None and tok is not None:
        eos_id = tok.eos_id
    engine = Engine(
        model, {"params": params},
        EngineConfig(
            slots=args.slots, max_len=args.max_len, eos_id=eos_id,
            queue_capacity=args.queue_capacity,
            prefill_budget=args.prefill_budget,
            block_size=args.block_size, num_blocks=args.num_blocks,
            prefix_cache=args.prefix_cache,
        ),
        tracer=tracer, heartbeat=hb, chaos=chaos,
    )
    hb.pulse(phase="warmup")
    warm = [int(x) for x in args.warmup_lens.split(",") if x.strip()]
    engine.warmup(warm or None)

    defaults = {"max_new_tokens": args.max_new_default}
    try:
        if args.socket:
            print(f"[serve] listening on {args.socket} "
                  f"({args.slots} slots, max_len {args.max_len})",
                  file=sys.stderr)
            serve_socket(engine, args.socket, tok, defaults)
        else:
            serve_jsonl(engine, sys.stdin, sys.stdout, tok, defaults)
    except KeyboardInterrupt:
        pass
    finally:
        tracer.close()
        if tracer.enabled:
            # every request's lifecycle (queue/gate/prefill/decode/
            # client-write, with client-write timed around the sink
            # calls this process just made) is on the stream — point at
            # the consumer instead of making the operator remember it
            print(f"[serve] request traces at {tracer.path} — inspect "
                  f"with `python -m hyperion_tpu.cli.main obs trace "
                  f"{tracer.path}`",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
