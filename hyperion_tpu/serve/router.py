"""Replica-tier router — `hyperion route --replicas N --ckpt ...`.

PRs 5–8 made ONE engine process a good fleet citizen: continuous
batching, radix prefix reuse, per-request tracing, journal-replay crash
safety. This module is the layer that multiplies it — the front-end
process that turns "a server" into "a deployment" (ROADMAP item 3):

  * **Fleet supervision** — N `hyperion serve` children, each with its
    own unix socket, request journal, telemetry dir, and heartbeat,
    run under the shared supervisor core (`hyperion_tpu/supervisor.py`)
    with per-replica restart budgets and the heartbeat hang watchdog.
    The router itself never touches a jax backend (all device work
    lives in the children), so it stays responsive while a child is
    wedged inside a dead one.
  * **Health-aware dispatch** — least-loaded scoring over each
    replica's heartbeat payload (active slots + queue depth, which the
    engine publishes on serve, idle, AND terminal beats) plus the
    dispatches the router has sent since that beat. A stale heartbeat,
    a beat showing the replica left the serve phases (draining/done),
    a connection error, or a child exit EJECTS the replica; it is
    readmitted only on a fresh serve-phase beat newer than the
    ejection (`serve/replica.py` is the state machine).
  * **Session/prefix affinity** — requests sharing a `session_id`, or
    a long common prompt prefix, route to the same replica so its
    RadixPrefixCache keeps hitting. Stickiness yields when the sticky
    target's load exceeds the least-loaded replica by more than the
    slack (a hot session must not melt one replica while others idle).
  * **Failover with exactly-once delivery** — every token record on
    the wire carries its stream index `i`. When a replica dies
    mid-stream the router re-dispatches the ORIGINAL request to
    another replica: sampling is seed-deterministic (PRNG keys fold the
    absolute position, never the wall clock), so the new replica
    recomputes the identical stream and the router forwards only the
    tokens the client has not seen. The dead replica's own journal
    replays the request sink-less on restart — visible on its
    telemetry as the resumed prefill the acceptance test asserts — so
    no completion is ever lost, and none is ever delivered twice.
  * **Backpressure composition** — a `queue_full` rejection from one
    replica triggers re-dispatch to the next-best; when EVERY ready
    replica says queue_full (or none is ready) past the dispatch
    deadline, the router rejects with the standard `request_rejected`
    vocabulary (`queue_full` / `no_replica`) on its own stream, so
    fleet-wide saturation lands in the same doctor/diff tables as
    single-engine backpressure.
  * **Acting on alerts** (PR 14) — the monitor does not just TALLY the
    SLO alerts replicas report on their heartbeats, it acts on them. A
    replica burning its TTFT budget is STEERED: interactive traffic
    routes around it while batch keeps flowing (protect the latency
    tier without starving the replica), and its engine is ordered into
    a batch-class brownout over the exposition control socket. Steering
    reverses only after `--steer-clear-sweeps` CONSECUTIVE alert-free
    monitor sweeps — hysteresis, so a flapping alert cannot turn
    dispatch into a lottery. Sustained burn additionally spawns standby
    replicas up to `--max-replicas` and retires them once the fleet is
    quiet again. Every action is a telemetry event (`router_steer`,
    `router_scale`, `class_brownout`) that `obs doctor` narrates.

  * **The router itself is no longer the SPOF** — a router WAL
    (`serve/router_journal.py`) journals every dispatch (original wire
    line, chosen replica, session key) and each stream's forwarded
    high-water mark, flushed ahead of the client write like the
    replica journals. Under `hyperion route --supervise` the router
    runs with its own heartbeat watchdog; a restarted router life
    RE-ADOPTS still-live replicas straight from their heartbeats
    (no respawn, no replay storm), recovers the WAL, and re-dispatches
    orphaned streams through the same dedup + seed-deterministic
    recompute path — the union stream across router lives stays
    bit-identical and duplicate-free. Clients ride it out with the
    wire protocol's `resume` verb (`serve/client.py` auto-reconnects
    and resumes from its own last received index).

Failure matrix (SERVING.md "Replica tier" has the long version):
replica crash → supervised restart + journal replay + router failover;
router crash → the supervisor restarts it, the new life re-adopts the
still-live replicas and recovers the dispatch WAL, and auto-resuming
clients reconnect and receive the rest of each stream exactly once;
both crash → replicas replay their journals first, the router
re-adopts (or respawns the dead), clients resume last.
"""

from __future__ import annotations

import argparse
import hashlib
import itertools
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import OrderedDict
from pathlib import Path

from hyperion_tpu.obs import slo as slo_mod
from hyperion_tpu.obs.export import DEFAULT_WINDOW_S
from hyperion_tpu.obs.heartbeat import host_rss_mb
from hyperion_tpu.serve.client import TERMINAL_EVENTS, ServeClient
from hyperion_tpu.serve.hostcache import prefix_root_digest
from hyperion_tpu.serve.metrics import RouterMetrics
from hyperion_tpu.serve.queue import (
    CLASS_BATCH,
    REJECT_BAD_REQUEST,
    REJECT_DRAINING,
    REJECT_NO_REPLICA,
    REJECT_QUEUE_FULL,
    BrownoutGovernor,
)
from hyperion_tpu.serve.replica import SERVE_PHASES, READY, ReplicaHandle
from hyperion_tpu.serve.router_journal import OrphanedDispatch, RouterJournal
from hyperion_tpu.serve.server import _LineWriter, maybe_resume_doc
from hyperion_tpu.utils.clock import SYSTEM
from hyperion_tpu.utils.retry import RetryPolicy

# connect policy for replica dispatch: generous enough to ride a
# supervised restart (compile-cache warmups on real chips take seconds),
# bounded so a replica that never comes back fails over instead of
# hanging the relay
DISPATCH_CONNECT_RETRY = RetryPolicy(tries=8, base_delay_s=0.05,
                                     max_delay_s=1.0, deadline_s=20.0)


class ClientGone(Exception):
    """The CLIENT side of a relay died (its writer raised): the
    replica is healthy — this must never be mistaken for a replica
    failure, or one disconnecting client would eject the fleet."""


class _ClientWriter:
    """Wraps the client-facing writer so its failures raise ClientGone
    instead of the OSError the failover path treats as replica death."""

    def __init__(self, writer):
        self._w = writer

    def write(self, rec) -> None:
        try:
            self._w.write(rec)
        except Exception as e:  # noqa: BLE001 — any client-side failure
            raise ClientGone(repr(e)) from e


class StreamDedup:
    """Exactly-once filter over (possibly re-dispatched) token streams.

    Token records carry their stream index `i` (serve/server.py stamps
    it from the request's own token list). A failover re-dispatch
    recomputes the stream from index 0 — deterministic seeds make it
    bit-identical — and this filter drops everything the client already
    received. Records without an index (an old replica build) fall back
    to positional counting, which is still exact within one stream."""

    def __init__(self):
        self.delivered = 0

    def admit(self, rec: dict) -> bool:
        if rec.get("event") != "token":
            return True
        i = rec.get("i")
        if not isinstance(i, int):
            i = self.delivered
        if i < self.delivered:
            return False
        self.delivered = i + 1
        return True


class RouterPolicy:
    """Dispatch policy over a fleet of ReplicaHandles — pure host
    logic (no sockets, no processes) so `tests/test_router.py` drives
    it with fabricated heartbeats and zero jit compiles."""

    def __init__(self, replicas: list[ReplicaHandle], *,
                 affinity_slack: int = 4, affinity_cap: int = 512,
                 prefix_tokens: int = 32, prefix_chars: int = 128,
                 cache_aware: bool = True, clock=None):
        self.replicas = list(replicas)
        # wall-time source for eject/readmit decisions (heartbeats
        # stamp t_wall); injectable so the fleet simulator can run the
        # policy on virtual time
        self._clock = clock if clock is not None else SYSTEM
        self.affinity_slack = affinity_slack
        self.affinity_cap = affinity_cap
        self.prefix_tokens = prefix_tokens
        self.prefix_chars = prefix_chars
        self.cache_aware = cache_aware
        self._affinity: OrderedDict[str, int] = OrderedDict()
        self._ever_ready: set[int] = set()
        self._lock = threading.Lock()

    # -------------------------------------------------------- affinity

    def affinity_key(self, doc: dict) -> str | None:
        """Stickiness key: an explicit session beats a prompt prefix; a
        short prompt has no key (nothing worth pinning a replica for)."""
        sid = doc.get("session_id")
        if sid:
            return f"s:{sid}"
        ids = doc.get("prompt_ids")
        if isinstance(ids, list) and len(ids) >= self.prefix_tokens:
            head = ",".join(str(int(t)) for t in ids[:self.prefix_tokens])
            return "p:" + hashlib.sha1(head.encode()).hexdigest()[:16]
        prompt = doc.get("prompt")
        if isinstance(prompt, str) and len(prompt) >= self.prefix_chars:
            return "t:" + hashlib.sha1(
                prompt[:self.prefix_chars].encode()).hexdigest()[:16]
        return None

    # -------------------------------------------------------- dispatch

    def choose(self, doc: dict, exclude: set[int] | frozenset = frozenset(),
               ) -> tuple[ReplicaHandle | None, dict]:
        """Pick the dispatch target: the affinity-mapped replica when
        it is ready and within `affinity_slack` of the least-loaded
        score, else the least-loaded ready replica (ties broken by
        index, deterministically). Returns (replica, meta) with the
        replica's accounting already bumped — callers MUST `release`
        when the stream ends. (None, meta) when no ready replica
        remains outside `exclude`.

        Steering: a replica the router marked `steered` (burning its
        TTFT budget) is excluded for interactive requests while any
        un-steered alternative exists — batch traffic still flows to
        it, and with NO alternative interactive flows too (degraded
        service beats no service). Affinity yields the same way: a
        sticky key whose target is steered re-maps to a clean replica
        for the latency tier.

        Cache-aware term: when no affinity mapping fires, a replica
        that ADVERTISED this request's prefix-root digest on its last
        heartbeat (`prefix_roots`, from the engine's tiered KV cache)
        wins the dispatch if it sits within `affinity_slack` of the
        least-loaded score — its radix/host tiers already hold the
        prefix, so landing there skips the prefill the least-loaded
        replica would recompute. Past the slack (or with no advertiser)
        the policy degrades to plain least-loaded, and a successful
        steer seeds the affinity map so the rest of the burst sticks
        without re-consulting stale advertisements."""
        with self._lock:
            key = self.affinity_key(doc)
            meta = {"had_key": key is not None, "affinity_hit": False,
                    "steered_away": False, "cache_hit": False}
            ready = [r for r in self.replicas
                     if r.state == READY and r.index not in exclude]
            if not ready:
                return None, meta
            if str(doc.get("class", "")) != CLASS_BATCH:
                clear = [r for r in ready if not r.steered]
                if clear:
                    meta["steered_away"] = len(clear) < len(ready)
                    ready = clear
            best = min(ready, key=lambda r: (r.load_score(), r.index))
            target = best
            if key is not None:
                idx = self._affinity.get(key)
                cand = next((r for r in ready if r.index == idx), None)
                if cand is not None and cand.load_score() \
                        <= best.load_score() + self.affinity_slack:
                    target = cand
                    meta["affinity_hit"] = True
            if not meta["affinity_hit"] and self.cache_aware:
                ids = doc.get("prompt_ids")
                digest = (prefix_root_digest(ids)
                          if isinstance(ids, list) else None)
                if digest is not None:
                    hot = min((r for r in ready
                               if digest in r.hb_prefix_roots),
                              key=lambda r: (r.load_score(), r.index),
                              default=None)
                    if hot is not None and hot.load_score() \
                            <= best.load_score() + self.affinity_slack:
                        target = hot
                        meta["cache_hit"] = True
            if key is not None:
                self._affinity[key] = target.index
                self._affinity.move_to_end(key)
                while len(self._affinity) > self.affinity_cap:
                    self._affinity.popitem(last=False)
            target.inflight += 1
            target.dispatched_since_beat += 1
            target.dispatched_total += 1
            return target, meta

    def release(self, rep: ReplicaHandle) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)

    def add_replica(self, rep: ReplicaHandle) -> None:
        """Admit a scale-up standby into the dispatch set (it starts in
        STARTING and becomes dispatchable on its first serve beat, the
        same road every base replica walks)."""
        with self._lock:
            self.replicas.append(rep)

    def set_steered(self, rep: ReplicaHandle, on: bool) -> None:
        """Flip steering under the dispatch lock so choose() never sees
        a half-applied sweep."""
        with self._lock:
            rep.steered = on
            rep.steer_clear_sweeps = 0

    # ---------------------------------------------------------- health

    def eject(self, rep: ReplicaHandle, reason: str,
              now: float | None = None) -> bool:
        """Mark a replica not-dispatchable; True on a transition."""
        now = self._clock.wall() if now is None else now
        with self._lock:
            was = rep.state == READY
            rep.eject(now, reason)
            return was

    def observe_beats(self, read_hb, now: float | None = None,
                      stale_s: float = 10.0) -> list[tuple]:
        """One health sweep: feed each replica its latest heartbeat and
        apply the staleness rule. Returns transition tuples —
        ("ready"|"readmitted", replica) and ("ejected", replica,
        reason) — for the runtime to turn into events/metrics.
        `read_hb(path) -> dict | None` is injectable for tests."""
        now = self._clock.wall() if now is None else now
        # file I/O OUTSIDE the lock: a slow heartbeat read (NFS base
        # dir, big fleet) must never stall every relay's choose()
        beats = [read_hb(rep.heartbeat_path) for rep in self.replicas]
        out: list[tuple] = []
        with self._lock:
            for rep, hb in zip(self.replicas, beats):
                tr = rep.observe_beat(hb, now)
                if tr == "ready":
                    kind = ("readmitted" if rep.index in self._ever_ready
                            else "ready")
                    self._ever_ready.add(rep.index)
                    out.append((kind, rep))
                elif tr == "ejected":
                    # still beating, but draining/done: the handle
                    # already flipped state; surface the transition
                    out.append(("ejected", rep, rep.eject_reason))
                reason = rep.check_stale(now, stale_s)
                if reason is not None:
                    out.append(("ejected", rep, reason))
        return out

    @property
    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.state == READY)

    @property
    def inflight_total(self) -> int:
        with self._lock:
            return sum(r.inflight for r in self.replicas)


# ------------------------------------------------------------- runtime


def replica_argv(args, rep: ReplicaHandle) -> list[str]:
    """Child command for one replica: the serve surface the router
    fronts, with the per-replica socket/journal wired in. Chaos plans
    (`--replica-chaos IDX:PLAN`) attach only to their replica — the
    deterministic kill-one-mid-stream drill."""
    argv = [sys.executable, "-m", "hyperion_tpu.cli.main", "serve",
            "--ckpt", args.ckpt,
            "--socket", rep.socket_path,
            "--journal", rep.journal_path,
            "--max-len", str(args.max_len),
            "--slots", str(args.slots),
            "--block-size", str(args.block_size),
            "--num-blocks", str(args.num_blocks),
            "--queue-capacity", str(args.queue_capacity),
            "--prefill-budget", str(args.prefill_budget),
            "--prefill-chunk", str(getattr(args, "prefill_chunk", 0)),
            "--interactive-weight",
            str(getattr(args, "interactive_weight", 3)),
            "--batch-weight", str(getattr(args, "batch_weight", 1)),
            "--batch-capacity", str(getattr(args, "batch_capacity", 0)),
            "--batch-deadline-s",
            str(getattr(args, "batch_deadline_s", 0.0)),
            "--max-new-default", str(args.max_new_default),
            "--warmup-lens", args.warmup_lens,
            "--heartbeat-every", str(args.replica_heartbeat_every),
            "--drain-timeout", str(args.drain_timeout)]
    argv.append("--prefix-cache" if args.prefix_cache
                else "--no-prefix-cache")
    # tiered KV host spill (serve/hostcache.py) rides to every replica;
    # the hot prefix roots their heartbeats advertise back feed the
    # dispatch policy's cache-aware steering
    hc = int(getattr(args, "host_cache_mb", 0) or 0)
    if hc:
        argv += ["--host-cache-mb", str(hc)]
    # engine-level SLO targets ride to every replica (the TTFT
    # histograms live in the engines; the router only tallies the
    # alerts their heartbeats report back)
    for flag, val in (("--slo-ttft-p99-ms", args.slo_ttft_p99_ms),
                      ("--slo-reject-rate", args.slo_reject_rate),
                      ("--slo-availability", args.slo_availability),
                      ("--slo-fast-s", args.slo_fast_s),
                      ("--slo-slow-s", args.slo_slow_s)):
        if val:
            argv += [flag, str(val)]
    if args.no_tokenizer:
        argv.append("--no-tokenizer")
    else:
        argv += ["--tokenizer-dir", args.tokenizer_dir]
    if args.eos_id is not None:
        argv += ["--eos-id", str(args.eos_id)]
    plan = dict(p.split(":", 1) for p in (args.replica_chaos or [])
                if ":" in p).get(str(rep.index))
    if plan:
        argv += ["--chaos", plan]
    return argv


def _route_window_value(reg, metric: str, window_s: float,
                        now: float | None = None,
                        min_count: int = 1) -> float | None:
    """Router-level SLO metric: the fraction of finished relays the
    ROUTER rejected (fleet saturation / no-replica), windowed. Engine
    rejects a replica absorbed via re-dispatch never count — those are
    the router doing its job."""
    if metric == "reject_rate":
        return slo_mod.counter_ratio(reg, ("route_rejected",),
                                     ("route_completed",), window_s, now)
    return None


class FleetActions:
    """The acting half of the monitor sweep — alert tallying,
    steer/unsteer hysteresis, and the burning-count scale governor over
    a `RouterPolicy` — factored free of threads, sockets, and
    subprocesses. The live `Router` drives it from its monitor thread
    with real side-effect callbacks (control-socket brownout orders,
    child spawn/retire); the fleet simulator (`serve/simulate.py`)
    drives the SAME object on a virtual clock with synthetic callbacks,
    so steer/scale policy has exactly one implementation wherever it
    runs."""

    def __init__(self, policy: RouterPolicy, metrics: RouterMetrics,
                 tracer, *, act: bool = True,
                 steer_clear_sweeps: int = 3,
                 scale_gov: BrownoutGovernor | None = None,
                 order_brownout=None, scale_up=None, scale_down=None,
                 scaling_paused=None, log=None):
        self.policy = policy
        self.metrics = metrics
        self.tracer = tracer
        self.act = bool(act)
        self.steer_clear_sweeps = max(1, int(steer_clear_sweeps or 3))
        self.scale_gov = scale_gov
        self._order_brownout = order_brownout or (lambda rep, on: None)
        self._scale_up = scale_up or (lambda: None)
        self._scale_down = scale_down or (lambda: None)
        self._scaling_paused = scaling_paused or (lambda: False)
        self._log = log or (lambda msg: None)
        # alert names already seen per replica, so the fleet tally
        # counts RAISES, not beats
        self._alert_seen: dict[int, set] = {}

    def sweep_alerts(self) -> list[str]:
        """Fleet alert surfacing: each replica's heartbeat carries the
        SLO alerts its engine has FIRING (obs/slo.py); tally them so
        one `obs top` row — and one router_end field — answers "is
        anything alarming, anywhere" without opening N streams. New
        names count as raises; a name persisting across beats does not
        re-count. Only a DISPATCHABLE replica's alerts count: an
        ejected/dead child's last beat would otherwise keep a ghost
        alert firing fleet-wide forever (the dead replica itself is
        already a named incident — its stale alarm must not page on
        top of it). A restarted replica still alerting re-counts on
        readmission: a new observation epoch, honestly re-raised."""
        fleet_alerts: list[str] = []
        new_raises = 0
        for rep in self.policy.replicas:
            cur = set(rep.hb_alerts) if rep.state == READY else set()
            fleet_alerts += [f"r{rep.index}:{a}" for a in sorted(cur)]
            fresh = cur - self._alert_seen.get(rep.index, set())
            for a in sorted(fresh):
                new_raises += 1
                self.tracer.event("replica_alert", replica=rep.index,
                                  alert=a)
            self._alert_seen[rep.index] = cur
        self.metrics.on_fleet_alerts(new_raises)
        return fleet_alerts

    @staticmethod
    def burning(rep: ReplicaHandle) -> bool:
        """A READY replica reporting any TTFT-family SLO alert on its
        last beat — the one signal that says the LATENCY tier is being
        hurt there right now (reject/availability alerts have their own
        remedies: failover and restart already handle those)."""
        return rep.state == READY and any("ttft" in a for a in rep.hb_alerts)

    def sweep(self) -> int:
        """Steer/unsteer each replica off its heartbeat alerts, then
        feed the burning count to the scale governor. Returns the
        burning count (rides the router heartbeat). No-op when not
        acting — the fleet is then observed and tallied only."""
        if not self.act:
            return 0
        burning = 0
        for rep in self.policy.replicas:
            if self.burning(rep):
                burning += 1
                if not rep.steered:
                    self.policy.set_steered(rep, True)
                    self.metrics.on_steer(True)
                    self.tracer.event("router_steer", replica=rep.index,
                                      on=True,
                                      alerts=list(rep.hb_alerts))
                    self._log(f"[route] replica {rep.index} steered: "
                              f"{','.join(rep.hb_alerts)}")
                    self._order_brownout(rep, True)
                else:
                    rep.steer_clear_sweeps = 0
            elif rep.steered and rep.state == READY:
                # hysteresis: only CONSECUTIVE alert-free sweeps of a
                # beating replica count toward unsteer — an ejected
                # replica's silence is not evidence of recovery
                rep.steer_clear_sweeps += 1
                if rep.steer_clear_sweeps >= self.steer_clear_sweeps:
                    self.policy.set_steered(rep, False)
                    self.metrics.on_steer(False)
                    self.tracer.event("router_steer", replica=rep.index,
                                      on=False)
                    self._log(f"[route] replica {rep.index} unsteered "
                              f"after {self.steer_clear_sweeps} clean "
                              f"sweeps")
                    self._order_brownout(rep, False)
        self.metrics.observe_steered(
            sum(1 for r in self.policy.replicas if r.steered))
        if self.scale_gov is not None and not self._scaling_paused():
            tr = self.scale_gov.update(burning)
            if tr == "enter":
                self._scale_up()
            elif tr == "exit":
                self._scale_down()
        return burning


class Router:
    """The running fleet: supervisor thread per replica, a heartbeat
    monitor, and one relay thread per in-flight request."""

    def __init__(self, args, tracer, hb,
                 metrics: RouterMetrics | None = None,
                 child_argv_fn=replica_argv, clock=None):
        self.args = args
        self._clock = clock if clock is not None else SYSTEM
        self.tracer = tracer
        self.hb = hb
        self.metrics = metrics or RouterMetrics()
        # which supervised life of this router is running (the
        # supervisor stamps HYPERION_ATTEMPT per restart): rides every
        # hop context so a fleet trace can tell "dispatched before the
        # router crash" from "re-dispatched by the next life"
        self.router_life = int(
            os.environ.get("HYPERION_ATTEMPT", "0") or 0)
        # injectable child command (tests run the router runtime over
        # jax-free fake replicas that speak the wire protocol)
        self._child_argv_fn = child_argv_fn
        base = Path(args.base_dir)
        self.replicas = [ReplicaHandle.under(base, i)
                         for i in range(args.replicas)]
        self.policy = RouterPolicy(
            self.replicas,
            affinity_slack=args.affinity_slack,
            prefix_tokens=args.affinity_prefix,
            clock=self._clock)
        self._procs: dict[int, subprocess.Popen] = {}
        self._sup_threads: list[threading.Thread] = []
        self._req_threads: list[threading.Thread] = []
        self._active: set[str] = set()
        self._req_lock = threading.Lock()
        self._rids = itertools.count()
        self._stopping = threading.Event()   # no new work
        self._hard_stop = threading.Event()  # abandon in-flight relays
        # router-scoped chaos (crash@dispatch, conn_reset): its state
        # file sits next to the WAL so dispatch-count faults fire once
        # per supervisor LINEAGE, not once per router life
        self.chaos = None
        if getattr(args, "chaos", ""):
            from hyperion_tpu.testing import chaos as chaos_mod

            self.chaos = chaos_mod.activate(
                args.chaos, state_path=base / "route_chaos_state.json")
        # the router WAL (serve/router_journal.py): dispatch records +
        # forwarded high-water marks, recovered by the next router life
        jpath = str(getattr(args, "router_journal", "") or "")
        self.journal: RouterJournal | None = None
        if jpath not in ("off", "none", "0"):
            self.journal = RouterJournal(
                jpath or str(base / "router_journal.jsonl"),
                fault=(self.chaos.journal_io
                       if self.chaos is not None else None))
        self._dispatch_n = itertools.count(1)  # chaos crash@dispatch
        # resume bookkeeping: original wire lines by request id (bounded
        # — a resume for an evicted id falls back to the WAL or the
        # client's carried request), plus WAL orphans awaiting a
        # socket-mode client's resume verb
        self._resume_docs: OrderedDict[str, str] = OrderedDict()
        self._recovered: dict[str, OrphanedDispatch] = {}
        self._mon_stop = threading.Event()
        self._mon_thread: threading.Thread | None = None
        # acting state (PR 14): steer hysteresis + the scale governor.
        # The governor is the queue's own BrownoutGovernor watching the
        # count of BURNING replicas as its "depth" — enter (>=1 burning)
        # spawns a standby, exit (0 burning) retires one, and the
        # hysteresis that keeps brownout from flapping keeps the fleet
        # size from flapping too.
        self._act = bool(getattr(args, "act", True))
        self._steer_clear_sweeps = max(
            1, int(getattr(args, "steer_clear_sweeps", 3)))
        self._max_replicas = int(getattr(args, "max_replicas", 0) or 0)
        self._scale_gov = None
        if self._act and self._max_replicas > len(self.replicas):
            self._scale_gov = BrownoutGovernor(depth_high=1)
        # the shared steer/scale sweep (FleetActions): the Router wires
        # in its real side effects — control-socket brownout orders and
        # child spawn/retire — where the simulator wires synthetic ones
        self.actions = FleetActions(
            self.policy, self.metrics, tracer,
            act=self._act,
            steer_clear_sweeps=self._steer_clear_sweeps,
            scale_gov=self._scale_gov,
            order_brownout=self._order_class_brownout,
            scale_up=self._scale_up, scale_down=self._scale_down,
            scaling_paused=self._stopping.is_set, log=self._log)
        self._exporter = None
        self._slo = None
        route_budget = getattr(args, "slo_reject_rate", 0.0) or 0.0
        if route_budget > 0:
            self._slo = slo_mod.SLOMonitor(
                (slo_mod.SLOTarget("route_reject_rate", "reject_rate",
                                   float(route_budget)),),
                self.metrics.reg,
                fast_s=getattr(args, "slo_fast_s", 0.0)
                or slo_mod.DEFAULT_FAST_S,
                slow_s=getattr(args, "slo_slow_s", 0.0)
                or slo_mod.DEFAULT_SLOW_S,
                value_fn=_route_window_value)

    # ----------------------------------------------------------- fleet

    def _log(self, msg: str) -> None:
        # stderr always: stdout is the client's JSONL wire stream
        print(msg, file=sys.stderr, flush=True)

    def _notify_eject(self, rep: ReplicaHandle, reason: str) -> None:
        """THE ejection emission — metric (unless this is the planned
        shutdown taking everyone out), event, stderr line. Callers must
        only invoke it for a transition that actually happened."""
        if not self._stopping.is_set():
            self.metrics.on_eject()
        self.tracer.event("replica_ejected", replica=rep.index,
                          reason=reason)
        self._log(f"[route] replica {rep.index} ejected: {reason}")

    def _eject(self, rep: ReplicaHandle, reason: str) -> None:
        if self.policy.eject(rep, reason):
            self._notify_eject(rep, reason)

    def _adopt_live(self, rep: ReplicaHandle) -> int | None:
        """A previous router life's child may still be alive and
        serving — restarting it would throw away its warm caches and
        force a pointless journal replay. Adoption test: a fresh
        serve-phase heartbeat whose pid answers signal 0. Returns the
        live pid, or None (spawn normally)."""
        from hyperion_tpu.obs.heartbeat import read_heartbeat

        hb = read_heartbeat(rep.heartbeat_path)
        if not isinstance(hb, dict):
            return None
        t_wall = hb.get("t_wall")
        pid = hb.get("pid")
        if hb.get("phase") not in SERVE_PHASES \
                or not isinstance(t_wall, (int, float)) \
                or self._clock.wall() - float(t_wall) > self.args.stale_s \
                or not isinstance(pid, int) or pid <= 0:
            return None
        try:
            os.kill(pid, 0)
        except (OSError, ProcessLookupError):
            return None
        return pid

    def _babysit_adopted(self, rep: ReplicaHandle, pid: int) -> bool:
        """Watch an adopted child until it dies or we stop. True means
        the router is stopping/retiring it (supervisor thread should
        end); False means the child died — fall through to a normal
        supervised respawn."""
        hang = self.args.hang_timeout
        while True:
            if self._stopping.is_set() or rep.retiring:
                return True
            try:
                os.kill(pid, 0)
            except (OSError, ProcessLookupError):
                return False
            if hang > 0 and rep.hb_t_wall is not None \
                    and self._clock.wall() - rep.hb_t_wall > hang:
                # wedged exactly like a spawned child would be: the
                # watchdog contract applies to adoptees too
                self._log(f"[route] adopted replica {rep.index} "
                          f"heartbeat stale past {hang:.0f}s — SIGKILL "
                          f"pid {pid}")
                try:
                    os.kill(pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
                return False
            time.sleep(0.25)

    def _supervise_one(self, rep: ReplicaHandle) -> None:
        from hyperion_tpu.supervisor import (
            Decision,
            heartbeat_watchdog,
            supervise_loop,
        )

        pid = self._adopt_live(rep)
        if pid is not None:
            rep.adopted = True
            self.metrics.on_adopt()
            self.tracer.event("replica_adopted", replica=rep.index,
                              pid=pid)
            self._log(f"[route] replica {rep.index} adopted from a "
                      f"previous router life (pid {pid}) — serving "
                      "continues uninterrupted")
            if self._babysit_adopted(rep, pid):
                return
            rep.adopted = False
            self._eject(rep, "adopted replica died")
            self.tracer.event("replica_exit", replica=rep.index,
                              rc=None, adopted=True)
            if self._stopping.is_set() or rep.retiring:
                return
            rep.restarts += 1  # the respawn below is a restart

        try:
            err_fd = sys.stderr.fileno()
        except Exception:  # noqa: BLE001
            err_fd = 2  # pytest capture replaces sys.stderr objects
        runner = heartbeat_watchdog(
            rep.heartbeat_path, self.args.hang_timeout, log=self._log,
            on_spawn=lambda p: self._procs.__setitem__(rep.index, p),
            # the children's stdout must never reach the router's —
            # chaos chatter and stray prints go where supervisor logs go
            popen_kwargs={"stdout": err_fd},
        )

        def run(argv: list, env: dict) -> int:
            env = {**env,
                   # the heartbeat IS the router's control plane: force
                   # each child's stream on, to its own dir, whatever
                   # the operator chose for the router's telemetry
                   "HYPERION_TELEMETRY": rep.telemetry_path,
                   "HYPERION_REPLICA": str(rep.index)}
            env.pop("HYPERION_HEARTBEAT", None)
            return runner(argv, env)

        def decide(rc: int) -> Decision:
            self._eject(rep, f"child exit {rc}")
            self.tracer.event("replica_exit", replica=rep.index, rc=rc)
            if self._stopping.is_set() or rep.retiring:
                return Decision.stop(0)
            rep.restarts += 1
            # restart immediately: an ejected replica costs fleet
            # capacity every second, and the journal replay it owes is
            # idempotent — backoff belongs to crash LOOPS, which the
            # per-replica restart budget already bounds
            return Decision.restart(immediate=rep.restarts <= 1)

        rc = supervise_loop(
            self._child_argv_fn(self.args, rep), decide=decide,
            max_restarts=self.args.max_restarts, run_child=run,
            label=f"replica{rep.index}", log=self._log)
        # always logged (the eject below is silent when the relay's
        # connection error ejected first): a supervisor that stops
        # while the router is still serving is a fact the operator —
        # and any flake hunt — needs on stderr
        self._log(f"[route] replica {rep.index} supervisor done "
                  f"(rc {rc}, restarts {rep.restarts}, "
                  f"stopping={self._stopping.is_set()})")
        self._eject(rep, f"supervisor finished (rc {rc})")

    def exposition(self, window_s: float = DEFAULT_WINDOW_S) -> dict:
        """Live snapshot for the router's exposition socket: fleet
        table (per-replica state/occupancy/alerts from the handles the
        monitor keeps fresh) + the router's own metrics. Host-only —
        the router never touches a jax backend, and neither does this."""
        reps = [{
            "replica": r.index, "state": r.state, "phase": r.hb_phase,
            "active": r.hb_active, "queue": r.hb_queue,
            "inflight": r.inflight, "restarts": r.restarts,
            "alerts": list(r.hb_alerts),
            "steered": r.steered, "standby": r.standby,
        } for r in self.replicas]
        msum = self.metrics.summary()
        own = (self._slo.active_names() if self._slo is not None else [])
        # the aggregated list counts READY replicas only (a dead
        # child's stale alarm is not a live alert); the per-replica
        # rows keep the last-known alerts next to their state, so the
        # evidence is still on the board
        fleet = [f"r{r['replica']}:{a}" for r in reps
                 if r["state"] == READY for a in r["alerts"]]
        return {
            "role": "router",
            "run": self.tracer.run,
            "phase": "route",
            "step": msum["dispatched"],
            "active": self.policy.inflight_total,
            "queue": 0,
            "ready": self.policy.ready_count,
            "draining": self._stopping.is_set(),
            "alerts": own + fleet,
            "replicas": reps,
            # what the acting layer is doing RIGHT NOW — `obs top`'s
            # act column and the doctor's router-action narration
            "act": {
                "enabled": self._act,
                "steered": [r.index for r in self.replicas if r.steered],
                "fleet": len(self.replicas),
                "max_replicas": self._max_replicas,
                # crash-safety counters: replicas adopted from a dead
                # router life, client streams resumed across the cut
                "adopted": msum["adopted"],
                "resumes": msum["resumes"],
            },
            "metrics": self.metrics.reg.snapshot(),
            "windows": self.metrics.reg.windowed_snapshot(window_s),
            # host memory only: the router holds no params and no KV
            # pool, but its RSS still belongs on the obs top board
            "memory": {"rss_mb": host_rss_mb()},
        }

    def _sweep_fleet_alerts(self) -> list[str]:
        """Delegates to the shared `FleetActions` sweep (the simulator
        drives the same object)."""
        return self.actions.sweep_alerts()

    # --------------------------------------------- acting on alerts

    _burning = staticmethod(FleetActions.burning)

    def _order_class_brownout(self, rep: ReplicaHandle,
                              active: bool) -> None:
        """One control verb to one replica's engine over its exposition
        socket: clamp/shed the batch tier (or lift the order). Best-
        effort — a replica that predates the verb, or is mid-restart,
        simply doesn't ack; steering alone still protects the latency
        tier, and the event records `acked` either way so the doctor
        can tell an ignored order from an obeyed one."""
        from hyperion_tpu.obs.export import (
            exposition_path,
            request_control,
        )

        resp = None
        try:
            resp = request_control(
                exposition_path(rep.heartbeat_path),
                {"cmd": "class_brownout", "active": active},
                timeout_s=2.0)
        except Exception:  # noqa: BLE001 — an order must never kill
            pass           # the monitor thread
        acked = isinstance(resp, dict) and resp.get("status") == "ok"
        self.metrics.on_class_brownout(active)
        self.tracer.event("class_brownout", replica=rep.index,
                          active=active, acked=acked)
        self._log(f"[route] replica {rep.index} class_brownout "
                  f"{'on' if active else 'off'}"
                  f"{'' if acked else ' (no ack)'}")

    def _sweep_actions(self) -> int:
        """The acting half of the monitor sweep (`--no-act` turns it
        off — the router then observes and tallies exactly as PR 13
        built it). Delegates to the shared `FleetActions` object."""
        self.actions.act = self._act
        return self.actions.sweep()

    def _scale_up(self) -> None:
        """Spawn one standby replica (the next index under the base
        dir) — same supervisor road as the base fleet, dispatchable on
        its first serve beat."""
        idx = len(self.replicas)
        if idx >= self._max_replicas:
            return
        rep = ReplicaHandle.under(Path(self.args.base_dir), idx)
        rep.standby = True
        rep.dir.mkdir(parents=True, exist_ok=True)
        self.replicas.append(rep)
        self.policy.add_replica(rep)
        t = threading.Thread(target=self._supervise_one, args=(rep,),
                             name=f"replica{rep.index}-sup", daemon=True)
        t.start()
        self._sup_threads.append(t)
        self.metrics.on_scale(True)
        self.tracer.event("router_scale", direction="up",
                          replica=rep.index, fleet=len(self.replicas))
        self._log(f"[route] scale up: standby replica {rep.index} "
                  f"spawning ({len(self.replicas)}/{self._max_replicas})")

    def _scale_down(self) -> None:
        """Retire the youngest live standby: eject it from dispatch
        (in-flight relays fail over exactly like a crash — exactly-once
        delivery holds), terminate the child, and let its supervisor's
        decide() see `retiring` and stop instead of restarting."""
        rep = next((r for r in reversed(self.replicas)
                    if r.standby and not r.retiring), None)
        if rep is None:
            return
        rep.retiring = True
        self._eject(rep, "retired (scale-down)")
        proc = self._procs.get(rep.index)
        if proc is not None and proc.poll() is None:
            try:
                proc.terminate()
            except OSError:
                pass
        self.metrics.on_scale(False)
        self.tracer.event("router_scale", direction="down",
                          replica=rep.index,
                          fleet=sum(1 for r in self.replicas
                                    if not r.retiring))
        self._log(f"[route] scale down: standby replica {rep.index} "
                  f"retiring")

    def start(self) -> None:
        self.tracer.event(
            "router_start", replicas=len(self.replicas),
            slots=self.args.slots, max_len=self.args.max_len,
            stale_s=self.args.stale_s,
            affinity_prefix=self.args.affinity_prefix)
        self.hb.pulse(phase="route_spawn", ready=0)
        if self.hb.enabled:
            # obs.sock next to the router's heartbeat — `obs top` on
            # the base dir reads the whole fleet through this one
            # socket even before it walks the replica dirs
            from hyperion_tpu.obs.export import (
                MetricsExporter,
                exposition_path,
            )

            self._exporter = MetricsExporter(
                exposition_path(self.hb.path), self.exposition,
                label="route-obs").start()
        for rep in self.replicas:
            rep.dir.mkdir(parents=True, exist_ok=True)
            t = threading.Thread(target=self._supervise_one, args=(rep,),
                                 name=f"replica{rep.index}-sup",
                                 daemon=True)
            t.start()
            self._sup_threads.append(t)
        self._mon_thread = threading.Thread(
            target=self._monitor, name="route-monitor", daemon=True)
        self._mon_thread.start()

    def _monitor(self, poll_s: float = 0.25) -> None:
        from hyperion_tpu.obs.heartbeat import read_heartbeat

        last_snap = 0.0
        while not self._mon_stop.is_set():
            for tr in self.policy.observe_beats(
                    read_heartbeat, stale_s=self.args.stale_s):
                if tr[0] in ("ready", "readmitted"):
                    rep = tr[1]
                    if tr[0] == "readmitted":
                        self.metrics.on_readmit()
                    self.tracer.event(f"replica_{tr[0]}",
                                      replica=rep.index,
                                      restarts=rep.restarts)
                    self._log(f"[route] replica {rep.index} {tr[0]} "
                              f"(pid {rep.hb_pid})")
                else:
                    # observe_beats already flipped the handle's state
                    # (the tuple IS the transition) — notify directly,
                    # the idempotent _eject would swallow it
                    self._notify_eject(tr[1], tr[2])
            ready = self.policy.ready_count
            inflight = self.policy.inflight_total
            fleet_alerts = self._sweep_fleet_alerts()
            self._sweep_actions()
            self.metrics.observe_fleet(ready, inflight,
                                       alerts_active=len(fleet_alerts))
            if self._slo is not None:
                trs = self._slo.evaluate()
                if trs:
                    slo_mod.publish(trs, self.tracer, self.metrics.reg,
                                    prefix="route",
                                    active=len(self._slo.active))
            self.hb.beat(step=self.metrics.summary()["dispatched"],
                         phase="route", active=inflight, queue=0,
                         ready=ready, alerts=fleet_alerts)
            now = self._clock()
            if now - last_snap >= 5.0:
                self.tracer.snapshot(self.metrics.reg)
                last_snap = now
            self._mon_stop.wait(poll_s)

    def wait_ready(self, n: int = 1, timeout_s: float = 120.0) -> bool:
        t0 = self._clock()
        while self._clock() - t0 < timeout_s:
            if self.policy.ready_count >= n:
                return True
            if self._hard_stop.is_set():
                return False
            time.sleep(0.1)
        return self.policy.ready_count >= n

    # --------------------------------------------------------- intake

    @property
    def requests_idle(self) -> bool:
        with self._req_lock:
            return not self._active

    def begin_drain(self) -> None:
        if not self._stopping.is_set():
            self._stopping.set()
            self.tracer.event("router_draining",
                              inflight=self.policy.inflight_total)

    def submit_line(self, line: str, writer) -> threading.Thread | None:
        """Parse the routing envelope of one wire line and hand it to a
        relay thread. Malformed lines reject immediately with the
        standard vocabulary — never an exception on the intake path.
        The wire protocol's `resume` verb takes the resume path
        instead of a fresh dispatch."""
        if (rdoc := maybe_resume_doc(line)) is not None:
            return self._resume(rdoc, writer)
        try:
            doc = json.loads(line)
            if not isinstance(doc, dict):
                raise ValueError("request line must be a JSON object")
        except (json.JSONDecodeError, ValueError) as e:
            self.metrics.on_reject(REJECT_BAD_REQUEST)
            self.tracer.event("request_rejected",
                              request=f"unparsed_{next(self._rids)}",
                              reason=REJECT_BAD_REQUEST,
                              error=str(e)[:200], queued_s=0.0)
            writer.write({"id": None, "event": "error",
                          "error": f"bad json: {e}"})
            return None
        if not doc.get("id"):
            doc["id"] = f"route_{next(self._rids)}"
        rid = str(doc["id"])
        if self._stopping.is_set():
            self._reject(rid, REJECT_DRAINING, self._clock(), writer)
            return None
        # the WAL line: the request exactly as the client sent it (plus
        # the minted id) — what a NEXT router life needs to re-dispatch.
        # Remembered in-process too, so a client resume after conn_reset
        # does not depend on the client carrying its request back.
        wal_line = json.dumps(doc, separators=(",", ":"))
        self._resume_docs[rid] = wal_line
        while len(self._resume_docs) > 1024:
            self._resume_docs.popitem(last=False)
        with self._req_lock:
            self._active.add(rid)
        t = threading.Thread(target=self._relay,
                             args=(rid, doc, writer),
                             kwargs={"wal_line": wal_line},
                             name=f"relay-{rid}", daemon=True)
        t.start()
        if len(self._req_threads) > 256:
            # a long-lived router must not accumulate dead thread
            # objects one per request served
            self._req_threads = [x for x in self._req_threads
                                 if x.is_alive()]
        self._req_threads.append(t)
        return t

    def _reject(self, rid: str, reason: str, submitted: float,
                writer) -> None:
        self.metrics.on_reject(reason)
        self.tracer.event(
            "request_rejected", request=rid, reason=reason,
            queued_s=round(max(0.0, self._clock() - submitted), 6))
        if self.journal is not None:
            self.journal.done(rid, reason)
        writer.write({"id": rid, "event": "rejected", "reason": reason})

    # ---------------------------------------------------------- relay

    def _relay(self, rid: str, doc: dict, writer, *,
               resume_from: int = 0, wal_line: str | None = None,
               as_resume: bool = False, hop_base: int = 0) -> None:
        try:
            self._relay_inner(rid, doc, _ClientWriter(writer),
                              resume_from=resume_from, wal_line=wal_line,
                              as_resume=as_resume, hop_base=hop_base)
        except ClientGone as e:
            # the CLIENT vanished mid-stream: its request dies with it
            # (nothing left to deliver to), the replica keeps serving —
            # the engine's own dropped-sink handling finishes the slot.
            # Terminal in the WAL too: a RESUME re-opens it (the parse
            # side treats dispatch-after-done as exactly that), but a
            # router death must not re-dispatch a stream whose client
            # already walked away.
            if self.journal is not None:
                self.journal.done(rid, "client_gone")
            self.tracer.event("client_disconnected", request=rid,
                              error=str(e)[:200])
        except Exception as e:  # noqa: BLE001 — a relay bug must reject
            # its request, never silently strand the client's stream
            try:
                self._reject(rid, REJECT_BAD_REQUEST, self._clock(),
                             writer)
            except Exception:  # noqa: BLE001 — reject write to a dead
                pass           # client must not mask the real error
            self._log(f"[route] relay {rid} failed: {e!r}")
        finally:
            with self._req_lock:
                self._active.discard(rid)

    def _relay_inner(self, rid: str, doc: dict, writer, *,
                     resume_from: int = 0, wal_line: str | None = None,
                     as_resume: bool = False, hop_base: int = 0) -> None:
        submitted = self._clock()
        dedup = StreamDedup()
        # a resume (client-driven or WAL orphan re-dispatch) floors the
        # dedup at what was already forwarded — the replica recomputes
        # the identical stream from 0 and only the remainder passes
        dedup.delivered = max(0, int(resume_from))
        crashed: set[int] = set()   # replicas this request already
        #                             visited: their journals hold its
        #                             admit record — never go back
        qfull: set[int] = set()
        deadline = submitted + self.args.dispatch_timeout
        redispatches = 0
        saw_qfull = False
        backoff = 0.05
        # failover-gap clock: starts the instant a replica death is
        # detected, stops at the FIRST record the client sees from the
        # replacement — connect retries against a restarting replica
        # ARE the gap, so the stop lives inside the next stream
        fail_at: float | None = None

        def _gap_done() -> None:
            nonlocal fail_at
            if fail_at is not None:
                self.metrics.on_failover_gap(self._clock() - fail_at)
                fail_at = None

        trace: dict = {"id": rid, "hop": hop_base, "attempt": 0,
                       "router_life": self.router_life}
        while True:
            if self._hard_stop.is_set():
                self._reject(rid, REJECT_DRAINING, submitted, writer)
                return
            rep, meta = self.policy.choose(doc, exclude=crashed | qfull)
            if rep is None:
                if self._clock() > deadline:
                    self._reject(
                        rid,
                        REJECT_QUEUE_FULL if saw_qfull
                        else REJECT_NO_REPLICA,
                        submitted, writer)
                    return
                # every ready replica rejected queue_full this sweep:
                # clear the sweep set and retry after a breath — the
                # fleet may drain, and the deadline bounds the wait
                qfull.clear()
                time.sleep(backoff)
                backoff = min(backoff * 2.0, 0.5)
                continue
            self.metrics.on_dispatch(rep.index, meta["affinity_hit"],
                                     meta["had_key"],
                                     cache_hit=meta.get("cache_hit",
                                                        False))
            # the hop context: trace id = the minted request id; `hop`
            # counts placements across the request's WHOLE journey
            # (resume relays continue past the legs a previous relay
            # already burned via hop_base), `attempt` counts
            # re-dispatch retries within THIS relay
            trace = {"id": rid, "hop": hop_base + redispatches,
                     "attempt": redispatches,
                     "router_life": self.router_life}
            self.tracer.event(
                "route_dispatch", request=rid, replica=rep.index,
                affinity=meta["affinity_hit"],
                cache_steer=meta.get("cache_hit", False),
                redispatch=redispatches, trace=trace)
            # WAL before wire: the placement is durable before the
            # replica can possibly have seen the request. The stored
            # line stays the request exactly as the client sent it —
            # the hop context rides a separate record field.
            if self.journal is not None:
                self.journal.dispatch(
                    rid,
                    line=(wal_line if wal_line is not None
                          else json.dumps(doc, separators=(",", ":"))),
                    replica=rep.index,
                    session=self.policy.affinity_key(doc),
                    n=redispatches, trace=trace)
            if self.chaos is not None:
                # counts every placement router-wide — the
                # crash@dispatch=N drill's trigger
                self.chaos.on_dispatch(next(self._dispatch_n))
            send_doc = dict(doc)
            send_doc["trace"] = trace
            try:
                outcome, terminal = self._stream_from(rep, rid, send_doc,
                                                      dedup, writer,
                                                      as_resume=as_resume,
                                                      gap_cb=_gap_done)
            except (OSError, ConnectionError, ValueError) as e:
                # mid-stream death (or connect that never came up):
                # eject, fail over. The renewed deadline is deliberate —
                # this request was admitted somewhere; dropping it now
                # would turn one replica crash into client-visible loss
                self._eject(rep, f"connection error "
                                 f"({e.__class__.__name__})")
                crashed.add(rep.index)
                redispatches += 1
                if fail_at is None:
                    fail_at = self._clock()
                self.metrics.on_redispatch("replica_lost")
                self.tracer.event("route_redispatch", request=rid,
                                  from_replica=rep.index,
                                  reason="replica_lost",
                                  delivered=dedup.delivered,
                                  trace=trace)
                deadline = max(deadline, self._clock()
                               + self.args.dispatch_timeout)
                continue
            finally:
                # whatever ends the attempt — terminal, failover, or a
                # relay bug propagating out — the load accounting must
                # not leak an inflight count
                self.policy.release(rep)
            if outcome == "queue_full":
                saw_qfull = True
                qfull.add(rep.index)
                redispatches += 1
                self.metrics.on_redispatch(REJECT_QUEUE_FULL)
                self.tracer.event("route_redispatch", request=rid,
                                  from_replica=rep.index,
                                  reason=REJECT_QUEUE_FULL,
                                  trace=trace)
                continue
            self.metrics.on_complete()
            if self.journal is not None:
                self.journal.done(rid, outcome)
            self.tracer.event(
                "route_complete", request=rid, replica=rep.index,
                status=outcome, tokens=dedup.delivered,
                redispatches=redispatches,
                e2e_s=round(self._clock() - submitted, 6),
                trace=trace)
            return

    def _stream_from(self, rep: ReplicaHandle, rid: str, doc: dict,
                     dedup: StreamDedup, writer,
                     as_resume: bool = False,
                     gap_cb=None) -> tuple[str, dict]:
        """One dispatch attempt: open the replica stream, forward
        deduplicated records to the client. Returns (outcome, terminal
        record) where outcome is the terminal event name or
        "queue_full" (the one rejection the router retries elsewhere
        instead of forwarding). Raises OSError/ConnectionError on a
        dead replica — the caller's failover path.

        `as_resume` relays the request as the wire protocol's resume
        verb instead of the raw request: the replica suffixes its
        internal wire id, so a replica that already holds this id's
        admit record (it served the stream before the crash) never
        sees a duplicate id on its journal."""
        with ServeClient(rep.socket_path,
                         timeout_s=self.args.stream_timeout,
                         retry=DISPATCH_CONNECT_RETRY) as client:
            if as_resume:
                stream = client.stream(
                    kind="resume", request_id=rid,
                    next_index=dedup.delivered, request=doc, id=rid)
            else:
                stream = client.stream(**doc)
            for rec in stream:
                if gap_cb is not None:
                    # first record from this replica closes any open
                    # failover gap (no-op when none is running)
                    gap_cb()
                ev = rec.get("event")
                if ev == "token":
                    if dedup.admit(rec):
                        # hwm ahead of the client write (mirror of the
                        # replica journal's journal-before-sink rule):
                        # a router death between the two costs AT MOST
                        # one replayed-and-deduped token on recovery
                        if self.journal is not None:
                            self.journal.hwm(rid, dedup.delivered)
                        writer.write(rec)
                    continue
                if ev in TERMINAL_EVENTS:
                    if ev == "rejected" \
                            and rec.get("reason") == REJECT_QUEUE_FULL:
                        return "queue_full", rec
                    writer.write(rec)
                    return ev, rec
                # non-terminal bookkeeping records pass through
                writer.write(rec)
        raise ConnectionError("replica stream ended without a terminal "
                              "event")

    # --------------------------------------------------------- resume

    def _resume(self, doc: dict, writer) -> threading.Thread | None:
        """Answer a client's `resume {request_id, next_index}` verb:
        find the original request (in-process memory from this life,
        the WAL orphan a previous life left, or the copy the client
        itself carried — in that order) and relay it again with the
        dedup floored at the client's own index. The client's count is
        authoritative: the journaled hwm may run one token ahead."""
        rid = str(doc.get("request_id") or "")
        try:
            next_index = max(0, int(doc.get("next_index", 0)))
        except (TypeError, ValueError):
            next_index = 0
        src: dict | None = None
        wal_line = self._resume_docs.get(rid) if rid else None
        if wal_line is not None:
            try:
                src = json.loads(wal_line)
            except json.JSONDecodeError:
                src = None
        if src is None and rid in self._recovered:
            orphan = self._recovered.pop(rid)
            src = orphan.doc
            wal_line = orphan.line if src is not None else None
        if src is None:
            carried = doc.get("request")
            if isinstance(carried, dict):
                src = dict(carried)
                src["id"] = rid
                wal_line = json.dumps(src, separators=(",", ":"))
        if not rid or not isinstance(src, dict):
            writer.write({"id": rid or None, "event": "rejected",
                          "reason": "unknown_request"})
            return None
        self.metrics.on_resume()
        self.tracer.event("route_resume", request=rid,
                          next_index=next_index,
                          router_life=self.router_life)
        self._log(f"[route] resuming {rid} from index {next_index}")
        with self._req_lock:
            self._active.add(rid)
        t = threading.Thread(
            target=self._relay, args=(rid, src, writer),
            kwargs={"resume_from": next_index, "wal_line": wal_line,
                    "as_resume": True, "hop_base": 1},
            name=f"resume-{rid}", daemon=True)
        t.start()
        self._req_threads.append(t)
        return t

    def recover_journal(self, writer=None) -> int:
        """Recover the previous router life's WAL. Socket mode
        (writer=None): orphans wait for their clients' resume verbs —
        the client's own index is the authoritative floor, and a
        pre-emptive re-dispatch would race the reconnect. JSONL mode:
        there is no reconnect (the pipe is the client), so orphans
        re-dispatch immediately, floored at the journaled hwm."""
        if self.journal is None:
            return 0
        orphans, clean = self.journal.recover()
        if not orphans:
            return 0
        self.metrics.on_orphans(len(orphans))
        for o in orphans:
            self.tracer.event("route_orphan_recovered", request=o.id,
                              replica=o.replica, hwm=o.hwm,
                              dispatches=o.dispatches)
        self._log(f"[route] WAL recovery: {len(orphans)} orphaned "
                  f"dispatch(es) from a previous router life")
        if writer is None:
            self._recovered = {o.id: o for o in orphans}
            return len(orphans)
        for o in orphans:
            src = o.doc
            if src is None:
                self.journal.done(o.id, "unrecoverable")
                continue
            self._resume_docs[o.id] = o.line
            with self._req_lock:
                self._active.add(o.id)
            t = threading.Thread(
                target=self._relay, args=(o.id, src, writer),
                kwargs={"resume_from": o.hwm, "wal_line": o.line,
                        "as_resume": True,
                        "hop_base": max(1, o.dispatches)},
                name=f"recover-{o.id}", daemon=True)
            t.start()
            self._req_threads.append(t)
        return len(orphans)

    # ------------------------------------------------------- shutdown

    def shutdown(self) -> dict:
        """Drain the fleet: SIGTERM every child (their own graceful
        drain finishes in-flight work and close-cleans the journal),
        join the supervisors, stop the monitor, stamp `router_end`."""
        self._stopping.set()

        def signal_children(kill: bool = False) -> None:
            for rep in self.replicas:
                proc = self._procs.get(rep.index)
                if proc is not None and proc.poll() is None:
                    try:
                        proc.kill() if kill else proc.terminate()
                    except OSError:
                        pass
                elif rep.adopted and rep.hb_pid:
                    # adopted from a previous router life: no Popen
                    # handle, signal by the heartbeat's pid
                    try:
                        os.kill(rep.hb_pid, signal.SIGKILL if kill
                                else signal.SIGTERM)
                    except (OSError, ProcessLookupError):
                        pass

        # a child may still be mid-spawn: wait briefly for every live
        # supervisor to register its Popen, or the signal pass below
        # misses it and the join runs out its whole budget before the
        # kill fallback can reach the late arrival
        t0 = self._clock()
        while self._clock() - t0 < 5.0 and any(
                t.is_alive() and self._procs.get(rep.index) is None
                for t, rep in zip(self._sup_threads, self.replicas)):
            time.sleep(0.05)
        signal_children()
        join_s = self.args.drain_timeout + 10.0
        t0 = self._clock()
        for t in self._sup_threads:
            t.join(timeout=max(0.5, join_s - (self._clock() - t0)))
        signal_children(kill=True)
        for t in self._sup_threads:
            t.join(timeout=5.0)
        self._mon_stop.set()
        if self._mon_thread is not None:
            self._mon_thread.join(timeout=5.0)
        if self._exporter is not None:
            self._exporter.close()
        if self.journal is not None:
            # clean only when nothing is owed: an in-flight stream at
            # hard-stop must survive as a WAL orphan for the next life
            if self.requests_idle:
                self.journal.close_clean()
            else:
                self.journal.close()
        summary = self.metrics.summary()
        summary["per_replica_restarts"] = {
            str(r.index): r.restarts for r in self.replicas}
        self.tracer.snapshot(self.metrics.reg)
        # the full summary rides the terminal event — nested per-replica
        # dicts included, they are what the bench probe reads back for
        # its fairness and affinity keys
        self.tracer.event("router_end", **summary)
        self.hb.close(phase="done",
                      dispatched=summary["dispatched"],
                      completed=summary["completed"])
        return summary


# --------------------------------------------------------- front-ends


def route_jsonl(router: Router, infile, outfile,
                drain=None, hard_stop=None) -> dict:
    """stdin/stdout mode: a reader thread feeds relay threads; the
    router drains on EOF (same composition contract as serve_jsonl —
    the smoke script pipes into it)."""
    out = _LineWriter(outfile)
    # a previous router life's orphans re-dispatch straight onto this
    # pipe — there is no per-client reconnect in JSONL mode, the hwm
    # floor is the only dedup boundary
    router.recover_journal(out)
    eof = threading.Event()

    def reader():
        try:
            for line in infile:
                line = line.strip()
                if not line:
                    continue
                router.submit_line(line, out)
        finally:
            eof.set()

    t = threading.Thread(target=reader, name="route-stdin", daemon=True)
    t.start()
    while True:
        if hard_stop is not None and hard_stop.is_set():
            router._hard_stop.set()
            break
        if drain is not None and drain.is_set():
            router.begin_drain()
        if eof.is_set() and router.requests_idle:
            break
        time.sleep(0.02)
    t.join(timeout=5)
    return router.shutdown()


def route_socket(router: Router, socket_path: str,
                 drain=None, hard_stop=None, ready=None) -> dict:
    """Unix-socket mode: each connection's requests relay back over its
    own writer — the same transport contract as serve_socket, one
    level up."""
    import socket as socket_mod
    import socketserver

    from hyperion_tpu.serve.server import prepare_socket_path

    class _ChaosResetWriter:
        """conn_reset@p=X injection point: before each client write the
        chaos plan may raise ConnectionResetError; the handler then
        hard-closes the connection so the CLIENT sees the cut (EOF
        mid-stream) and exercises its resume path."""

        def __init__(self, writer, connection):
            self._w = writer
            self._conn = connection

        def write(self, rec) -> None:
            try:
                router.chaos.conn_reset("route_client_write")
            except ConnectionResetError:
                try:
                    self._conn.shutdown(socket_mod.SHUT_RDWR)
                    self._conn.close()
                except OSError:
                    pass
                raise
            self._w.write(rec)

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            writer = _LineWriter(self.wfile)
            if router.chaos is not None and any(
                    f.kind == "conn_reset" for f in router.chaos.faults):
                writer = _ChaosResetWriter(writer, self.connection)
            mine: list[threading.Thread] = []
            for raw in self.rfile:
                try:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line:
                        continue
                    t = router.submit_line(line, writer)
                    if t is not None:
                        mine.append(t)
                except Exception:  # noqa: BLE001 — a dead client's
                    break          # problem, never the router's
            for t in mine:
                t.join(timeout=router.args.stream_timeout)

    class Server(socketserver.ThreadingMixIn,
                 socketserver.UnixStreamServer):
        daemon_threads = True
        allow_reuse_address = True

        def handle_error(self, request, client_address):
            router.tracer.event("client_error",
                                client=str(client_address))

    # orphans from a previous life park in _recovered and wait for
    # their clients' resume verbs — BEFORE the socket opens, so a fast
    # reconnect cannot race the recovery scan
    router.recover_journal(None)
    # bind under the flock so a dying previous life's still-bound file
    # can never be probed/unlinked/rebound into a race
    srv = prepare_socket_path(socket_path,
                              bind=lambda: Server(socket_path, Handler))
    acceptor = threading.Thread(target=srv.serve_forever,
                                name="route-accept", daemon=True)
    acceptor.start()
    if ready is not None:
        ready.set()
    try:
        while True:
            if hard_stop is not None and hard_stop.is_set():
                router._hard_stop.set()
                break
            if drain is not None and drain.is_set():
                router.begin_drain()
                if router.requests_idle:
                    break
            time.sleep(0.05)
    finally:
        srv.shutdown()
        srv.server_close()
        try:
            Path(socket_path).unlink()
        except OSError:
            pass
    return router.shutdown()


# --------------------------------------------------------------- CLI


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hyperion route",
        description="replica-tier serving: N supervised engine "
                    "replicas behind a health-aware, prefix-affine "
                    "router (stdin/JSONL by default, --socket for a "
                    "local unix socket)")
    p.add_argument("--replicas", type=int, default=2,
                   help="engine replicas to spawn and supervise")
    p.add_argument("--base-dir", default="data/router",
                   help="fleet root: replica_<i>/ holds each child's "
                        "socket, journal, telemetry, heartbeat; the "
                        "router's own telemetry.jsonl sits beside them "
                        "(`obs doctor <base-dir>` renders the fleet)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="router front-end socket (default: stdin/stdout)")
    # ---- dispatch policy ----
    p.add_argument("--affinity-prefix", type=int, default=32,
                   help="prompt tokens hashed into the prefix-affinity "
                        "key: requests sharing this long a prefix (or a "
                        "session_id) stick to one replica so its radix "
                        "cache keeps hitting")
    p.add_argument("--affinity-slack", type=int, default=4,
                   help="load headroom an affinity target may carry "
                        "over the least-loaded replica before "
                        "stickiness yields")
    p.add_argument("--dispatch-timeout", type=float, default=60.0,
                   help="seconds a request may wait for a dispatchable "
                        "replica (renewed after a failover) before the "
                        "router rejects it")
    p.add_argument("--stream-timeout", type=float, default=300.0,
                   help="per-read socket timeout on a replica stream")
    # ---- fleet health ----
    p.add_argument("--stale-s", type=float, default=10.0,
                   help="heartbeat age that ejects a replica from "
                        "dispatch (readmission needs a fresh serve-"
                        "phase beat)")
    p.add_argument("--hang-timeout", type=float, default=60.0,
                   help="heartbeat age at which the supervisor SIGKILLs "
                        "a wedged child (0 = off)")
    p.add_argument("--max-restarts", type=int, default=2,
                   help="per-replica restart budget before its "
                        "supervisor gives up")
    p.add_argument("--ready-timeout", type=float, default=180.0,
                   help="seconds to wait for replicas to come up before "
                        "serving")
    p.add_argument("--min-ready", type=int, default=1,
                   help="replicas that must be READY before the router "
                        "starts accepting requests (deterministic "
                        "spread for drills/benches; default 1 = serve "
                        "as soon as anything can)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="graceful-drain window, router AND replicas")
    p.add_argument("--replica-chaos", action="append", default=None,
                   metavar="IDX:PLAN",
                   help="attach a chaos plan (testing/chaos.py grammar) "
                        "to one replica, e.g. 0:crash@tick=2 — the "
                        "kill-one-mid-stream drill")
    # ---- router crash safety (WAL + supervised failover) ----
    p.add_argument("--supervise", action="store_true",
                   help="run the router itself under the supervisor "
                        "core (heartbeat watchdog + restart budget): a "
                        "crashed router life restarts, re-adopts still-"
                        "live replicas, recovers the dispatch WAL, and "
                        "answers client resume verbs")
    p.add_argument("--router-journal", default="", metavar="PATH",
                   help="router WAL path (default: <base-dir>/"
                        "router_journal.jsonl; 'off' disables): every "
                        "dispatch + forwarded high-water mark, "
                        "recovered by the next router life")
    p.add_argument("--chaos", default="", metavar="PLAN",
                   help="router-scoped chaos plan (testing/chaos.py "
                        "grammar): crash@dispatch=N hard-exits the "
                        "router after its Nth placement, conn_reset@p=X "
                        "resets client wires probabilistically — the "
                        "router-death and stream-resume drills")
    # ---- acting on alerts (steer / class brownout / scale) ----
    p.add_argument("--act", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="act on replica SLO alerts: steer interactive "
                        "traffic off a TTFT-burning replica, order its "
                        "engine into a batch-class brownout, and (with "
                        "--max-replicas) scale standbys in and out "
                        "(--no-act = observe/tally only)")
    p.add_argument("--steer-clear-sweeps", type=int, default=3,
                   help="consecutive alert-free monitor sweeps before "
                        "a steered replica takes interactive traffic "
                        "again (unsteer hysteresis)")
    p.add_argument("--max-replicas", type=int, default=0,
                   help="fleet ceiling for alert-driven scale-up "
                        "(standby replicas spawn while any replica "
                        "burns its TTFT budget, retire when the fleet "
                        "is quiet; 0 = no scaling)")
    # ---- replica engine surface (forwarded to each child) ----
    p.add_argument("--ckpt", required=True,
                   help="gathered-export .npz every replica serves")
    p.add_argument("--tokenizer-dir", default="data/tokenizer")
    p.add_argument("--no-tokenizer", action="store_true")
    p.add_argument("--eos-id", type=int, default=None)
    p.add_argument("--max-len", type=int, default=256)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-blocks", type=int, default=0)
    p.add_argument("--prefix-cache", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--host-cache-mb", type=int, default=0,
                   help="per-replica host-RAM KV spill tier "
                        "(serve/hostcache.py), forwarded to every "
                        "engine; replicas advertise hot prefix roots "
                        "on heartbeats and the dispatch policy steers "
                        "matching no-session requests to an "
                        "advertising replica within the affinity "
                        "slack (0 = off)")
    p.add_argument("--queue-capacity", type=int, default=64)
    p.add_argument("--prefill-budget", type=int, default=512)
    p.add_argument("--prefill-chunk", type=int, default=0)
    p.add_argument("--interactive-weight", type=int, default=3)
    p.add_argument("--batch-weight", type=int, default=1)
    p.add_argument("--batch-capacity", type=int, default=0)
    p.add_argument("--batch-deadline-s", type=float, default=0.0)
    p.add_argument("--max-new-default", type=int, default=32)
    p.add_argument("--warmup-lens", default="8,32")
    p.add_argument("--replica-heartbeat-every", type=int, default=5,
                   help="replica beat cadence in ticks — the router's "
                        "load scores are only as fresh as these beats")
    # ---- SLO burn-rate alerting (obs/slo.py) ----
    p.add_argument("--slo-ttft-p99-ms", type=float, default=0.0,
                   help="per-replica SLO target forwarded to every "
                        "engine (windowed TTFT p99 ceiling in ms; 0 = "
                        "off); firing alerts ride replica heartbeats "
                        "back into the router's fleet tally")
    p.add_argument("--slo-reject-rate", type=float, default=0.0,
                   help="reject-rate budget (0 = off): forwarded to "
                        "every engine AND evaluated router-level over "
                        "the fleet-wide relay outcomes (prefix "
                        "`route_` on the router's own alerts)")
    p.add_argument("--slo-availability", type=float, default=0.0,
                   help="per-replica availability floor forwarded to "
                        "every engine (0 = off)")
    p.add_argument("--slo-fast-s", type=float, default=0.0,
                   help="fast burn window seconds (0 = 60)")
    p.add_argument("--slo-slow-s", type=float, default=0.0,
                   help="slow burn window seconds (0 = 600)")
    return p


def supervise_route(argv: list[str], args) -> int:
    """`hyperion route --supervise`: the crash loop around the ROUTER —
    the same supervisor core the router wraps around its replicas, one
    level up. A dead router life restarts immediately (orphaned streams
    cost fleet throughput every second; the WAL makes the restart
    idempotent); a router whose heartbeat goes stale past
    --hang-timeout is SIGKILLed. The restarted life re-adopts still-
    live replicas from their heartbeats (no respawn), recovers the
    dispatch WAL, and answers the resume verbs of reconnecting
    clients — the doctor is consulted between lives for the verdict
    the operator reads."""
    from hyperion_tpu.supervisor import (
        Decision,
        heartbeat_watchdog,
        run_child,
        strip_flags,
        supervise_loop,
    )

    def log(msg: str) -> None:
        # stderr, always: the router's stdout is the client wire
        print(msg, file=sys.stderr, flush=True)

    base = Path(args.base_dir)
    base.mkdir(parents=True, exist_ok=True)
    hb_path = str(base / "heartbeat.json")
    runner = run_child
    if args.hang_timeout > 0:
        runner = heartbeat_watchdog(hb_path, args.hang_timeout, log=log)

    def decide(rc: int) -> Decision:
        verdict = None
        try:
            from hyperion_tpu.obs.doctor import diagnose

            verdict = diagnose(str(base / "telemetry.jsonl")) \
                .get("verdict")
        except Exception as e:  # noqa: BLE001 — triage is advisory
            log(f"[route-supervisor] doctor consult failed: {e}")
        log(f"[route-supervisor] router exit {rc}; doctor verdict: "
            f"{verdict or 'unavailable'}; restarting — the new life "
            "re-adopts live replicas and recovers the dispatch WAL")
        return Decision.restart(immediate=True)

    child_argv = strip_flags(argv, {"--supervise"}, set())
    child = [sys.executable, "-m", "hyperion_tpu.cli.main", "route",
             *child_argv]
    return supervise_loop(child, decide=decide,
                          max_restarts=args.max_restarts,
                          run_child=runner, label="route-supervisor",
                          log=log)


def main(argv=None) -> int:
    import os
    import signal

    argv = sys.argv[1:] if argv is None else list(argv)
    args = build_parser().parse_args(argv)
    if args.supervise:
        return supervise_route(argv, args)

    from hyperion_tpu.obs import heartbeat as obs_heartbeat
    from hyperion_tpu.obs import trace as obs_trace

    base = Path(args.base_dir)
    base.mkdir(parents=True, exist_ok=True)
    # the router's stream defaults ON (it is the fleet's control-plane
    # record); HYPERION_TELEMETRY=0 still silences it. proc=0 skips the
    # dist lookup — the router must never touch a jax backend.
    tracer = obs_trace.from_env(
        str(base / "telemetry.jsonl"),
        run=f"route_{int(SYSTEM.wall())}", proc=0, enabled_by_default=True)
    hb = obs_heartbeat.Heartbeat.for_tracer(tracer, every=25)
    router = Router(args, tracer, hb)
    router.start()
    need = max(1, min(args.min_ready, args.replicas))
    if not router.wait_ready(need, timeout_s=args.ready_timeout):
        print(f"[route] fewer than {need} replica(s) ready within "
              f"{args.ready_timeout:.0f}s — check "
              f"{base}/replica_*/telemetry.jsonl", file=sys.stderr)
        router._hard_stop.set()
        router.shutdown()
        tracer.close()
        return 3

    drain_evt = threading.Event()
    hard_evt = threading.Event()

    def _on_signal(signum, frame):
        if drain_evt.is_set():
            hard_evt.set()
        else:
            print(f"[route] signal {signum}: draining (signal again to "
                  "stop now)", file=sys.stderr)
        drain_evt.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass

    print(f"[route] {router.policy.ready_count}/{args.replicas} "
          f"replica(s) ready under {base}", file=sys.stderr)
    try:
        if args.socket:
            print(f"[route] listening on {args.socket}", file=sys.stderr)
            summary = route_socket(router, args.socket,
                                   drain=drain_evt, hard_stop=hard_evt)
        else:
            summary = route_jsonl(router, sys.stdin, sys.stdout,
                                  drain=drain_evt, hard_stop=hard_evt)
    except KeyboardInterrupt:
        summary = router.shutdown()
    print(f"[route] done: {summary['dispatched']} dispatched, "
          f"{summary['completed']} completed, "
          f"{summary['redispatched']} re-dispatched, "
          f"{summary['rejected']} rejected; per-replica "
          f"{summary['per_replica_dispatched']}", file=sys.stderr)
    tracer.close()
    if tracer.enabled:
        print(f"[route] fleet evidence: `python -m hyperion_tpu.cli.main "
              f"obs doctor {base}`", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
