"""Slot-based continuous-batching decode engine — Orca-style iteration
scheduling on a paged, prefix-shared TPU cache.

The single-shot path (`infer/generate.py`) decodes ONE batch of aligned
prompts: prefill, then a `lax.scan` that every request enters and
leaves together. A server cannot batch that way — requests arrive when
they arrive, finish when they finish, and a batch that waits for its
slowest member wastes every other slot's ticks. Continuous batching
(Yu et al., OSDI '22) decouples the two: the unit of scheduling is one
decode TICK, and membership of the batch is re-decided between ticks.

TPU constraint that shapes everything here: **recompilation is the
enemy.** XLA specializes on shapes, so every device-side structure is
shape-fixed at construction and the tick/prefill executables compile
once, at warmup, forever:

  * The KV cache is a `[num_blocks, block_size]` POOL
    (`models/llama.py:init_paged_cache`), not a per-slot slab. A slot
    addresses it through a block table (`serve/blocks.py`): logical
    position p lives at physical block `bt[slot, p // bs]`. HBM burn
    tracks tokens actually held, not `slots × max_len`, and two slots
    whose prompts share a prefix share the physical blocks outright
    (PagedAttention — Kwon et al., SOSP '23). The table itself is a
    tiny `[S, MB]` int32 host array shipped with each jitted call, so
    block churn never touches compiled code.
  * Every per-request quantity the tick needs — cache depth, eos
    latch, remaining budget, temperature/top_k/top_p, PRNG key — is a
    `[S]` device array threaded through the jitted call, so slot
    churn is a cheap scatter into state rows, never a retrace.
  * A radix prefix cache (`serve/blocks.py:RadixPrefixCache`) maps
    token prefixes to retained block chains: a shared system prompt is
    prefilled ONCE, and every later request that starts with it skips
    straight to its own suffix — the prefill jit runs on the suffix
    bucket, attending over the shared blocks through the table. A
    prompt that diverges mid-block still reuses the agreeing positions
    via one copy-on-write block copy (the `copy` jit).
  * Admission is block-aware: the queue only pops a request when its
    worst-case block demand fits (`can_admit` — free + evictable
    radix blocks minus outstanding reservations). Under `optimistic`
    admission the pool can still exhaust mid-decode; the engine then
    PREEMPTS the youngest slot back to the queue head (its generated
    tokens ride along and re-prefill, usually from its own still-
    cached prefix) instead of crashing.

  * Speculative decoding (`spec_k` + `serve/draft.py`) turns the tick
    into a draft/verify/accept round: a host-side draft source
    proposes up to k tokens per slot, ONE batched target forward over
    a `[S, k+1]` window scores all slots' proposals through the same
    paged path (vector `cache_index` + per-row position masks), and a
    fully static accept-masked select emits the longest prefix the
    target agrees with plus its own correction — 1..k+1 tokens per
    slot per tick, one executable per (S, k), zero retraces.

Semantics contract (the oracle `tests/test_serve.py` pins): at
temperature 0 a request decoded through this engine — while other
slots churn, share its blocks, or preempt around it, with or without
speculation — emits **bit-identical tokens** to
`infer/generate.generate` on the same prompt. K/V at position p depend
only on tokens 0..p, so shared blocks hold exactly the values each
sharer would have computed, and every per-slot op is row-independent;
the acceptance rule only ever keeps tokens the target itself would
have produced.
"""

from __future__ import annotations

import dataclasses
import itertools
import sys
import time

from hyperion_tpu.utils.clock import SYSTEM as _CLOCK
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from hyperion_tpu.infer.generate import sample_token_slots
from hyperion_tpu.infer.speculative import accept_draft
from hyperion_tpu.serve.draft import DraftSource, NgramDraft
from hyperion_tpu.serve.blocks import (
    BlockManager,
    RadixPrefixCache,
    SeqAlloc,
    blocks_for,
)
from hyperion_tpu.serve.hostcache import (
    HostBlockStore,
    HotRootTracker,
    prefix_root_digest,
)
from hyperion_tpu.obs import slo as slo_mod
from hyperion_tpu.obs.export import DEFAULT_WINDOW_S
from hyperion_tpu.obs.heartbeat import host_rss_mb as hb_host_rss_mb
from hyperion_tpu.obs.ledger import CompileLedger
from hyperion_tpu.obs.tickprof import (
    FlightRecorder,
    TickProfiler,
    null_flight_recorder,
)
from hyperion_tpu.serve.journal import MAX_REPLAYS_DEFAULT
from hyperion_tpu.serve.metrics import ServeMetrics
from hyperion_tpu.serve.queue import (
    CLASS_BATCH,
    CLASS_INTERACTIVE,
    REJECT_BAD_REQUEST,
    REJECT_DRAINING,
    REJECT_POISONED,
    REJECT_SHED,
    AdmissionQueue,
    BrownoutGovernor,
    Request,
)

_SNAPSHOT_EVERY = 32  # ticks between metric snapshots on the stream

# `_admit`'s third outcome: the slot was claimed but prefill proceeds
# in chunks across later steps (distinct from None = allocation race,
# which requeues). No token exists yet; the caller just moves on.
_CHUNK_ADMIT = object()


# --- the three compiled surfaces, shared process-wide -----------------
# Module-level bodies with the model/eos/pad as STATIC jit arguments:
# every Engine in a process shares one jit cache per surface, so two
# engines over the same model and shapes (the test suite's shape, and
# any multi-engine deployment's) compile each executable exactly once.

def _tick_impl(model, eos_id, pad_id, variables, cache, st, bt, live):
    # every live slot advances one token: write last_token's K/V at
    # its own depth through its block-table row, attend its own
    # filled prefix (gathered from the pool), sample with its own
    # params. Dead lanes (freed or preempted — `live` is the host's
    # slot table shipped as a mask) still compute but write to the
    # null block and emit pad.
    act = st["active"] & live
    logits, cache = model.apply(
        variables, st["last_token"][:, None],
        cache=cache, cache_index=st["lengths"], block_tables=bt,
    )
    keys = jax.vmap(jax.random.fold_in)(st["keys"], st["lengths"])
    nxt = sample_token_slots(
        logits[:, 0], keys,
        st["temperature"], st["top_k"], st["top_p"],
    )
    nxt = jnp.where(act, nxt, jnp.int32(pad_id))
    adv = act.astype(jnp.int32)
    gen = st["generated"] + adv
    lengths = st["lengths"] + adv
    hit_eos = (nxt == eos_id) if eos_id is not None \
        else jnp.zeros_like(act)
    finished = act & (hit_eos | (gen >= st["budget"]))
    st = {
        **st,
        "last_token": jnp.where(act, nxt, st["last_token"]),
        "generated": gen,
        "lengths": lengths,
        "active": act & ~finished,
    }
    return cache, st, nxt, finished


def _spec_tick_impl(model, eos_id, pad_id, variables, cache, st, bt, live,
                    drafts):
    # the speculative tick: every live slot advances 1..k+1 tokens in
    # ONE target forward. The verify window [last_token, d_1..d_k]
    # writes K/V at positions lengths..lengths+k through each slot's
    # block-table row (the paged path takes a [S]-vector cache_index
    # and spans T positions per row — models/llama.py), and row i's
    # logits predict position lengths+i+1. Acceptance per slot is the
    # shared longest-agreeing-prefix rule (infer/speculative.py), so
    # temp-0 output is bit-identical to sequential decode; rejected
    # positions hold stale K/V that the causal mask keeps invisible
    # until the next window idempotently overwrites them. Every update
    # below is an accept-MASKED select over static [S, k+1] shapes —
    # never a dynamic slice — so one executable serves every
    # acceptance pattern and `compile_stats()` stays flat.
    act = st["active"] & live
    k = drafts.shape[1]
    window = jnp.concatenate([st["last_token"][:, None], drafts], axis=1)
    logits, cache = model.apply(
        variables, window,
        cache=cache, cache_index=st["lengths"], block_tables=bt,
    )
    # t[s, i] = the token the SEQUENTIAL tick would emit at position
    # lengths[s]+i given this window prefix: greedy rows take argmax;
    # temp>0 rows draw with the slot key folded at that position —
    # the exact fold the sequential tick performs — so a seeded
    # sampling stream is unchanged whether its drafts hit or miss
    pos = st["lengths"][:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    keys = jax.vmap(jax.vmap(jax.random.fold_in, in_axes=(None, 0)))(
        st["keys"], pos)
    t_arr = jax.vmap(
        lambda lg, ky: sample_token_slots(
            lg, ky, st["temperature"], st["top_k"], st["top_p"]),
        in_axes=1, out_axes=1,
    )(logits, keys)  # [S, k+1]
    m, v = accept_draft(drafts, t_arr)
    # emit v[:, j] iff j is within the accepted prefix (+correction),
    # within the remaining budget, and no earlier eos in the window —
    # active rows always emit >= 1 (j=0 is the correction of an empty
    # prefix and budget >= 1 while active), matching the sequential
    # tick's liveness
    iota = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    is_eos = (v == eos_id) if eos_id is not None \
        else jnp.zeros(v.shape, bool)
    eos_before = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) \
        - is_eos.astype(jnp.int32)
    remaining = st["budget"] - st["generated"]
    emit = (iota <= m[:, None]) & (iota < remaining[:, None]) \
        & (eos_before == 0) & act[:, None]
    cnt = emit.sum(axis=1).astype(jnp.int32)
    out = jnp.where(emit, v, jnp.int32(pad_id))
    last_i = jnp.maximum(cnt - 1, 0)[:, None]
    last = jnp.take_along_axis(v, last_i, axis=1)[:, 0]
    ended_eos = jnp.take_along_axis(is_eos, last_i, axis=1)[:, 0] & (cnt > 0)
    gen = st["generated"] + cnt
    finished = act & (ended_eos | (gen >= st["budget"]))
    st = {
        **st,
        "last_token": jnp.where(act & (cnt > 0), last, st["last_token"]),
        "generated": gen,
        "lengths": st["lengths"] + cnt,
        "active": act & ~finished,
    }
    # accepted DRAFTS only (the correction token is a normal decode
    # token, not a draft win) — what the acceptance-rate gauge reads
    acc = jnp.minimum(m, cnt)
    return cache, st, out, cnt, acc, finished


def _prefill_impl(model, eos_id, variables, cache, st, prompt, bt_row,
                  slot, start, true_len, temperature, top_k, top_p,
                  budget, key):
    # prompt [1, Pb]: the UNCACHED suffix, bucket-padded, whose
    # positions are start..start+Pb-1. `start` > 0 is a prefix-cache
    # hit: positions 0..start-1 already sit in shared blocks of bt_row
    # and are attended, never recomputed. Pad positions beyond the
    # table's coverage write to the null block (the model routes
    # them); pad K/V inside the tail block is masked until decode
    # overwrites it position by position. Compiled once per bucket.
    logits, cache = model.apply(
        variables, prompt, cache=cache, cache_index=start,
        block_tables=bt_row[None],
    )
    last = jax.lax.dynamic_slice_in_dim(
        logits[0], true_len - 1, 1, axis=0)  # [1, V]
    # fold position = (total prompt length - 1): identical whether the
    # prefix came from cache or compute, so a hit never shifts the
    # sampling stream
    fkey = jax.random.fold_in(key, start + true_len - 1)
    first = sample_token_slots(
        last, fkey[None], temperature[None], top_k[None], top_p[None],
    )[0]
    hit_eos = (first == eos_id) if eos_id is not None else False
    finished = jnp.logical_or(hit_eos, budget <= 1)
    st = {
        "lengths": st["lengths"].at[slot].set(start + true_len),
        "active": st["active"].at[slot].set(~finished),
        "last_token": st["last_token"].at[slot].set(first),
        "generated": st["generated"].at[slot].set(1),
        "budget": st["budget"].at[slot].set(budget),
        "temperature": st["temperature"].at[slot].set(temperature),
        "top_k": st["top_k"].at[slot].set(top_k),
        "top_p": st["top_p"].at[slot].set(top_p),
        "keys": st["keys"].at[slot].set(key),
    }
    return cache, st, first, finished


def _chunk_impl(model, variables, cache, window, bt_row, start):
    # one chunked-prefill segment (Sarathi-Serve, OSDI '24): write the
    # K/V of `window`'s positions start..start+C-1 through this slot's
    # block-table row and DISCARD the logits — no sampling happens
    # until the final segment runs through `_prefill_impl`, whose fold
    # position (total prompt length - 1) is independent of how the
    # prefix was produced, so chunking never shifts the sampling
    # stream. K/V at position p depend only on tokens 0..p, which every
    # earlier segment already wrote: the values are bit-identical to a
    # one-shot prefill of the same prompt. The window is a FIXED [1, C]
    # shape — one executable per chunk size, forever.
    _, cache = model.apply(
        variables, window, cache=cache, cache_index=start,
        block_tables=bt_row[None],
    )
    return cache


def _copy_impl(cache, src, dst):
    # whole-block K/V copy (copy-on-write fork): dst becomes a private
    # duplicate the writer may overwrite from its divergence offset
    # onward. src/dst are [C] vectors so one executable serves every
    # fork.
    return [
        {kv: layer[kv].at[dst].set(layer[kv][src]) for kv in ("k", "v")}
        for layer in cache
    ]


_SHARED_JITS: dict[bool, tuple] = {}


def _shared_jits(donate: bool) -> tuple:
    """(tick, prefill, copy, spec_tick, chunk) jit objects, one set per
    donation mode. Donation keeps the pool + state slabs in place on
    real chips; the CPU backend ignores donation with a warning, so
    callers pass donate=False there. The spec tick specializes on the
    drafts array's [S, k] shape, so one executable serves a given
    (slots, k) forever — k is a config constant, never a retrace; the
    chunk jit likewise specializes on the [1, C] window, one executable
    per chunk size."""
    if donate not in _SHARED_JITS:
        _SHARED_JITS[donate] = (
            jax.jit(_tick_impl, static_argnums=(0, 1, 2),
                    donate_argnums=(4, 5) if donate else ()),
            jax.jit(_prefill_impl, static_argnums=(0, 1),
                    donate_argnums=(3, 4) if donate else ()),
            jax.jit(_copy_impl,
                    donate_argnums=(0,) if donate else ()),
            jax.jit(_spec_tick_impl, static_argnums=(0, 1, 2),
                    donate_argnums=(4, 5) if donate else ()),
            jax.jit(_chunk_impl, static_argnums=(0,),
                    donate_argnums=(2,) if donate else ()),
        )
    return _SHARED_JITS[donate]


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    slots: int = 4                 # S: concurrent requests in flight
    max_len: int = 0               # L: per-slot logical length (0 = model max)
    eos_id: int | None = None
    pad_id: int = 0
    queue_capacity: int = 64
    prefill_budget: int = 512      # prompt tokens admitted per round
    min_bucket: int = 8            # smallest prefill padding bucket
    snapshot_every: int = _SNAPSHOT_EVERY
    # ---- paged cache ----
    block_size: int = 16           # tokens per KV block
    num_blocks: int = 0            # pool size incl. null block (0 = auto:
    #                                slots * ceil(L/bs) + 1, the slab equivalent)
    prefix_cache: bool = True      # radix prefix reuse on/off
    # ---- tiered KV (serve/hostcache.py) ----
    # > 0 enables the host-RAM spill tier: radix eviction demotes cold
    # prefix chains to host numpy buffers under this LRU budget, and a
    # later same-prefix admission restores them with one H2D scatter
    # per block instead of a re-prefill. Needs the prefix cache on.
    host_cache_mb: int = 0
    # where the store serializes on drain (empty = no persistence):
    # a spilled chain outlives the process, riding the journal's
    # recovery path — restart between evict and rehit still restores
    host_cache_dir: str = ""
    # "reserve": a request only admits when its WORST-CASE block demand
    # (prompt + full budget, minus shared prefix) is covered — pool
    # exhaustion is impossible by accounting. "optimistic": admit on
    # prompt-fit only, oversubscribe the growth, and preempt-to-queue
    # when the pool runs dry (vLLM's default posture; higher occupancy,
    # tail-latency risk under pathological growth).
    admission: str = "reserve"
    # ---- speculative decoding (serve/draft.py) ----
    # spec_k > 0 with a draft source turns each decode tick into a
    # draft/verify/accept round emitting 1..spec_k+1 tokens per slot;
    # temp-0 output stays bit-identical to sequential decode (the
    # accept rule only keeps tokens the target would have produced)
    spec_k: int = 0                # draft tokens per slot per tick (0 = off)
    draft: str = "off"             # "ngram" (self-drafting) | "off"
    # ---- SLO classes + chunked prefill (workload isolation) ----
    # prompts whose uncached suffix exceeds `prefill_chunk` prefill in
    # fixed [1, chunk] segments interleaved with decode ticks (one
    # segment per step) — co-running slots' TTFT stops spiking on
    # long-prompt admission, at one extra executable total
    prefill_chunk: int = 0         # 0 = one-shot prefill (off)
    interactive_weight: int = 3    # weighted-fair picks per pattern round
    batch_weight: int = 1
    batch_capacity: int = 0        # batch queue depth cap (0 = shared cap)
    batch_deadline_s: float = 0.0  # default batch deadline (0 = none) —
    #                                what makes batch sheddable under
    #                                brownout when clients state no SLO
    # ---- overload brownout (serve/queue.py:BrownoutGovernor) ----
    brownout: bool = False         # enable the governor
    brownout_depth: int = 0        # enter watermark (0 = 3/4 of capacity)
    brownout_wait_s: float = 0.0   # queue-wait p95 enter watermark (0 = off)
    brownout_clamp: int = 0        # clamp max_new_tokens while active (0 = off)
    # ---- SLO burn-rate alerting (obs/slo.py) — 0 = that target off ----
    slo_ttft_p99_ms: float = 0.0   # windowed TTFT p99 must stay under this
    slo_reject_rate: float = 0.0   # windowed reject fraction budget
    slo_availability: float = 0.0  # windowed completed/(completed+failed) floor
    slo_fast_s: float = 0.0        # fast burn window (0 = obs/slo default 60s)
    slo_slow_s: float = 0.0        # slow burn window (0 = obs/slo default 600s)
    # ---- introspection (obs/ledger.py, obs/tickprof.py) ----
    # opt-in AOT cost_analysis at warmup: `lower().compile()` compiles
    # AGAIN outside the jit cache — real wall time bench pays once per
    # round but the test suite must not pay hundreds of times
    ledger_costs: bool = False


@dataclasses.dataclass
class TokenEvent:
    """One emission the host routes to a transport/test."""
    request: Request
    token: int | None              # None for reject/timeout events
    finished: bool
    kind: str = "token"            # token | rejected | timed_out
    reason: str | None = None


def _tr(req) -> dict:
    """The request's fleet hop context as event attrs. Every
    request-scoped event splats this so a router-dispatched request's
    replica-side lifecycle joins the fleet trace by id; {} for direct
    clients, so local-only runs pay zero extra bytes."""
    trace = getattr(req, "trace", None)
    return {"trace": trace} if trace else {}


class Engine:
    """Continuous-batching engine over one model + one variables tree.

    Host-side it owns the slot table (slot index -> Request), block
    manager + radix cache, the admission queue, metrics, and telemetry;
    device-side the `[num_blocks, block_size]` KV pool and the [S]
    state rows. `step()` is one scheduling round (admit -> ensure
    blocks -> tick -> route); `run()` loops it."""

    def __init__(
        self,
        model: Any,
        variables: dict,
        cfg: EngineConfig,
        *,
        metrics: ServeMetrics | None = None,
        tracer=None,
        heartbeat=None,
        chaos=None,
        journal=None,
        on_event: Callable[[TokenEvent], Any] | None = None,
        flight_path=None,
    ):
        from hyperion_tpu.models.llama import (
            init_paged_cache,
            paged_cache_block_bytes,
        )
        from hyperion_tpu.obs import heartbeat as hb_mod
        from hyperion_tpu.obs import trace as trace_mod

        self.model = model
        self.variables = variables
        mcfg = model.cfg
        L = cfg.max_len or mcfg.max_len
        if L > mcfg.max_len:
            raise ValueError(
                f"engine max_len {L} exceeds model max_len {mcfg.max_len}")
        if cfg.admission not in ("reserve", "optimistic"):
            raise ValueError(f"admission must be 'reserve' or 'optimistic', "
                             f"got {cfg.admission!r}")
        if cfg.draft not in ("off", "ngram"):
            raise ValueError(
                f"draft must be 'off' or 'ngram', got {cfg.draft!r}")
        if cfg.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {cfg.spec_k}")
        # speculation needs both a window (spec_k) and a proposer
        # (draft): either alone leaves the sequential tick in charge
        self._spec = cfg.spec_k > 0 and cfg.draft != "off"
        self._drafter: DraftSource | None = \
            NgramDraft() if self._spec else None
        bs = cfg.block_size
        self._mb = blocks_for(L, bs)          # block-table width per slot
        num_blocks = cfg.num_blocks or cfg.slots * self._mb + 1
        if num_blocks < self._mb + 1:
            raise ValueError(
                f"num_blocks {num_blocks} cannot hold one worst-case "
                f"request ({self._mb} blocks + the null block); raise "
                f"--num-blocks or --block-size")
        if cfg.prefill_chunk < 0 or cfg.prefill_chunk > L:
            raise ValueError(
                f"prefill_chunk must be in [0, max_len={L}], "
                f"got {cfg.prefill_chunk}")
        self.cfg = dataclasses.replace(cfg, max_len=L, num_blocks=num_blocks)
        self.queue = AdmissionQueue(
            cfg.queue_capacity, max_total_tokens=L,
            prefill_budget=cfg.prefill_budget,
            class_weights={CLASS_INTERACTIVE: cfg.interactive_weight,
                           CLASS_BATCH: cfg.batch_weight},
            class_capacity={CLASS_BATCH: cfg.batch_capacity}
            if cfg.batch_capacity else None,
            class_deadline_s={CLASS_BATCH: cfg.batch_deadline_s}
            if cfg.batch_deadline_s else None,
        )
        # router-ordered batch-class brownout (the `class_brownout`
        # control verb): batch sheds/clamps as under the local governor,
        # but interactive is NEVER touched — the order says "this
        # replica is someone's overflow valve", not "this replica is
        # drowning". Written by the exporter thread, read by the engine
        # thread; a bool flip is atomic under the GIL.
        self._class_brownout = False
        # chunked-prefill slots: slot -> {req, prompt, budget, pos,
        # row, resumed}. While a slot chunks, its real block-table row
        # is held HERE and the device row stays zeroed: the decode tick
        # writes K/V at lengths[slot] for every lane regardless of the
        # live mask, and stale state in a reused slot must null-route,
        # not corrupt the prompt's blocks mid-prefill.
        self._chunking: dict[int, dict] = {}
        self.metrics = metrics or ServeMetrics()
        self.tracer = tracer if tracer is not None else trace_mod.null_tracer()
        self.hb = heartbeat if heartbeat is not None \
            else hb_mod.null_heartbeat()
        self.chaos = chaos
        self.on_event = on_event
        # crash-safety + overload state (PR 8)
        self.journal = journal
        self._journal_err_reported = False
        self._draining = False
        self._drain_deadline: float | None = None
        self._unparsed = itertools.count()
        self._governor: BrownoutGovernor | None = None
        if cfg.brownout:
            depth_high = cfg.brownout_depth or max(
                1, (3 * cfg.queue_capacity) // 4)
            self._governor = BrownoutGovernor(
                depth_high=depth_high, wait_high_s=cfg.brownout_wait_s)
        # SLO burn-rate alerting (obs/slo.py): evaluated from the
        # serve loop (step AND idle ticks — an alert must be able to
        # clear while the engine sits idle after load drops) over the
        # windowed instruments the metrics layer already keeps.
        self.slo = None
        targets = slo_mod.standard_targets(
            cfg.slo_ttft_p99_ms, cfg.slo_reject_rate,
            cfg.slo_availability)
        if targets:
            self.slo = slo_mod.SLOMonitor(
                targets, self.metrics.reg,
                fast_s=cfg.slo_fast_s or slo_mod.DEFAULT_FAST_S,
                slow_s=cfg.slo_slow_s or slo_mod.DEFAULT_SLOW_S)
        self._slots: list[Request | None] = [None] * cfg.slots
        self._seqs: list[SeqAlloc | None] = [None] * cfg.slots
        self.mgr = BlockManager(num_blocks, bs)
        self.prefix = RadixPrefixCache(self.mgr) if cfg.prefix_cache else None
        # tiered KV: the host-RAM spill tier behind the radix cache
        # (serve/hostcache.py) — eviction demotes, admission restores
        self.host: HostBlockStore | None = None
        self._hot_roots = HotRootTracker()
        if cfg.host_cache_mb > 0 and self.prefix is not None:
            self.host = HostBlockStore(cfg.host_cache_mb, bs)
            self.prefix.spill = self._spill_block
            if cfg.host_cache_dir:
                n_loaded = self.host.load(cfg.host_cache_dir)
                if n_loaded:
                    self.tracer.event(
                        "hostcache_loaded", chains=n_loaded,
                        mb=round(self.host.occupancy_mb, 3),
                        path=cfg.host_cache_dir)
            # publish occupancy from tick zero: `obs top` renders a
            # null gauge as tier-DISABLED, and an enabled-but-cold
            # tier must read 0.00/0M instead
            self.metrics.observe_host_cache(
                self.host.occupancy_mb, len(self.host))
        self._bt = np.zeros((cfg.slots, self._mb), np.int32)
        self._bt_dev = None   # device mirror of (_bt, live); None = stale
        self._pending_reserve: dict[str, int] = {}
        self._order = itertools.count()
        self._block_bytes = paged_cache_block_bytes(mcfg, bs)
        self._cache = init_paged_cache(mcfg, num_blocks, bs)
        self._state = self._init_state()
        self._tick_no = 0
        # cumulative transport-sink seconds (all requests); per-request
        # marks against this counter net decode gaps of EVERY sink
        # write in the window, not just the request's own — a slow
        # neighbour's client must not read as this slot's decode time
        self._sink_s = 0.0
        # introspection plane: compile ledger + host-tick profiler +
        # flight recorder (all host-only — none touch the device)
        self.ledger = CompileLedger()
        self.tickprof = TickProfiler()
        self.flight = (FlightRecorder(flight_path, run=self.tracer.run)
                       if flight_path else null_flight_recorder())
        self._journal_s = 0.0     # cumulative journal seconds (see _sink_s)
        self._bt_upload_s = 0.0   # cumulative block-table upload seconds
        self._last_prefill_bucket: int | None = None
        # `.nbytes` is shape metadata — summing it syncs nothing
        self._param_bytes = int(sum(
            getattr(x, "nbytes", 0)
            for x in jax.tree_util.tree_leaves(variables)))
        (self._tick_jit, self._prefill_jit, self._copy_jit,
         self._spec_jit, self._chunk_jit) = _shared_jits(
            donate=jax.default_backend() != "cpu")

    # ------------------------------------------------------ device state

    def _init_state(self) -> dict:
        S = self.cfg.slots
        return {
            "lengths": jnp.zeros((S,), jnp.int32),
            "active": jnp.zeros((S,), bool),
            "last_token": jnp.zeros((S,), jnp.int32),
            "generated": jnp.zeros((S,), jnp.int32),
            "budget": jnp.ones((S,), jnp.int32),
            "temperature": jnp.zeros((S,), jnp.float32),
            "top_k": jnp.zeros((S,), jnp.int32),
            "top_p": jnp.ones((S,), jnp.float32),
            "keys": jax.random.split(jax.random.key(0), S),
        }

    # --------------------------------------------------------- plumbing

    def bucket(self, prompt_len: int) -> int:
        """Smallest power-of-two >= prompt_len (floored at min_bucket,
        capped at max_len): the prefill jit compiles once per value
        this returns."""
        b = self.cfg.min_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.cfg.max_len)

    def compile_stats(self) -> dict:
        """Executable counts in the three jit caches — the no-recompile
        guarantee made measurable (tier-1 asserts these stay flat
        across slot churn, prefix hits, COW forks, and preemptions
        after `warmup`). The caches are PROCESS-wide (`_shared_jits`):
        engines over the same model and shapes share executables, so a
        second engine's warmup is free — counts only ever grow, and
        flatness between two readings still means "nothing traced"."""
        return {
            "tick_executables": self._tick_jit._cache_size(),
            "prefill_executables": self._prefill_jit._cache_size(),
            "copy_executables": self._copy_jit._cache_size(),
            "spec_tick_executables": self._spec_jit._cache_size(),
            "chunk_executables": self._chunk_jit._cache_size(),
        }

    def warmup(self, prompt_lens: list[int] | None = None) -> dict:
        """Compile the tick, the COW block copy, and one prefill per
        bucket, then reset serving state. The ladder covers EVERY
        bucket at or below the largest reachable suffix, not just the
        requested lengths: a prefix-cache hit shrinks a prompt to its
        suffix, which may land in any smaller bucket, and a hit must
        never cost a compile. Under `optimistic` admission the ladder
        extends all the way to max_len regardless of `prompt_lens`,
        because a pool-exhaustion preemption GROWS the prompt (the
        resume re-prefills prompt + generated) — O(log max_len)
        compiles, paid once. Under `reserve` admission nothing ever
        grows (the only requeue path fires before a token exists), so
        `prompt_lens` bounds the ladder."""
        want = self.bucket(max(prompt_lens or [self.cfg.min_bucket]))
        if self.cfg.admission == "optimistic":
            want = self.cfg.max_len
        if self.cfg.prefill_chunk > 0:
            # chunking caps every sampling prefill at the final segment
            # (suffix <= chunk), so the ladder stops at bucket(chunk)
            # no matter how long prompts get — resume growth under
            # optimistic admission included (a grown prompt just chunks
            # more segments)
            want = self.bucket(self.cfg.prefill_chunk)
        lens: list[int] = []
        b = self.cfg.min_bucket
        while True:
            pb = min(b, self.cfg.max_len)
            if pb not in lens:
                lens.append(pb)
            if pb >= want:
                break
            b *= 2
        compile_s: dict[str, float] = {}
        with self.tracer.span("serve_warmup") as sp:
            for pb in lens:
                dummy = Request(prompt_ids=np.ones((min(pb, 2),), np.int32),
                                max_new_tokens=2)
                # bt row is all-null during warmup: the dummy's writes
                # land in the garbage block, real state is untouched
                t0 = time.perf_counter()
                self._prefill_call(dummy, slot=0, bucket_len=pb)
                compile_s[f"prefill_b{pb}"] = round(
                    time.perf_counter() - t0, 4)
            t0 = time.perf_counter()
            _ = self._tick_device()
            compile_s["tick"] = round(time.perf_counter() - t0, 4)
            if self._spec:
                # the spec tick's one executable for this (S, k) —
                # all-zero drafts exercise the same shapes live
                # traffic will (acceptance is data, not shape)
                t0 = time.perf_counter()
                _ = self._spec_tick_device(
                    np.zeros((self.cfg.slots, self.cfg.spec_k), np.int32))
                compile_s["spec_tick"] = round(time.perf_counter() - t0, 4)
            if self.cfg.prefill_chunk > 0:
                # the chunk jit's ONE executable for this [1, C] window
                # — all-null bt row, so the dummy's K/V land in the
                # garbage block
                C = self.cfg.prefill_chunk
                t0 = time.perf_counter()
                self._cache = self._chunk_jit(
                    self.model, self.variables, self._cache,
                    jnp.full((1, C), self.cfg.pad_id, jnp.int32),
                    jnp.zeros((self._mb,), jnp.int32), jnp.int32(0))
                compile_s["chunk"] = round(time.perf_counter() - t0, 4)
            zero = jnp.zeros((1,), jnp.int32)
            t0 = time.perf_counter()
            self._cache = self._copy_jit(self._cache, zero, zero)
            compile_s["copy"] = round(time.perf_counter() - t0, 4)
            costs = self._warmup_costs() if self.cfg.ledger_costs else None
            sp.set(buckets=lens)
        self._state = self._init_state()
        self._slots = [None] * self.cfg.slots
        self._seqs = [None] * self.cfg.slots
        self._chunking = {}
        self._bt[:] = 0
        self._bt_dev = None
        stats = self.compile_stats()
        total_s = round(sp.dur_s or 0.0, 4)
        self.ledger.record_warmup(stats, compile_s=compile_s, costs=costs,
                                  total_s=total_s)
        self.ledger.set_baseline(stats)
        self.tracer.event("serve_warmup_done", **stats)
        self.tracer.event("compile_ledger", total_s=total_s,
                          compile_s=compile_s, costs=costs or {}, **stats)
        return stats

    def _warmup_costs(self) -> dict:
        """Opt-in AOT `cost_analysis()` of the decode-tick executable —
        FLOPs/bytes per tick for the ledger. `lower().compile()` builds
        a SECOND executable outside the jit call cache (doesn't grow
        `compile_stats()`, but costs real compile wall time), hence the
        `ledger_costs` gate: bench pays it once per round, tests never."""
        from hyperion_tpu.obs.registry import compiled_cost
        live = np.fromiter((r is not None for r in self._slots),
                           bool, len(self._slots))
        cost = compiled_cost(
            self._tick_jit, self.model, self.cfg.eos_id, self.cfg.pad_id,
            self.variables, self._cache, self._state,
            jnp.asarray(self._bt), jnp.asarray(live))
        return {f"tick_{k}": v for k, v in (cost or {}).items()}

    def _prefill_call(self, req: Request, slot: int, *, start: int = 0,
                      prompt: np.ndarray | None = None,
                      budget: int | None = None,
                      bucket_len: int | None = None):
        ids = req.prompt_ids if prompt is None else prompt
        suffix = ids[start:]
        P = int(suffix.shape[0])
        Pb = bucket_len or self.bucket(P)
        self._last_prefill_bucket = Pb   # churn context for the ledger
        buf = np.full((1, Pb), self.cfg.pad_id, np.int32)
        buf[0, :P] = suffix
        self._cache, self._state, first, finished = self._prefill_jit(
            self.model, self.cfg.eos_id,
            self.variables, self._cache, self._state,
            jnp.asarray(buf), jnp.asarray(self._bt[slot]),
            jnp.int32(slot), jnp.int32(start), jnp.int32(P),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jnp.float32(req.top_p),
            jnp.int32(req.max_new_tokens if budget is None else budget),
            jax.random.key(req.seed),
        )
        return int(first), bool(finished)

    def _live_mask(self) -> np.ndarray:
        """Slots the decode tick may advance: occupied AND not mid-
        chunk. A chunking slot's device row is zeroed and its state
        rows are a previous occupant's leftovers — the mask (plus the
        zeroed row, belt and braces) keeps the tick from decoding
        garbage into it."""
        return np.fromiter(
            (r is not None and s not in self._chunking
             for s, r in enumerate(self._slots)),
            bool, len(self._slots))

    def _tick_device(self):
        if self._bt_dev is None:
            # upload only when the table or slot liveness changed —
            # steady-state decode re-uses the device copies, so a tick
            # costs zero host->device traffic
            t0u = _CLOCK()
            self._bt_dev = (jnp.asarray(self._bt),
                            jnp.asarray(self._live_mask()))
            self._bt_upload_s += _CLOCK() - t0u
        self._cache, self._state, toks, fins = self._tick_jit(
            self.model, self.cfg.eos_id, self.cfg.pad_id,
            self.variables, self._cache, self._state, *self._bt_dev)
        # the host fetch is the fence: tick spans time real work
        return np.asarray(toks), np.asarray(fins)

    def _collect_drafts(self) -> np.ndarray:
        """[S, spec_k] proposals for this tick, one drafter call per
        live slot over its visible context — host-side only, shipped
        with the tick like the block table. Dead lanes stay zero (the
        tick masks them out anyway)."""
        k = self.cfg.spec_k
        drafts = np.zeros((self.cfg.slots, k), np.int32)
        for s, req in enumerate(self._slots):
            if req is not None and s not in self._chunking:
                drafts[s] = self._drafter.propose(
                    s, req.prompt_ids, req.tokens, k)
        return drafts

    def _spec_tick_device(self, drafts: np.ndarray):
        if self._bt_dev is None:
            t0u = _CLOCK()
            self._bt_dev = (jnp.asarray(self._bt),
                            jnp.asarray(self._live_mask()))
            self._bt_upload_s += _CLOCK() - t0u
        self._cache, self._state, out, cnt, acc, fins = self._spec_jit(
            self.model, self.cfg.eos_id, self.cfg.pad_id,
            self.variables, self._cache, self._state, *self._bt_dev,
            jnp.asarray(drafts))
        return (np.asarray(out), np.asarray(cnt), np.asarray(acc),
                np.asarray(fins))

    # --------------------------------------------------- block plumbing

    def _effective(self, req: Request) -> tuple[np.ndarray, int]:
        """(prompt, remaining budget) — preemption-aware: a preempted
        request resumes by prefilling prompt + everything it already
        generated (recompute preemption), which reproduces the exact
        decode state it lost."""
        if req.tokens:
            prompt = np.concatenate(
                [req.prompt_ids, np.asarray(req.tokens, np.int32)])
            return prompt, req.max_new_tokens - len(req.tokens)
        return req.prompt_ids, req.max_new_tokens

    def _block_demand(self, req: Request) -> int:
        """Exclusive new blocks this request needs — worst-case span
        under `reserve` admission, prompt-only under `optimistic` —
        net of blocks a radix hit would share."""
        prompt, budget = self._effective(req)
        P = int(prompt.shape[0])
        span = P + budget if self.cfg.admission == "reserve" else P
        need = blocks_for(span, self.cfg.block_size)
        if self.prefix is not None:
            need -= len(self.prefix.lookup(prompt, P - 1).blocks)
        return need

    def _can_admit(self, req: Request) -> bool:
        """Block-availability gate for the queue: pop only when the
        demand is covered by free + evictable-radix blocks, net of
        reservations already promised to in-flight requests. Covered
        demand is reserved immediately (released as real blocks are
        claimed), so one scheduling round cannot double-spend."""
        need = self._block_demand(req)
        evictable = self.prefix.evictable() if self.prefix else 0
        if need > self.mgr.num_free + evictable - self.mgr.reserved:
            return False
        self.mgr.reserve(need)
        self._pending_reserve[req.id] = need
        return True

    def _alloc(self, n: int, seq: SeqAlloc | None = None) -> list[int] | None:
        """Pool allocation with radix eviction backing; claims against
        `seq`'s reservation when it holds one."""
        blocks = self.mgr.alloc(n)
        if blocks is None and self.prefix is not None:
            freed = self.prefix.evict(n - self.mgr.num_free)
            if freed:
                self.metrics.on_evict(freed)
            blocks = self.mgr.alloc(n)
        if blocks is not None and seq is not None and seq.reserved:
            take = min(seq.reserved, n)
            seq.reserved -= take
            self.mgr.release(take)
        return blocks

    def _spill_block(self, chain_tokens: tuple[int, ...],
                     block: int) -> None:
        """Radix eviction's demotion hook (blocks.py `_drop`): read the
        dying block's K/V out of the device pool into one stacked host
        array and hand it to the host tier keyed by its full chain
        prefix. Eager per-layer D2H reads — none of the engine's
        tracked jits are involved, so `compile_stats()` stays flat."""
        payload = np.stack([
            np.stack([np.asarray(layer["k"][block]),
                      np.asarray(layer["v"][block])])
            for layer in self._cache])  # [L, 2, bs, H, D]
        if self.host.put(chain_tokens, payload):
            self.metrics.on_host_spill(payload.nbytes)
            self.metrics.observe_host_cache(
                self.host.occupancy_mb, len(self.host))

    def _restore_blocks(self, blocks: list[int],
                        payloads: list[np.ndarray]) -> int:
        """The promotion half: scatter spilled host payloads into
        freshly allocated device blocks — one device_put + `.at[].set`
        block-scatter per layer, eager (never a tracked jit), and the
        D2H/H2D round trip in the pool's own dtype is bit-exact, so a
        restored stream matches the never-evicted run. Returns bytes
        moved."""
        ids = jnp.asarray(np.asarray(blocks, np.int32))
        stacked = np.stack(payloads)  # [n, L, 2, bs, H, D]
        moved = int(stacked.nbytes)
        dev = jax.device_put(stacked)
        self._cache = [
            {"k": layer["k"].at[ids].set(dev[:, li, 0]),
             "v": layer["v"].at[ids].set(dev[:, li, 1])}
            for li, layer in enumerate(self._cache)
        ]
        return moved

    def _free_slot(self, slot: int) -> None:
        seq = self._seqs[slot]
        if seq is not None:
            self.mgr.release(seq.reserved)
            self.mgr.decref(seq.blocks)
        self._seqs[slot] = None
        self._slots[slot] = None
        self._chunking.pop(slot, None)
        self._bt[slot, :] = 0
        self._bt_dev = None

    def _admit(self, req: Request, slot: int) -> TokenEvent | None:
        """Prefill `req` into `slot` through the paged pool: radix
        lookup -> share/COW -> allocate exclusives -> prefill the
        suffix -> register prompt blocks. Returns the first-token
        event, or None when allocation lost a race (caller requeues)."""
        reserve = self._pending_reserve.pop(req.id, 0)
        prompt, budget = self._effective(req)
        P = int(prompt.shape[0])
        bs = self.cfg.block_size
        shared: list[int] = []
        cow_src: int | None = None
        start = 0
        host_payloads: list[np.ndarray] = []
        device_start = 0
        if self.prefix is not None:
            m = self.prefix.lookup(prompt, P - 1)
            shared, start, cow_src = m.blocks, m.tokens, m.cow_src
            device_start = start
            if self.host is not None:
                # device-miss -> host-hit fall-through: probe the host
                # tier for full-block chain links beyond the device
                # match. A host extension only wins when it covers MORE
                # than the device walk (its mid-block COW extension
                # included) — then the restore supersedes the COW copy.
                base = len(shared) * bs
                host_payloads = self.host.match(prompt, base, P - 1)
                if host_payloads \
                        and base + len(host_payloads) * bs > start:
                    start = base + len(host_payloads) * bs
                    cow_src = None
                else:
                    host_payloads = []
        need_now = blocks_for(P, bs) - len(shared)
        # pin the matched chain (and the COW source) BEFORE allocating:
        # allocation may evict radix holds, and a trie-only block we
        # just matched is exactly what LRU eviction would pick off
        pin = shared + ([cow_src] if cow_src is not None else [])
        self.mgr.incref(pin)
        fresh = self._alloc(need_now) if need_now else []
        if fresh is None:
            self.mgr.decref(pin)
            self.mgr.release(reserve)
            return None
        # Re-derive the growth reservation instead of netting the
        # gate's estimate against need_now: an earlier admission this
        # round may have evicted blocks the gate counted as shared, and
        # growth demand — blocks_for(P+budget) - blocks_for(P) — does
        # not depend on sharing at all, so computing it directly keeps
        # the reserve-mode "exhaustion impossible" ledger exact even
        # when the gate's sharing estimate went stale.
        self.mgr.release(reserve)
        growth = 0
        if self.cfg.admission == "reserve":
            growth = blocks_for(P + budget, bs) - blocks_for(P, bs)
            self.mgr.reserve(growth)
        seq = SeqAlloc(
            blocks=shared + fresh, n_shared=len(shared),
            reserved=growth, order=next(self._order),
        )
        if cow_src is not None:
            # mid-block divergence: duplicate the agreeing block so our
            # writes (suffix prefill + decode) never touch the shared
            # original — the copy-on-write half of the design
            idx = jnp.asarray([cow_src], jnp.int32)
            self._cache = self._copy_jit(
                self._cache, idx, jnp.asarray([fresh[0]], jnp.int32))
            self.mgr.decref([cow_src])  # the pin; the copy is ours now
            self.metrics.on_cow()
        if host_payloads:
            # promote the matched chain out of the host tier: the first
            # len(host_payloads) fresh blocks are exactly the logical
            # positions after the device-shared span, so the scatter
            # lands them where the block table will address them. The
            # post-prefill `prefix.insert` re-registers the whole chain
            # (restored blocks included) in the radix, so the prefix is
            # device-cached again for the next sharer.
            moved = self._restore_blocks(
                fresh[:len(host_payloads)], host_payloads)
            host_tokens = len(host_payloads) * bs
            self.metrics.on_host_restore(len(host_payloads), moved)
            self.metrics.observe_host_cache(
                self.host.occupancy_mb, len(self.host))
            self.tracer.event(
                "host_restore", request=req.id, tick=self._tick_no,
                blocks=len(host_payloads), tokens=host_tokens,
                bytes=moved, **_tr(req))
        if self.prefix is not None:
            self.metrics.on_prefix_lookup(P, start)
            # tier attribution: under a host hit the device's share is
            # the full-block walk (the superseded COW extension never
            # ran), so device + host sum to exactly `start`
            self.metrics.on_tier_lookup(
                device_tokens=len(shared) * bs if host_payloads
                else device_start,
                host_tokens=len(host_payloads) * bs)
            self._hot_roots.note(prefix_root_digest(prompt))
        resumed = req.first_token_at is not None
        C = self.cfg.prefill_chunk
        if C > 0 and P - start > C:
            # chunked prefill: the suffix is too long for one segment.
            # Claim the slot and its blocks NOW (the gate already
            # reserved them), but hold the real block-table row ASIDE
            # and keep the device row zeroed — the decode tick writes
            # K/V at lengths[slot] for ALL lanes and this slot's device
            # state still belongs to a previous occupant, so its writes
            # must null-route until the final segment installs real
            # state. `_advance_chunks` runs one [1, C] segment per step
            # between decode ticks; the prefix is NOT registered in the
            # radix until the blocks actually hold it.
            row = np.zeros((self._mb,), np.int32)
            row[:len(seq.blocks)] = seq.blocks
            self._bt[slot, :] = 0
            self._bt_dev = None
            seq.n_filled = start
            self._slots[slot] = req
            self._seqs[slot] = seq
            self._chunking[slot] = {
                "req": req, "prompt": prompt, "budget": budget,
                "pos": start, "row": row, "resumed": resumed,
            }
            self.tracer.event(
                "prefill_chunked", request=req.id, tick=self._tick_no,
                slot=slot, prompt_len=P, cached_tokens=start, chunk=C,
                segments=-(-(P - start) // C), resumed=resumed,
                **_tr(req))
            return _CHUNK_ADMIT
        self._bt[slot, :len(seq.blocks)] = seq.blocks
        self._bt[slot, len(seq.blocks):] = 0
        self._bt_dev = None
        with self.tracer.span("serve_prefill", step=self._tick_no) as sp:
            first, finished = self._prefill_call(
                req, slot, start=start, prompt=prompt, budget=budget)
            sp.set(request=req.id, slot=slot, prompt_len=P,
                   cached_tokens=start, bucket=self.bucket(P - start),
                   resumed=resumed)
        seq.n_filled = P
        if self.prefix is not None:
            self.prefix.insert(prompt, seq.blocks)
        now = _CLOCK()
        req.prefilled_at = now
        if resumed:
            # a resume re-prefills prompt + generated: pure replay cost
            req.replay_s += sp.dur_s or 0.0
        else:
            req.prefill_s += sp.dur_s or 0.0
        if not resumed:
            req.first_token_at = now
            self.metrics.on_first_token(req, now)
            self.tracer.event(
                "request_first_token", request=req.id, tick=self._tick_no,
                ttft_s=round(now - req.submitted_at, 6),
                queue_wait_s=round(req.queue_wait_s, 6),
                gate_wait_s=round(req.gate_wait_s, 6),
                prefill_s=round(req.prefill_s, 6), **_tr(req))
        else:
            gap_from = getattr(req, "_last_emit_at", None)
            if gap_from is not None:
                self.metrics.on_token_gap(now - gap_from, req.sla_class)
        req._last_emit_at = now
        req._sink_mark = self._sink_s
        self.metrics.count_tokens(1)  # the prefill-sampled token
        self._slots[slot] = req
        self._seqs[slot] = seq
        if finished:
            self._free_slot(slot)
        return TokenEvent(req, first, finished)

    def _advance_chunks(self) -> list[TokenEvent]:
        """Run at most ONE prefill segment this step — the oldest
        chunking slot's — so long prompts interleave with decode ticks
        instead of stalling them (Sarathi-Serve's stall-free schedule).
        Intermediate segments go through the chunk jit (K/V only, no
        sampling); the final segment (suffix <= chunk, so its bucket is
        already on the warmup ladder) runs the normal sampling prefill
        with `start` at the chunk boundary — the fold position is the
        total prompt length - 1 either way, so the first token is
        bit-identical to a one-shot prefill."""
        if not self._chunking:
            return []
        C = self.cfg.prefill_chunk
        slot = min(self._chunking, key=lambda s: self._seqs[s].order)
        ck = self._chunking[slot]
        req, prompt, budget = ck["req"], ck["prompt"], ck["budget"]
        P = int(prompt.shape[0])
        pos = ck["pos"]
        if P - pos > C:
            t0 = _CLOCK()
            self._cache = self._chunk_jit(
                self.model, self.variables, self._cache,
                jnp.asarray(np.asarray(prompt[pos:pos + C],
                                       np.int32)[None, :]),
                jnp.asarray(ck["row"]), jnp.int32(pos))
            # fence: the segment's wall time must land in THIS step's
            # chunk segment, not smear into the next device call
            jax.block_until_ready(self._cache)
            dt = _CLOCK() - t0
            if ck["resumed"]:
                req.replay_s += dt
            else:
                req.prefill_s += dt
            ck["pos"] = pos + C
            self._seqs[slot].n_filled = pos + C
            return []
        # final segment: install the real row — `_prefill_impl` sets
        # every state field for this slot via `.at[slot].set`, so the
        # stale-lane hazard ends here
        self._bt[slot, :] = ck["row"]
        self._bt_dev = None
        del self._chunking[slot]
        resumed = ck["resumed"]
        with self.tracer.span("serve_prefill", step=self._tick_no) as sp:
            first, finished = self._prefill_call(
                req, slot, start=pos, prompt=prompt, budget=budget)
            sp.set(request=req.id, slot=slot, prompt_len=P,
                   cached_tokens=pos, bucket=self.bucket(P - pos),
                   resumed=resumed, chunked=True)
        seq = self._seqs[slot]
        seq.n_filled = P
        if self.prefix is not None:
            self.prefix.insert(prompt, seq.blocks)
        now = _CLOCK()
        req.prefilled_at = now
        if resumed:
            req.replay_s += sp.dur_s or 0.0
        else:
            req.prefill_s += sp.dur_s or 0.0
        if not resumed:
            req.first_token_at = now
            self.metrics.on_first_token(req, now)
            self.tracer.event(
                "request_first_token", request=req.id, tick=self._tick_no,
                ttft_s=round(now - req.submitted_at, 6),
                queue_wait_s=round(req.queue_wait_s, 6),
                gate_wait_s=round(req.gate_wait_s, 6),
                prefill_s=round(req.prefill_s, 6), chunked=True,
                **_tr(req))
        else:
            gap_from = getattr(req, "_last_emit_at", None)
            if gap_from is not None:
                self.metrics.on_token_gap(now - gap_from, req.sla_class)
        req._last_emit_at = now
        req._sink_mark = self._sink_s
        self.metrics.count_tokens(1)  # the prefill-sampled token
        if finished:
            self._free_slot(slot)
        return [TokenEvent(req, first, finished)]

    def _preempt(self, slot: int, reason: str = "pool_exhausted") -> None:
        """Push this slot's request back to the queue HEAD (recompute
        preemption — generated tokens ride along and re-prefill on
        re-admission, often from their own radix-cached prefix). Fires
        on pool exhaustion and on preempt-batch-for-interactive (a
        block-gated interactive head evicting the youngest batch slot).
        The degraded-but-alive alternative to a crash."""
        req = self._slots[slot]
        self._free_slot(slot)
        self.metrics.on_preempt()
        req.preempts += 1
        req._preempted = True  # its next queue wait is replay, not FIFO
        self.tracer.event("request_preempted", request=req.id,
                          generated=len(req.tokens), tick=self._tick_no,
                          reason=reason, sla_class=req.sla_class,
                          **_tr(req))
        self.queue.push_front(req)

    def _account_pop(self, req) -> bool:
        """Bank the queue wait that ended at this pop into its
        attribution bucket: replay wait when the pop resumes a
        preemption, otherwise FIFO wait with the block-gated tail
        (stamped by `pop_ready` at the first denial) broken out.
        Returns whether this pop was a preemption resume, so a caller
        that requeues the request (allocation race) can restore the
        flag — the request is STILL a resume and its next wait must
        bank as replay, not FIFO queue_wait."""
        popped = (req.admitted_at if req.admitted_at is not None
                  else _CLOCK())
        wait = max(0.0, popped - req.enqueued_at)
        gate = 0.0
        if req.gate_blocked_at is not None:
            gate = min(wait, max(0.0, popped - req.gate_blocked_at))
            req.gate_blocked_at = None
        resumed = req._preempted
        if resumed:
            req._preempted = False
            req.replay_s += wait
        else:
            req.gate_wait_s += gate
            req.queue_wait_s += wait - gate
        if self._governor is not None:
            # every completed wait (replay stints included — congestion
            # is congestion) feeds the brownout p95 window, tagged with
            # its class so shed_doomed can estimate per-class
            self._governor.observe_wait(wait, req.sla_class)
        self.tracer.event(
            "request_scheduled", request=req.id, tick=self._tick_no,
            resumed=resumed,
            queue_wait_s=round(0.0 if resumed else wait - gate, 6),
            gate_wait_s=round(0.0 if resumed else gate, 6),
            replay_wait_s=round(wait if resumed else 0.0, 6),
            **_tr(req))
        return resumed

    def _ensure_blocks(self) -> None:
        """Before a tick, every live slot must own the block its next
        write lands in. Allocate (evicting radix holds as needed);
        when the pool is truly dry, preempt the YOUNGEST slot and
        retry — oldest requests always progress, so the loop
        terminates and nobody starves."""
        for s in sorted(
            (t for t in range(self.cfg.slots) if self._slots[t] is not None),
            key=lambda t: self._seqs[t].order,
        ):
            while self._slots[s] is not None:
                seq = self._seqs[s]
                lookahead = 0
                if self._spec:
                    # the verify window writes positions n_filled ..
                    # n_filled+k, but only positions an ACCEPTED token
                    # can land in need real blocks (acceptance is
                    # capped by the remaining budget; writes past the
                    # table's chain null-route harmlessly) — so the
                    # lookahead never exceeds the worst-case span the
                    # reserve-mode ledger already accounts for
                    req = self._slots[s]
                    lookahead = max(0, min(
                        self.cfg.spec_k,
                        req.max_new_tokens - len(req.tokens) - 1))
                needed = (seq.n_filled + lookahead) \
                    // self.cfg.block_size + 1
                if len(seq.blocks) >= needed:
                    break
                got = self._alloc(1, seq)
                if got is not None:
                    self._bt[s, len(seq.blocks)] = got[0]
                    seq.blocks.append(got[0])
                    self._bt_dev = None
                    continue
                live = [t for t in range(self.cfg.slots)
                        if self._slots[t] is not None]
                # batch absorbs pool pressure first: evict the
                # youngest batch slot when one exists, the youngest
                # overall otherwise (the starvation-freedom argument —
                # oldest always progresses — is unchanged either way)
                batch = [t for t in live
                         if self._slots[t].sla_class == CLASS_BATCH]
                victim = max(batch or live,
                             key=lambda t: self._seqs[t].order)
                self._preempt(victim)

    # ------------------------------------------------------------ events

    def _journal_guard(self) -> None:
        """Surface a journal IO failure exactly once: the engine keeps
        serving (durability degraded beats dead), but the stream and
        the counters must say so — a silent WAL loss would read as
        crash-safe right up to the crash."""
        j = self.journal
        if j is not None and not j.enabled and not self._journal_err_reported:
            self._journal_err_reported = True
            self.metrics.on_journal_error()
            self.tracer.event("journal_io_error", error=j.error)
            print(f"[serve] journal disabled after IO error: {j.error} — "
                  "serving continues WITHOUT crash recovery",
                  file=sys.stderr)

    def _emit(self, ev: TokenEvent) -> None:
        req = ev.request
        if ev.kind == "token" and ev.token is not None:
            req.tokens.append(ev.token)
        if ev.finished and ev.kind == "token":
            req.status = "done"
        # Journal BEFORE the sink write, flushed to the kernel inside
        # `token`/`finish` (serve/journal.py's ordering contract): any
        # token a client ever received is already durable, so a replay
        # can never re-compute — hence never re-deliver — it. The
        # client stream stays duplicate-free across kills.
        if self.journal is not None and req._journaled:
            jt0 = _CLOCK()
            if ev.kind == "token" and ev.token is not None:
                self.journal.token(req.id, ev.token)
            if ev.finished:
                self.journal.finish(
                    req.id,
                    "done" if ev.kind in ("token", "done")
                    else (ev.reason or ev.kind))
            if ev.kind in ("token", "timed_out"):
                # engine-thread emissions only (the _sink_s guard below,
                # same reasoning): reject writes on front-end reader
                # threads must not pollute the step profiler's journal
                # segment
                self._journal_s += _CLOCK() - jt0
            self._journal_guard()
        if self.chaos is not None:
            # the request rides along so tenant-targeted client chaos
            # (slowloris@tenant=...) can pick its victim
            self.chaos.on_client(self._tick_no, req)
        if req.sink is not None:
            t0 = _CLOCK()
            try:
                req.sink(ev)
            except Exception:  # noqa: BLE001
                # a client that died mid-stream must cost ITS request,
                # never the engine: drop the sink, let the slot finish
                # out its budget (eos/budget latch frees it) — and say
                # so on the stream, a vanished consumer is evidence
                req.sink = None
                self.metrics.on_dropped_sink()
                self.tracer.event("client_disconnected", request=req.id,
                                  tick=self._tick_no, **_tr(req))
            # charge transport time to the REQUEST (a slow client must
            # show up in its own tail attribution, not vanish into the
            # decode gap it inflates)
            dt = _CLOCK() - t0
            req.client_write_s += dt
            if ev.kind in ("token", "timed_out"):
                # token AND timeout emissions happen only on the engine
                # thread inside step(), so this read-modify-write is
                # serial with the decode-gap netting that reads it, and
                # both block live slots' gaps (a dead client stalling a
                # timeout write must not read as decode). Reject writes
                # run on front-end reader threads in parallel with
                # ticks and must NOT pollute the counter
                self._sink_s += dt
            self.metrics.on_client_write(dt)
        if self.on_event is not None:
            self.on_event(ev)
        if ev.finished or ev.kind != "token":
            # stamped AFTER the sink write, the same clock edge
            # `_on_finished` uses for e2e: the final token's delivery is
            # part of the request's life, or a slow client's last write
            # would be charged to client_write yet fall outside e2e and
            # the phases could sum past the total — and every reporter
            # (request_finished event, loadgen e2e) reads this one stamp
            req.finished_at = _CLOCK()
            req.done.set()

    def _on_finished(self, req) -> None:
        """Terminal accounting for a completed request: SLO metrics,
        phase histograms, and the `request_finished` event whose
        per-phase totals are what `obs trace` decomposes tails with.
        e2e ends at `finished_at`, which `_emit` stamps after the final
        sink write — the single terminal clock edge every reporter
        (this event, the histograms, loadgen) agrees on."""
        now = req.finished_at if req.finished_at is not None \
            else _CLOCK()
        self.metrics.on_finish(req, now)
        reason = ("eos" if self.cfg.eos_id is not None and req.tokens
                  and req.tokens[-1] == self.cfg.eos_id else "budget")
        req.finish_reason = reason
        self.metrics.on_phases(req)
        self.tracer.event(
            "request_finished", request=req.id, tick=self._tick_no,
            reason=reason, prompt_len=req.prompt_len,
            n_tokens=len(req.tokens), preempts=req.preempts,
            e2e_s=round(now - req.submitted_at, 6),
            ttft_s=(round(req.first_token_at - req.submitted_at, 6)
                    if req.first_token_at is not None else None),
            **{f"{p}_s": round(v, 6) for p, v in req.phases_s().items()},
            **_tr(req),
        )

    # -------------------------------------------------------- public api

    def submit(self, req: Request) -> tuple[bool, str | None]:
        """Queue a request (thread-safe). Rejections emit immediately —
        backpressure the caller can act on, not a silent drop."""
        gov = self._governor
        gov_active = gov is not None and gov.active
        # shed order made admission policy: batch clamps whenever ANY
        # brownout holds (local governor or router-ordered); interactive
        # clamps only when the local governor is active AND the batch
        # queue is already empty — batch absorbs every degradation
        # first, and a router order alone never touches interactive
        clamp_this = (gov_active or self._class_brownout) \
            if req.sla_class == CLASS_BATCH \
            else (gov_active and self.queue.depth_of(CLASS_BATCH) == 0)
        if clamp_this and self.cfg.brownout_clamp > 0 \
                and req.max_new_tokens > self.cfg.brownout_clamp:
            # brownout clamp, applied BEFORE the journal sees the
            # request: the WAL must record the budget actually served,
            # or a replay would un-clamp it mid-overload
            req.clamped_from = req.max_new_tokens
            req.max_new_tokens = self.cfg.brownout_clamp
        if self.journal is not None:
            # write-AHEAD of queue.submit: the instant the request is
            # in the queue the engine thread may pop it and emit its
            # first token, and that token's journal record needs the
            # admit record already on disk. A door rejection below
            # closes the speculative record with a terminal one, so it
            # can never replay.
            self.journal.admit(req)
            req._journaled = True
            self._journal_guard()
        ok, reason = self.queue.submit(req)
        if ok:
            self.metrics.on_accept(req.sla_class)
            if req.clamped_from is not None:
                self.metrics.on_clamp(req.sla_class)
            self.tracer.event("request_admitted", request=req.id,
                              prompt_len=req.prompt_len,
                              max_new_tokens=req.max_new_tokens,
                              deadline_s=req.deadline_s,
                              sla_class=req.sla_class,
                              **({"tenant": req.tenant}
                                 if req.tenant else {}),
                              **({"clamped_from": req.clamped_from}
                                 if req.clamped_from is not None else {}),
                              **_tr(req))
        else:
            # queued_s: rejection happens at the door, so the request
            # spent zero time queued — the key exists so rejects land in
            # the same attribution tables as everything else
            req.finish_reason = "rejected"
            self.metrics.on_reject(reason)
            self.tracer.event("request_rejected", request=req.id,
                              reason=reason, prompt_len=req.prompt_len,
                              sla_class=req.sla_class,
                              **({"tenant": req.tenant}
                                 if req.tenant else {}),
                              queued_s=0.0, **_tr(req))
            self._emit(TokenEvent(req, None, True, kind="rejected",
                                  reason=reason))
        return ok, reason

    def reject_unparsed(self, rid: str | None, error: str) -> None:
        """Front-end hand-off for a line that never became a Request:
        counted and evented like a door reject so malformed input is
        visible in the same tables — and never an engine-thread
        exception, whatever the line contained."""
        self.metrics.on_reject(REJECT_BAD_REQUEST)
        self.tracer.event(
            "request_rejected",
            request=rid or f"unparsed_{next(self._unparsed)}",
            reason=REJECT_BAD_REQUEST, error=str(error)[:200],
            queued_s=0.0)

    # ------------------------------------------------- drain + recovery

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self, timeout_s: float = 30.0) -> None:
        """Flip to graceful drain (idempotent): the queue closes with
        `reject(reason="draining")`, in-flight slots — and requests
        already accepted into the queue — run to eos/budget, bounded by
        `timeout_s`. The SIGTERM/SIGINT path (serve/server.py) lands
        here."""
        if self._draining:
            return
        self._draining = True
        self._drain_deadline = _CLOCK() + max(0.0, timeout_s)
        self.queue.close(REJECT_DRAINING)
        self.tracer.event("serve_draining", tick=self._tick_no,
                          active=self.n_active, queue=len(self.queue),
                          timeout_s=timeout_s)
        self.hb.pulse(phase="drain", step=self._tick_no,
                      active=self.n_active, queue=len(self.queue))

    def drain_expired(self) -> bool:
        return (self._draining and self._drain_deadline is not None
                and _CLOCK() > self._drain_deadline)

    def replay_pending(self, sink=None, *,
                       max_replays: int = MAX_REPLAYS_DEFAULT) -> dict:
        """Recover the journal into this engine — called once, after
        `warmup`, before the serve loop. Unfinished journaled requests
        re-enter HEAD of queue (original admit order preserved) with
        their generated tokens riding along; the next pop re-prefills
        prompt + generated through the same recompute path preemption
        uses, so the continuation is bit-identical and `obs trace`
        shows it as a resumed request. Requests whose output was
        already complete just owe the client a terminal event; requests
        that crashed the engine `max_replays` times are quarantined
        with a `request_poisoned` event instead of crash-looping."""
        if self.journal is None:
            return {"resumed": 0, "finished": 0, "poisoned": 0,
                    "clean": True}
        resume, finished, poisoned, clean = self.journal.recover(
            max_replays=max_replays, eos_id=self.cfg.eos_id)
        self._journal_guard()
        for req in finished:
            req.sink = sink
            req.status = "done"
            req.finish_reason = "recovered_complete"
            self.tracer.event(
                "request_finished", request=req.id, tick=self._tick_no,
                reason="recovered_complete", prompt_len=req.prompt_len,
                n_tokens=len(req.tokens), preempts=req.preempts,
                replayed=True, **_tr(req))
            self._emit(TokenEvent(req, None, True, kind="done",
                                  reason="recovered_complete"))
        for req in poisoned:
            req.sink = sink
            req.status = "rejected"
            req.finish_reason = REJECT_POISONED
            self.metrics.on_poisoned()
            self.tracer.event(
                "request_poisoned", request=req.id, replays=req.replays,
                prompt_len=req.prompt_len, generated=len(req.tokens),
                **_tr(req))
            self._emit(TokenEvent(req, None, True, kind="rejected",
                                  reason=REJECT_POISONED))
        for req in reversed(resume):  # reversed: first-admitted at head
            req.sink = sink
            req._journaled = True
            if req.tokens:
                # resumed mid-decode: its next queue wait banks as
                # replay, its prefill as replay_prefill, and no second
                # first-token event fires — the PR-7 resume vocabulary
                req._preempted = True
                req.first_token_at = req.submitted_at
            self.metrics.on_replay()
            self.tracer.event(
                "request_admitted", request=req.id,
                prompt_len=req.prompt_len,
                max_new_tokens=req.max_new_tokens,
                deadline_s=req.deadline_s, replayed=True,
                replay_n=req.replays, generated=len(req.tokens),
                **_tr(req))
            self.queue.push_front(req)
        if resume or finished or poisoned:
            self.tracer.event("journal_replayed", resumed=len(resume),
                              finished=len(finished),
                              poisoned=len(poisoned))
        return {"resumed": len(resume), "finished": len(finished),
                "poisoned": len(poisoned), "clean": clean}

    @property
    def n_active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def idle(self) -> bool:
        return self.n_active == 0 and len(self.queue) == 0

    def _phase(self) -> str:
        if self._draining:
            return "drain"
        return "serve" if (self.n_active or len(self.queue)) \
            else "serve_idle"

    def _slo_tick(self, now: float | None = None) -> None:
        """Advance the SLO burn-rate state machines (rate-limited
        inside the monitor). Transitions emit the standard
        alert_raised/alert_cleared events AND an unconditional
        heartbeat pulse: the heartbeat's `alerts` field is how the
        router and `obs top` see a replica's alarm state without
        opening its stream."""
        if self.slo is None:
            return
        trs = self.slo.evaluate(now)
        if trs:
            slo_mod.publish(trs, self.tracer, self.metrics.reg,
                            step=self._tick_no,
                            active=len(self.slo.active))
            self.hb.pulse(step=self._tick_no, phase=self._phase(),
                          active=self.n_active, queue=len(self.queue),
                          alerts=self.slo.active_names())

    def exposition(self, window_s: float = DEFAULT_WINDOW_S) -> dict:
        """Live snapshot for the exposition socket (obs/export.py):
        current loop state + lifetime metrics + the last-`window_s`
        windowed roll-up. Host floats and bounded ring copies only —
        answering can never touch the device or trace a jit, whatever
        thread asks."""
        reg = self.metrics.reg
        gov = self._governor
        return {
            "role": "engine",
            "run": self.tracer.run,
            "phase": self._phase(),
            "tick": self._tick_no,
            "active": self.n_active,
            "slots": self.cfg.slots,
            "occupancy": round(self.n_active / self.cfg.slots, 4)
            if self.cfg.slots else 0.0,
            "queue": len(self.queue),
            "queue_by_class": self.queue.depth_by_class(),
            "draining": self._draining,
            "brownout": bool(gov.active) if gov is not None else False,
            # the act dict: what degradation/scheduling posture this
            # engine is in RIGHT NOW (obs top's `act` column)
            "act": {
                "class_brownout": self._class_brownout,
                "brownout": bool(gov.active) if gov is not None else False,
                "chunking": len(self._chunking),
            },
            "blocks_in_use": self.mgr.in_use,
            "blocks_free": self.mgr.num_free,
            "alerts": (self.slo.active_names()
                       if self.slo is not None else []),
            "metrics": reg.snapshot(),
            "windows": reg.windowed_snapshot(window_s),
            "memory": self.memory_ledger(),
            "tickprof": self.tickprof.snapshot(window_s),
            "compile": {**self.ledger.last_seen,
                        "recompiles": self.ledger.recompiles},
        }

    def memory_ledger(self) -> dict:
        """Live memory accounting from known shapes — param bytes, the
        KV pool's full and in-use footprint, host RSS. Pure host
        arithmetic (`.nbytes` is metadata, `_block_bytes` a cached
        int), so any thread may ask."""
        bb = self._block_bytes
        # HBM the paged-read strategy copies per decode tick: the
        # gather path materializes every slot's full [MB] chain
        # (mapped or null) into a contiguous view; the pallas kernel
        # reads the pools in place, so the copy is zero.
        impl = getattr(self.model.cfg, "paged_attn_impl", "gather")
        gather = 0 if impl == "pallas" else \
            int(self.cfg.slots * self._mb * bb)
        return {
            "param_bytes": self._param_bytes,
            "kv_pool_bytes": int(self.cfg.num_blocks * bb),
            "blocks_in_use_bytes": int(self.mgr.in_use * bb),
            "kv_gather_bytes_per_tick": gather,
            # the host tier's occupancy rides the same ledger the HBM
            # numbers do — spilled KV is memory too, just cheaper
            "host_cache_mb": round(self.host.occupancy_mb, 3)
            if self.host is not None else 0.0,
            "host_cache_budget_mb": self.cfg.host_cache_mb,
            "rss_mb": hb_host_rss_mb(),
        }

    def _flight_payload(self) -> dict:
        """What a flight-record spill captures: loop state, the tick
        ring's tail, the windowed breakdown, compile counts, memory."""
        return {
            "phase": self._phase(),
            "active": self.n_active,
            "queue": len(self.queue),
            "ticks": self.tickprof.tail(32),
            "tickprof": self.tickprof.snapshot(),
            "compile": {**self.ledger.last_seen,
                        "recompiles": self.ledger.recompiles},
            "memory": self.memory_ledger(),
        }

    def flight_spill(self, reason: str, **extra) -> None:
        """Spill the flight record NOW — the server's SIGTERM handler,
        the fatal-exception path, and the final drain all call this.
        Host-only, so safe from a signal handler's frame."""
        if extra:
            self.flight.note(reason, **extra)
        self.flight.spill(reason, self._flight_payload(),
                          tick=self._tick_no)

    def control(self, req: dict) -> dict:
        """Control verbs arriving on the exposition socket (the
        request-line protocol in obs/export.py). `profile` brackets
        `jax.profiler.start_trace/stop_trace` on demand; anything
        unknown answers with an error dict instead of raising — the
        exporter thread must never die of a bad request."""
        cmd = req.get("cmd")
        if cmd == "profile":
            from hyperion_tpu.utils.profiling import on_demand_trace
            out = req.get("out")
            if not out:
                return {"status": "error", "error": "profile needs 'out'"}
            res = on_demand_trace(str(out),
                                  float(req.get("seconds") or 5.0))
            self.tracer.event("profile_requested", **res)
            return res
        if cmd == "class_brownout":
            # the router's degradation order (obs/export.py control
            # protocol): shed/clamp the batch class as if the local
            # governor were active, but never touch interactive — the
            # order means "yield batch capacity to the fleet", not
            # "this replica is drowning". Idempotent; a bool flip is
            # atomic under the GIL, so no lock against the engine
            # thread is needed.
            active = bool(req.get("active", True))
            changed = active != self._class_brownout
            self._class_brownout = active
            if changed:
                self.metrics.set_class_brownout(active)
                self.tracer.event("class_brownout", tick=self._tick_no,
                                  active=active, source="control")
            return {"status": "ok", "active": active, "changed": changed}
        return {"status": "error", "error": f"unknown cmd {cmd!r}"}

    def step(self) -> list[TokenEvent]:
        """One scheduling round: admit from the queue into free slots
        (block-gated, prefill, budget-limited), ensure every live slot
        owns its next write block (preempting on exhaustion), advance
        all active slots — one token each, or 1..spec_k+1 under the
        speculative tick — and route emissions."""
        emissions: list[TokenEvent] = []
        now = _CLOCK()
        # host-tick profiler (obs/tickprof.py): stamp each segment of
        # this step into `seg` — pure perf-counter arithmetic, no device
        # interaction. Journal/sink time is accumulated inside _emit
        # wherever it happens, so enclosing segments NET those deltas
        # out rather than double-charging them.
        seg: dict[str, float] = {}
        p_start = now
        j_start, s_start = self._journal_s, self._sink_s

        if self._governor is not None:
            tr = self._governor.update(len(self.queue))
            if tr == "enter":
                self.metrics.set_brownout(True)
                self.tracer.event(
                    "brownout_enter", tick=self._tick_no,
                    depth=len(self.queue),
                    wait_p95_ms=round(self._governor.wait_p95() * 1e3, 3))
            elif tr == "exit":
                self.metrics.set_brownout(False)
                self.tracer.event("brownout_exit", tick=self._tick_no,
                                  depth=len(self.queue))
        gov_active = self._governor is not None and self._governor.active
        if gov_active or self._class_brownout:
            # shed deadline-aware, cheapest first, BATCH FIRST: queued
            # requests that cannot meet their deadline even if service
            # began after their CLASS's estimated wait are already
            # doomed — reject them NOW so the client retries elsewhere
            # instead of burning a queue slot toward a timeout.
            # Interactive is swept only when the local governor is
            # active AND batch is already empty (a router-ordered
            # class brownout alone never touches interactive).
            shed_classes = [CLASS_BATCH]
            if gov_active and self.queue.depth_of(CLASS_BATCH) == 0:
                shed_classes = [CLASS_INTERACTIVE]
            est = {cls: self._governor.wait_p95(cls)
                   for cls in shed_classes} \
                if self._governor is not None else {}
            for req in self.queue.shed_doomed(
                    now, est_wait_by_class=est,
                    classes=tuple(shed_classes)):
                self.metrics.on_shed(req.sla_class)
                req.finish_reason = REJECT_SHED
                # the standard reject vocabulary (shed=true rides
                # along): `obs trace` keeps shed requests in the
                # same attribution tables as door rejects, with
                # the queue time they DID burn before dying
                self.tracer.event(
                    "request_rejected", request=req.id,
                    tick=self._tick_no, reason=REJECT_SHED, shed=True,
                    sla_class=req.sla_class,
                    **({"tenant": req.tenant} if req.tenant else {}),
                    queued_s=round(max(0.0, now - req.enqueued_at), 6),
                    deadline_s=req.deadline_s)
                ev = TokenEvent(req, None, True, kind="rejected",
                                reason=REJECT_SHED)
                self._emit(ev)
                emissions.append(ev)

        t_seg = _CLOCK()
        free = [s for s, r in enumerate(self._slots) if r is None]
        if free:
            admit, expired = self.queue.pop_ready(
                len(free), now, can_admit=self._can_admit)
            # pop_ready only expires requests it reaches; a block-gated
            # head stops the walk, so sweep the remainder too — a
            # deadline behind a stalled head must still fire on time
            expired += self.queue.drop_expired(now)
        else:
            admit, expired = [], self.queue.drop_expired(now)
        if CLASS_INTERACTIVE in self.queue.gate_blocked:
            # an interactive head is denied by the block gate while
            # batch work holds slots: preempt the YOUNGEST batch slot
            # to the queue (recompute resume — nothing is lost) so the
            # freed blocks admit the interactive head next round. One
            # victim per step: pool accounting settles between rounds,
            # and a single long prompt must not massacre the whole
            # batch tier in one tick.
            batch_live = [
                s for s, r in enumerate(self._slots)
                if r is not None and r.sla_class == CLASS_BATCH]
            if batch_live:
                victim = max(batch_live,
                             key=lambda t: self._seqs[t].order)
                self._preempt(victim, reason="interactive_gate")
        seg["queue_pop"] = _CLOCK() - t_seg
        t_seg = _CLOCK()
        j_mark, s_mark = self._journal_s, self._sink_s
        for req in expired:
            self.metrics.on_timeout()
            req.finish_reason = "timed_out"
            # enqueued_at, not submitted_at: a preempted-then-requeued
            # request that expires spent part of its life in a slot,
            # and that time is replay cost, not queue residency
            queued = round(max(0.0, now - req.enqueued_at), 6)
            self.tracer.event("request_timeout", request=req.id,
                              waited_s=round(now - req.submitted_at, 3),
                              queued_s=queued, **_tr(req))
            ev = TokenEvent(req, None, True, kind="timed_out",
                            reason="deadline exceeded in queue")
            self._emit(ev)
            emissions.append(ev)
        while admit:
            req = admit.pop(0)
            slot = free.pop(0)
            if self.chaos is not None:
                # poison_request@id=... fires here, at the moment the
                # request is about to occupy a slot — the journal has
                # its admit record, so the crash-replay counter (the
                # poison-pill rule) sees every death it causes
                self.chaos.on_request(req.id)
            resumed = self._account_pop(req)
            ev = self._admit(req, slot)
            if ev is _CHUNK_ADMIT:
                # the slot is claimed and prefilling in chunks across
                # later steps; no token yet, nothing to emit
                continue
            if ev is None:
                # allocation raced an eviction between gate and admit:
                # requeue head-first in arrival order and retry next
                # round — degraded, never dropped. EVERY popped request
                # streams the scheduled/requeued pair so no queue stint
                # vanishes from the trace: the scheduled event banks
                # the wait that just ended, the requeue mark starts the
                # renewed one (and keeps resume flags for the re-pop)
                req._preempted = resumed
                for r in reversed([req] + admit):
                    if r.admitted_at is not None and r is not req:
                        r._preempted = self._account_pop(r)
                    self.tracer.event(
                        "request_requeued", request=r.id,
                        tick=self._tick_no, reason="alloc_race")
                    self.mgr.release(self._pending_reserve.pop(r.id, 0))
                    self.queue.push_front(r)
                break
            self._emit(ev)
            emissions.append(ev)
            if ev.finished:
                self._on_finished(req)
        # admit covers expiry + admission + their prefill calls, net of
        # journal/sink writes those paths perform
        seg["admit"] = max(0.0, (_CLOCK() - t_seg)
                           - (self._journal_s - j_mark)
                           - (self._sink_s - s_mark))

        # one chunked-prefill segment per step, interleaved with the
        # decode tick below — the whole point: co-running slots tick
        # every step while a long prompt fills in bounded bites
        t_seg = _CLOCK()
        j_mark, s_mark = self._journal_s, self._sink_s
        for ev in self._advance_chunks():
            self._emit(ev)
            emissions.append(ev)
            if ev.finished:
                self._on_finished(ev.request)
        seg["chunk"] = max(0.0, (_CLOCK() - t_seg)
                           - (self._journal_s - j_mark)
                           - (self._sink_s - s_mark))

        if self.n_active:
            self._ensure_blocks()
        n_live = self.n_active - len(self._chunking)
        if n_live > 0:
            if self.chaos is not None:
                self.chaos.on_tick(self._tick_no)
            spec = self._spec
            cnts = accs = None
            t_seg = _CLOCK()
            drafts = self._collect_drafts() if spec else None
            seg["draft"] = _CLOCK() - t_seg
            u_mark = self._bt_upload_s
            with self.tracer.span("serve_tick", step=self._tick_no) as sp:
                t0 = _CLOCK()
                if spec:
                    toks, cnts, accs, fins = self._spec_tick_device(drafts)
                else:
                    toks, fins = self._tick_device()
                dur = _CLOCK() - t0
                sp.set(active=self.n_active)
            # the device call's wall splits into the host->device table
            # upload (when the table went stale) and dispatch+wait
            seg["bt_upload"] = self._bt_upload_s - u_mark
            seg["device"] = max(0.0, dur - seg["bt_upload"])
            emitted = 0
            slot_ticks = 0
            tnow = _CLOCK()
            j_mark, s_mark = self._journal_s, self._sink_s
            for s, req in enumerate(self._slots):
                if req is None or s in self._chunking:
                    # a chunking slot is masked out of the tick — its
                    # lane computed pad into the null block, nothing
                    # to route
                    continue
                slot_ticks += 1
                n = int(cnts[s]) if spec else 1
                if spec:
                    self.metrics.on_spec(self.cfg.spec_k, int(accs[s]))
                if n == 0:
                    continue
                self._seqs[s].n_filled += n
                gap_from = getattr(req, "_last_emit_at", None)
                if gap_from is not None:
                    # the gap is wall time shared by every slot: net it
                    # of ALL sink writes since this request's previous
                    # emission (its own are charged to client_write;
                    # neighbours' must not masquerade as decode). One
                    # verify pass produced n tokens, so TPOT charges
                    # the pass pro-rata across them — the per-token
                    # cadence a streaming client actually experiences
                    for _ in range(n):
                        self.metrics.on_token_gap((tnow - gap_from) / n,
                                                  req.sla_class)
                    sink = self._sink_s - getattr(
                        req, "_sink_mark", self._sink_s)
                    req.decode_s += max(0.0, tnow - gap_from - sink)
                req._last_emit_at = tnow
                req._sink_mark = self._sink_s
                fin_slot = bool(fins[s])
                # every accepted token flows through the SAME per-token
                # path the sequential tick uses: one journal `tok`
                # record, one stream index, one sink write apiece —
                # failover dedup and replay never see speculation
                for j in range(n):
                    tok = int(toks[s, j]) if spec else int(toks[s])
                    ev = TokenEvent(req, tok, fin_slot and j == n - 1)
                    self._emit(ev)
                    emissions.append(ev)
                    emitted += 1
                if fin_slot:
                    self._on_finished(req)
                    self._free_slot(s)
            # accept host path: token routing + gap netting, minus the
            # journal/sink writes _emit charged to their own segments
            seg["accept"] = max(0.0, (_CLOCK() - tnow)
                                - (self._journal_s - j_mark)
                                - (self._sink_s - s_mark))
            self.metrics.on_tick(dur, emitted, slot_ticks)
            self._tick_no += 1
            if self.cfg.snapshot_every \
                    and self._tick_no % self.cfg.snapshot_every == 0:
                rss = hb_host_rss_mb()
                if rss is not None:
                    # a gauge SERIES across snapshots — doctor reads the
                    # trend for its host-leak warning
                    self.metrics.reg.gauge("host_rss_mb").set(rss)
                self.tracer.snapshot(self.metrics.reg, step=self._tick_no,
                                     tickprof=self.tickprof.snapshot())

        # compile ledger: 4 host-int reads per step. Any growth after
        # warmup is a broken invariant — count it, name the executable,
        # and leave churn context (what shape work just ran) for doctor
        growth = self.ledger.check(self.compile_stats())
        if growth:
            self.metrics.on_recompile(
                sum(g["after"] - g["before"] for g in growth))
            for g in growth:
                ctx = dict(tick=self._tick_no, active=self.n_active,
                           queue=len(self.queue),
                           last_prefill_bucket=self._last_prefill_bucket)
                self.tracer.event("recompile_after_warmup",
                                  executable=g["executable"],
                                  before=g["before"], after=g["after"],
                                  **ctx)
                self.flight.note("recompile_after_warmup",
                                 executable=g["executable"], **ctx)

        seg["journal"] = self._journal_s - j_start
        seg["sink"] = self._sink_s - s_start
        t_seg = _CLOCK()
        self.metrics.observe_state(
            len(self.queue), self.n_active, self.cfg.slots)
        self.metrics.observe_cache(
            self.mgr.in_use, self.mgr.num_free, self.n_active,
            self._block_bytes)
        self._slo_tick()
        roots = self._hot_roots.top()
        self.hb.beat(step=self._tick_no, phase="serve",
                     active=self.n_active, queue=len(self.queue),
                     **({"alerts": self.slo.active_names()}
                        if self.slo is not None else {}),
                     **({"prefix_roots": roots} if roots else {}))
        seg["slo"] = _CLOCK() - t_seg
        self.tickprof.record(self._tick_no, seg,
                             _CLOCK() - p_start)
        if self.flight.due(self._tick_no):
            self.flight.spill("periodic", self._flight_payload(),
                              tick=self._tick_no)
        return emissions

    def run(
        self,
        *,
        should_stop: Callable[[], bool] | None = None,
        drain_when: Callable[[], bool] | None = None,
        idle_sleep_s: float = 0.01,
    ) -> dict:
        """Serve until `should_stop()` (hard stop) or until
        `drain_when()` and the engine is idle (graceful drain; default:
        drain immediately once idle). Emits `serve_start`/`serve_end`
        lifecycle events — `obs doctor` reads `serve_end` as the
        terminal record separating a drained server from a hung one."""
        drain_when = drain_when or (lambda: True)
        self.tracer.event(
            "serve_start", slots=self.cfg.slots, max_len=self.cfg.max_len,
            block_size=self.cfg.block_size, num_blocks=self.cfg.num_blocks,
            prefix_cache=self.cfg.prefix_cache,
            host_cache_mb=self.cfg.host_cache_mb)
        self.hb.pulse(phase="serve", step=self._tick_no)
        try:
            while True:
                if should_stop is not None and should_stop():
                    break
                if self.drain_expired():
                    # the grace window closed with work still in hand:
                    # stop NOW — everything unfinished is journaled, so
                    # the next life replays it instead of losing it
                    self.tracer.event("drain_timeout", tick=self._tick_no,
                                      active=self.n_active,
                                      queue=len(self.queue))
                    break
                if self.idle:
                    # drain_when first, idle RE-checked after: a
                    # transport's last submit happens-before its EOF
                    # flag, so this ordering can never strand a request
                    # that raced the drain signal
                    if (self._draining or drain_when()) and self.idle:
                        break
                    # idle SLO ticks: an alert raised under load must
                    # be able to CLEAR while the loop sits idle after
                    # the load drops — step() is not running, so the
                    # idle loop owns the evaluation cadence here
                    self._slo_tick()
                    # same payload shape as the serve beat so a watcher
                    # (obs doctor) reads occupancy whichever phase the
                    # loop froze in
                    idle_roots = self._hot_roots.top()
                    self.hb.beat(step=self._tick_no, phase="serve_idle",
                                 active=0, queue=len(self.queue),
                                 **({"alerts": self.slo.active_names()}
                                    if self.slo is not None else {}),
                                 **({"prefix_roots": idle_roots}
                                    if idle_roots else {}))
                    time.sleep(idle_sleep_s)
                    continue
                self.step()
        except BaseException as e:
            # the flight record IS the post-mortem: spill before the
            # exception unwinds the process so doctor can cite the
            # final ticks even when nothing catches it upstream
            self.flight_spill("fatal_exception", error=repr(e)[:200])
            raise
        finally:
            summary = self.metrics.summary()
            self.tracer.snapshot(self.metrics.reg, step=self._tick_no)
            self.tracer.event(
                "serve_end", ticks=self._tick_no,
                completed=summary["completed"],
                rejected=summary["rejected"],
                timed_out=summary["timed_out"],
                tokens=summary["tokens"],
                prefix_hits=summary["prefix_hits"],
                preempted=summary["preempted"],
                alerts_raised=summary["alerts_raised"],
                # the tier split rides the terminal record so smoke/
                # doctor read host-tier evidence without a snapshot
                tier_hits_host=summary["tier_hits_host"],
                tier_hits_device=summary["tier_hits_device"],
                tier_miss=summary["tier_miss"],
                host_spilled_blocks=summary["host_spilled_blocks"],
                host_restored_blocks=summary["host_restored_blocks"],
            )
            if self.host is not None and self.cfg.host_cache_dir:
                try:
                    st = self.host.save(self.cfg.host_cache_dir)
                    self.tracer.event(
                        "hostcache_saved", chains=st["chains"],
                        mb=st["mb"], path=self.cfg.host_cache_dir)
                except OSError as e:
                    # persistence is an optimization, never a crash on
                    # the drain path — say so and finish the drain
                    print(f"[serve] host-cache save failed: {e}",
                          file=sys.stderr)
            self.flight_spill("serve_end")
            # the file holds only the LAST beat, so the terminal pulse
            # repeats the occupancy payload — a watcher reading a
            # "done" heartbeat still sees what the loop drained to
            self.hb.close(phase="done", tokens=summary["tokens"],
                          active=self.n_active, queue=len(self.queue))
        return summary
