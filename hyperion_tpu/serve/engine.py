"""Slot-based continuous-batching decode engine — Orca-style iteration
scheduling on a static-shape TPU cache.

The single-shot path (`infer/generate.py`) decodes ONE batch of aligned
prompts: prefill, then a `lax.scan` that every request enters and
leaves together. A server cannot batch that way — requests arrive when
they arrive, finish when they finish, and a batch that waits for its
slowest member wastes every other slot's ticks. Continuous batching
(Yu et al., OSDI '22) decouples the two: the unit of scheduling is one
decode TICK, and membership of the batch is re-decided between ticks.

TPU constraint that shapes everything here: **recompilation is the
enemy.** XLA specializes on shapes, so the naive design — re-batch
active requests into a [n_active, ...] tensor each tick — compiles a
new executable every time occupancy changes. Instead:

  * The KV cache is a fixed `[S, L]` slab (`S` slots × `L` tokens,
    `models/llama.py:init_cache` buffers batched over slots). A slot
    holds one request; a finished slot is refilled from the queue
    without the shapes ever changing. The decode tick is compiled
    ONCE, at warmup, forever.
  * Every per-request quantity the tick needs — cache depth, eos
    latch, remaining budget, temperature/top_k/top_p, PRNG key — is a
    `[S]` device array threaded through the jitted call, so slot
    churn is a cheap scatter into state rows, never a retrace.
  * Per-slot attention masks key on per-slot lengths: slot b's query
    at depth `lengths[b]` attends cache rows `0..lengths[b]` of its
    own row only (the vector-`cache_index` path in
    `models/llama.py:LlamaAttention`). Inactive slots still compute —
    static shapes make their lanes free compared to a recompile — and
    their outputs are discarded on the host.
  * Prefill for a joining request is a SEPARATE jitted call per
    prompt-length bucket (next power of two): it runs the prompt
    through the cached forward at batch 1, scatters the K/V block into
    the free slot's row, samples the first token (TTFT ends here), and
    stamps the slot's state row. Buckets make prompt-length variety a
    handful of warmup compiles instead of one per length.

Semantics contract (the oracle `tests/test_serve.py` pins): at
temperature 0 a request decoded through this engine — while other
slots churn arbitrarily — emits **bit-identical tokens** to
`infer/generate.generate` on the same prompt. Every per-slot op above
is row-independent, so sharing the batch costs nothing semantically.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from hyperion_tpu.infer.generate import sample_token_slots
from hyperion_tpu.serve.metrics import ServeMetrics
from hyperion_tpu.serve.queue import AdmissionQueue, Request

_SNAPSHOT_EVERY = 32  # ticks between metric snapshots on the stream


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    slots: int = 4                 # S: concurrent requests in flight
    max_len: int = 0               # L: per-slot cache length (0 = model max)
    eos_id: int | None = None
    pad_id: int = 0
    queue_capacity: int = 64
    prefill_budget: int = 512      # prompt tokens admitted per round
    min_bucket: int = 8            # smallest prefill padding bucket
    snapshot_every: int = _SNAPSHOT_EVERY


@dataclasses.dataclass
class TokenEvent:
    """One emission the host routes to a transport/test."""
    request: Request
    token: int | None              # None for reject/timeout events
    finished: bool
    kind: str = "token"            # token | rejected | timed_out
    reason: str | None = None


class Engine:
    """Continuous-batching engine over one model + one variables tree.

    Host-side it owns the slot table (slot index -> Request), the
    admission queue, metrics, and telemetry; device-side the [S, L]
    cache and the [S] state rows. `step()` is one scheduling round
    (admit -> tick -> route); `run()` loops it."""

    def __init__(
        self,
        model: Any,
        variables: dict,
        cfg: EngineConfig,
        *,
        metrics: ServeMetrics | None = None,
        tracer=None,
        heartbeat=None,
        chaos=None,
        on_event: Callable[[TokenEvent], Any] | None = None,
    ):
        from hyperion_tpu.models.llama import init_cache
        from hyperion_tpu.obs import heartbeat as hb_mod
        from hyperion_tpu.obs import trace as trace_mod

        self.model = model
        self.variables = variables
        mcfg = model.cfg
        L = cfg.max_len or mcfg.max_len
        if L > mcfg.max_len:
            raise ValueError(
                f"engine max_len {L} exceeds model max_len {mcfg.max_len}")
        self.cfg = dataclasses.replace(cfg, max_len=L)
        self.queue = AdmissionQueue(
            cfg.queue_capacity, max_total_tokens=L,
            prefill_budget=cfg.prefill_budget,
        )
        self.metrics = metrics or ServeMetrics()
        self.tracer = tracer if tracer is not None else trace_mod.null_tracer()
        self.hb = heartbeat if heartbeat is not None \
            else hb_mod.null_heartbeat()
        self.chaos = chaos
        self.on_event = on_event
        self._slots: list[Request | None] = [None] * cfg.slots
        self._cache = init_cache(mcfg, cfg.slots, max_len=L)
        self._state = self._init_state()
        self._tick_no = 0
        # donation keeps the [S, L, Hkv, D] slabs in place on real
        # chips; the CPU backend ignores donation with a warning, so
        # don't ask there
        donate = () if jax.default_backend() == "cpu" else (1, 2)
        self._tick_jit = jax.jit(self._make_tick(), donate_argnums=donate)
        self._prefill_jit = jax.jit(self._make_prefill(),
                                    donate_argnums=donate)

    # ------------------------------------------------------ device state

    def _init_state(self) -> dict:
        S = self.cfg.slots
        return {
            "lengths": jnp.zeros((S,), jnp.int32),
            "active": jnp.zeros((S,), bool),
            "last_token": jnp.zeros((S,), jnp.int32),
            "generated": jnp.zeros((S,), jnp.int32),
            "budget": jnp.ones((S,), jnp.int32),
            "temperature": jnp.zeros((S,), jnp.float32),
            "top_k": jnp.zeros((S,), jnp.int32),
            "top_p": jnp.ones((S,), jnp.float32),
            "keys": jax.random.split(jax.random.key(0), S),
        }

    def _make_tick(self):
        model, eos_id, pad_id = self.model, self.cfg.eos_id, self.cfg.pad_id

        def tick(variables, cache, st):
            # every slot advances one token: write last_token's K/V at
            # its own depth, attend its own filled prefix, sample with
            # its own params. Inactive lanes compute too (static
            # shapes); their results are masked to pad and never
            # delivered.
            logits, cache = model.apply(
                variables, st["last_token"][:, None],
                cache=cache, cache_index=st["lengths"],
            )
            keys = jax.vmap(jax.random.fold_in)(st["keys"], st["lengths"])
            nxt = sample_token_slots(
                logits[:, 0], keys,
                st["temperature"], st["top_k"], st["top_p"],
            )
            nxt = jnp.where(st["active"], nxt, jnp.int32(pad_id))
            adv = st["active"].astype(jnp.int32)
            gen = st["generated"] + adv
            lengths = st["lengths"] + adv
            hit_eos = (nxt == eos_id) if eos_id is not None \
                else jnp.zeros_like(st["active"])
            finished = st["active"] & (hit_eos | (gen >= st["budget"]))
            st = {
                **st,
                "last_token": jnp.where(st["active"], nxt,
                                        st["last_token"]),
                "generated": gen,
                "lengths": lengths,
                "active": st["active"] & ~finished,
            }
            return cache, st, nxt, finished

        return tick

    def _make_prefill(self):
        from hyperion_tpu.models.llama import init_cache

        model, eos_id = self.model, self.cfg.eos_id
        mcfg = model.cfg

        def prefill(variables, cache, st, prompt, slot, true_len,
                    temperature, top_k, top_p, budget, key):
            # prompt [1, Pb] (bucket-padded; pad K/V beyond true_len is
            # written but masked until decode overwrites it position by
            # position). Compiled once per bucket length.
            Pb = prompt.shape[1]
            small = init_cache(mcfg, 1, max_len=Pb)
            logits, small = model.apply(
                variables, prompt, cache=small, cache_index=0)
            for layer, filled in zip(cache, small):
                for kv in ("k", "v"):
                    layer[kv] = jax.lax.dynamic_update_slice(
                        layer[kv], filled[kv].astype(layer[kv].dtype),
                        (slot, 0, 0, 0),
                    )
            last = jax.lax.dynamic_slice_in_dim(
                logits[0], true_len - 1, 1, axis=0)  # [1, V]
            fkey = jax.random.fold_in(key, true_len - 1)
            first = sample_token_slots(
                last, fkey[None], temperature[None], top_k[None],
                top_p[None],
            )[0]
            hit_eos = (first == eos_id) if eos_id is not None else False
            finished = jnp.logical_or(hit_eos, budget <= 1)
            st = {
                "lengths": st["lengths"].at[slot].set(true_len),
                "active": st["active"].at[slot].set(~finished),
                "last_token": st["last_token"].at[slot].set(first),
                "generated": st["generated"].at[slot].set(1),
                "budget": st["budget"].at[slot].set(budget),
                "temperature": st["temperature"].at[slot].set(temperature),
                "top_k": st["top_k"].at[slot].set(top_k),
                "top_p": st["top_p"].at[slot].set(top_p),
                "keys": st["keys"].at[slot].set(key),
            }
            return cache, st, first, finished

        return prefill

    # --------------------------------------------------------- plumbing

    def bucket(self, prompt_len: int) -> int:
        """Smallest power-of-two >= prompt_len (floored at min_bucket,
        capped at max_len): the prefill jit compiles once per value
        this returns."""
        b = self.cfg.min_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.cfg.max_len)

    def compile_stats(self) -> dict:
        """Executable counts in the two jit caches — the no-recompile
        guarantee made measurable (tier-1 asserts these stay flat
        across slot churn after `warmup`)."""
        return {
            "tick_executables": self._tick_jit._cache_size(),
            "prefill_executables": self._prefill_jit._cache_size(),
        }

    def warmup(self, prompt_lens: list[int] | None = None) -> dict:
        """Compile the tick and one prefill per bucket up front, then
        reset serving state. After this, admission/refill/decode never
        traces again — a request joining mid-flight costs a scatter,
        not a compile."""
        lens = sorted({self.bucket(p) for p in (prompt_lens or
                                                [self.cfg.min_bucket])})
        with self.tracer.span("serve_warmup") as sp:
            for pb in lens:
                dummy = Request(prompt_ids=np.ones((min(pb, 2),), np.int32),
                                max_new_tokens=2)
                # pad to the exact bucket so the real compile happens
                self._prefill_call(dummy, slot=0, bucket_len=pb)
            _ = self._tick_device()
            sp.set(buckets=lens)
        self._state = self._init_state()
        self._slots = [None] * self.cfg.slots
        stats = self.compile_stats()
        self.tracer.event("serve_warmup_done", **stats)
        return stats

    def _prefill_call(self, req: Request, slot: int,
                      bucket_len: int | None = None):
        P = req.prompt_len
        Pb = bucket_len or self.bucket(P)
        prompt = np.full((1, Pb), self.cfg.pad_id, np.int32)
        prompt[0, :P] = req.prompt_ids
        self._cache, self._state, first, finished = self._prefill_jit(
            self.variables, self._cache, self._state,
            jnp.asarray(prompt), jnp.int32(slot), jnp.int32(P),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jnp.float32(req.top_p), jnp.int32(req.max_new_tokens),
            jax.random.key(req.seed),
        )
        return int(first), bool(finished)

    def _tick_device(self):
        self._cache, self._state, toks, fins = self._tick_jit(
            self.variables, self._cache, self._state)
        # the host fetch is the fence: tick spans time real work
        return np.asarray(toks), np.asarray(fins)

    # ------------------------------------------------------------ events

    def _emit(self, ev: TokenEvent) -> None:
        req = ev.request
        if ev.kind == "token" and ev.token is not None:
            req.tokens.append(ev.token)
        if ev.finished or ev.kind != "token":
            req.finished_at = time.monotonic()
            if ev.kind == "token":
                req.status = "done"
        if self.chaos is not None:
            self.chaos.on_client(self._tick_no)
        if req.sink is not None:
            try:
                req.sink(ev)
            except Exception:  # noqa: BLE001
                # a client that died mid-stream must cost ITS request,
                # never the engine: drop the sink, let the slot finish
                # out its budget (eos/budget latch frees it)
                req.sink = None
        if self.on_event is not None:
            self.on_event(ev)
        if ev.finished or ev.kind != "token":
            req.done.set()

    # -------------------------------------------------------- public api

    def submit(self, req: Request) -> tuple[bool, str | None]:
        """Queue a request (thread-safe). Rejections emit immediately —
        backpressure the caller can act on, not a silent drop."""
        ok, reason = self.queue.submit(req)
        if ok:
            self.metrics.on_accept()
        else:
            self.metrics.on_reject(reason)
            self.tracer.event("request_rejected", request=req.id,
                              reason=reason, prompt_len=req.prompt_len)
            self._emit(TokenEvent(req, None, True, kind="rejected",
                                  reason=reason))
        return ok, reason

    @property
    def n_active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def idle(self) -> bool:
        return self.n_active == 0 and len(self.queue) == 0

    def step(self) -> list[TokenEvent]:
        """One scheduling round: admit from the queue into free slots
        (prefill, budget-limited), advance all active slots one token,
        route emissions. Returns this round's emissions."""
        emissions: list[TokenEvent] = []
        now = time.monotonic()

        free = [s for s, r in enumerate(self._slots) if r is None]
        if free:
            admit, expired = self.queue.pop_ready(len(free), now)
        else:
            admit, expired = [], self.queue.drop_expired(now)
        for req in expired:
            self.metrics.on_timeout()
            self.tracer.event("request_timeout", request=req.id,
                              waited_s=round(now - req.submitted_at, 3))
            ev = TokenEvent(req, None, True, kind="timed_out",
                            reason="deadline exceeded in queue")
            self._emit(ev)
            emissions.append(ev)
        for req in admit:
            slot = free.pop(0)
            with self.tracer.span("serve_prefill", step=self._tick_no) as sp:
                first, finished = self._prefill_call(req, slot)
                sp.set(request=req.id, slot=slot,
                       prompt_len=req.prompt_len,
                       bucket=self.bucket(req.prompt_len))
            req.prefilled_at = req.first_token_at = time.monotonic()
            req._last_emit_at = req.first_token_at
            self.metrics.on_first_token(req, req.first_token_at)
            self.metrics.count_tokens(1)  # the prefill-sampled token
            ev = TokenEvent(req, first, finished)
            self._emit(ev)
            emissions.append(ev)
            if finished:
                self.metrics.on_finish(req)
            else:
                self._slots[slot] = req

        if self.n_active:
            if self.chaos is not None:
                self.chaos.on_tick(self._tick_no)
            with self.tracer.span("serve_tick", step=self._tick_no) as sp:
                t0 = time.monotonic()
                toks, fins = self._tick_device()
                dur = time.monotonic() - t0
                sp.set(active=self.n_active)
            emitted = 0
            tnow = time.monotonic()
            for s, req in enumerate(self._slots):
                if req is None:
                    continue
                ev = TokenEvent(req, int(toks[s]), bool(fins[s]))
                gap_from = getattr(req, "_last_emit_at", None)
                if gap_from is not None:
                    self.metrics.on_token_gap(tnow - gap_from)
                req._last_emit_at = tnow
                self._emit(ev)
                emissions.append(ev)
                emitted += 1
                if ev.finished:
                    self.metrics.on_finish(req, tnow)
                    self._slots[s] = None
            self.metrics.on_tick(dur, emitted)
            self._tick_no += 1
            if self.cfg.snapshot_every \
                    and self._tick_no % self.cfg.snapshot_every == 0:
                self.tracer.snapshot(self.metrics.reg, step=self._tick_no)

        self.metrics.observe_state(
            len(self.queue), self.n_active, self.cfg.slots)
        self.hb.beat(step=self._tick_no, phase="serve",
                     active=self.n_active, queue=len(self.queue))
        return emissions

    def run(
        self,
        *,
        should_stop: Callable[[], bool] | None = None,
        drain_when: Callable[[], bool] | None = None,
        idle_sleep_s: float = 0.01,
    ) -> dict:
        """Serve until `should_stop()` (hard stop) or until
        `drain_when()` and the engine is idle (graceful drain; default:
        drain immediately once idle). Emits `serve_start`/`serve_end`
        lifecycle events — `obs doctor` reads `serve_end` as the
        terminal record separating a drained server from a hung one."""
        drain_when = drain_when or (lambda: True)
        self.tracer.event("serve_start", slots=self.cfg.slots,
                          max_len=self.cfg.max_len)
        self.hb.pulse(phase="serve", step=self._tick_no)
        try:
            while True:
                if should_stop is not None and should_stop():
                    break
                if self.idle:
                    # drain_when first, idle RE-checked after: a
                    # transport's last submit happens-before its EOF
                    # flag, so this ordering can never strand a request
                    # that raced the drain signal
                    if drain_when() and self.idle:
                        break
                    self.hb.beat(step=self._tick_no, phase="serve_idle")
                    time.sleep(idle_sleep_s)
                    continue
                self.step()
        finally:
            summary = self.metrics.summary()
            self.tracer.snapshot(self.metrics.reg, step=self._tick_no)
            self.tracer.event(
                "serve_end", ticks=self._tick_no,
                completed=summary["completed"],
                rejected=summary["rejected"],
                timed_out=summary["timed_out"],
                tokens=summary["tokens"],
            )
            self.hb.close(phase="done", tokens=summary["tokens"])
        return summary
