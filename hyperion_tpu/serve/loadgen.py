"""Synthetic load generator — Poisson arrivals against the engine.

Serving numbers measured one request at a time are fiction: TTFT under
load includes queue wait, throughput under load includes slot
contention, and reject rate only exists when arrivals outpace drains.
This driver produces those conditions deterministically (seeded
arrival schedule, seeded prompt mix) and runs CLOSED-LOOP with the
engine: the driver and the serve loop share one thread, alternating
submit-due-requests with `engine.step()`, so a run is reproducible —
no wall-clock race decides which tick a request joins.

Used by `bench.py --child-serving` (the `serving` probe riding the
headline line) and the slow soak test; both report the same keys, so
`obs diff` tracks serving regressions exactly like the PR-4
`input_pipeline` probe.
"""

from __future__ import annotations

import dataclasses
import time

from hyperion_tpu.utils.clock import SYSTEM as _CLOCK

import numpy as np

from hyperion_tpu.obs.export import DEFAULT_WINDOW_S
from hyperion_tpu.obs.registry import percentile
from hyperion_tpu.obs.timeline import PHASES, cohort_dominant
from hyperion_tpu.serve.queue import (
    CLASS_BATCH,
    CLASS_INTERACTIVE,
    SLA_CLASSES,
    Request,
)

# THE serving-row vocabulary: every key a `run_load` report carries
# that `obs diff`'s normalize() may consume. `scripts/check_diff_gates.py`
# cross-checks the gated metric names against this tuple so a gate can
# never outlive (or precede) the emitter that feeds it.
SERVING_REPORT_KEYS = (
    "requests", "completed", "rejected", "timed_out", "reject_rate",
    "tokens", "tokens_per_s", "ttft_p50_ms", "ttft_p99_ms",
    "e2e_p50_ms", "e2e_p99_ms", "elapsed_s", "arrival_rate_hz", "slots",
    "shared_prefix_tokens", "prefix_hit_rate", "prefill_tokens_saved",
    "preempted", "cow_copies", "blocks_in_use", "hbm_per_req_mb",
    "accept_rate", "tokens_per_tick", "spec_drafted", "spec_accepted",
    "spec_rejected", "shed", "brownout_clamped", "shed_rate",
    "clamp_rate",
    # tiered KV cache (PR 20, serve/hostcache.py): where prefix
    # lookups landed (device radix / host spill tier / miss) and what
    # the host tier moved — the `rehit` workload's verdict keys
    "tier_hits_device", "tier_hits_host", "tier_miss",
    "tier_hit_rate_host", "restore_bytes_per_s", "host_cache_mb",
    *(f"{p}_p99_ms" for p in PHASES),
    "dominant_phase_p99", "ttft_p99_windowed_ms", "tpot_p99_windowed_ms",
    "alerts_raised", "alerts_active", "recompiles",
    # per-SLO-class isolation keys (PR 14): the `@class` bench
    # dimension's verdict row — interactive latency must hold while
    # batch absorbs the sheds
    *(f"{cls}_{k}" for cls in SLA_CLASSES
      for k in ("ttft_p99_ms", "tpot_p99_ms", "completed", "shed",
                "shed_rate")),
)


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    n_requests: int = 32
    rate_hz: float = 50.0             # Poisson arrival rate
    prompt_lens: tuple[int, ...] = (4, 8, 16, 24)   # mixed, sampled
    max_new: tuple[int, ...] = (4, 8, 16)
    vocab: int = 256
    temperature: float = 0.0
    seed: int = 0
    deadline_s: float | None = None
    # > 0: every request's prompt starts with the SAME seeded
    # shared_prefix_tokens-long prefix (a system prompt), and
    # prompt_lens become the per-request TAIL lengths — the workload
    # shape that makes the engine's radix prefix cache earn its keep
    # (the first request prefills the prefix, every later one reuses
    # its blocks). The bench `serving` probe runs this mode.
    shared_prefix_tokens: int = 0
    # --- SLO-class mix (PR 14) ---
    # > 0: every batch_every-th request is class=batch — the mixed
    # workload the isolation drill and the bench `@class` dimension run
    batch_every: int = 0
    # --- adversarial tenant (PR 14) ---
    # one deterministic hostile tenant rides the base workload:
    #   burst     — its arrivals all collapse onto the first one (a
    #               thundering herd from one client)
    #   slowloris — its sinks sleep adversary_secs per token (a client
    #               that reads one byte at a time; in-process runs slow
    #               the sink, wire runs pair with the chaos clause)
    #   oversize  — its prompts balloon to adversary_prompt_len and it
    #               self-identifies as batch (the giant-prompt tenant
    #               chunked prefill exists for)
    # Shaping draws come from a SEPARATE rng AFTER the base draws, so
    # enabling a tenant never shifts the pinned base schedule.
    adversary: str = ""            # "" | burst | slowloris | oversize
    adversary_every: int = 0       # every Nth request is the tenant's
    adversary_secs: float = 0.05   # slowloris per-token stall
    adversary_prompt_len: int = 0  # oversize length (0 = 4x max base)
    # --- rehit churn (PR 20, serve/hostcache.py) ---
    # > 0 (with shared_prefix_tokens): the tiered-KV drill shape. The
    # MIDDLE rehit_churn requests swap the shared prefix for DISTINCT
    # per-request prompts long enough to evict the shared chain from a
    # small device pool; the tail of the workload then re-asks for the
    # original prefix. With --host-cache-mb the re-hit restores from
    # the host spill tier (tier_hits_host > 0, prefill skipped); with
    # the tier off it is a full re-prefill — the delta `obs diff`
    # gates. Churn prompts come from their OWN rng (seed + 0x0C0C),
    # after the base draws, so enabling churn never shifts the pinned
    # base schedule (same discipline as the adversary shaping).
    rehit_churn: int = 0
    rehit_churn_len: int = 0       # churn prompt len (0 = prefix + max tail)


def request_id(seed: int, i: int) -> str:
    """Deterministic, seed-derived request id: the same spec produces
    the same ids run-to-run, so trace fixtures and bench attribution
    keys line up across rounds (and across machines)."""
    return f"load_s{seed}_{i:03d}"


def build_workload(spec: LoadSpec):
    """(arrivals, requests) for one spec — THE workload definition,
    shared by the in-process driver (`run_load`) and the socket-target
    driver (`run_load_socket`) so "the same spec" means the same
    arrival schedule, prompts, budgets, and seeds on either path. The
    rng draw ORDER is pinned (inter-arrivals, shared prefix, then per
    request: tail length, tail, budget, seed) — reordering it would
    silently shift every bench serving row across rounds."""
    rng = np.random.default_rng(spec.seed)
    inter = rng.exponential(1.0 / spec.rate_hz, spec.n_requests)
    arrivals = np.cumsum(inter)
    prefix = (rng.integers(1, spec.vocab, spec.shared_prefix_tokens)
              if spec.shared_prefix_tokens else None)

    def next_prompt() -> np.ndarray:
        tail = rng.integers(1, spec.vocab, rng.choice(spec.prompt_lens))
        return tail if prefix is None else np.concatenate([prefix, tail])

    # base draws first, ALL of them, in the pinned order — class and
    # adversary shaping below reads a separate rng, so the same seed
    # yields the same base workload whatever tenants ride along
    base = [(next_prompt(), int(rng.choice(spec.max_new)),
             int(rng.integers(0, 2**31 - 1)))
            for _ in range(spec.n_requests)]

    cls_of: dict[int, str] = {}
    tenant_of: dict[int, str] = {}
    prompt_of: dict[int, np.ndarray] = {}
    if spec.batch_every > 0:
        for i in range(spec.n_requests):
            if (i + 1) % spec.batch_every == 0:
                cls_of[i] = CLASS_BATCH
    if spec.adversary and spec.adversary_every > 0:
        arng = np.random.default_rng(spec.seed + 0x5EED)
        tenant = f"adv_{spec.adversary}"
        adv = [i for i in range(spec.n_requests)
               if (i + 1) % spec.adversary_every == 0]
        for i in adv:
            tenant_of[i] = tenant
        if spec.adversary == "burst" and adv:
            # thundering herd: every adversary arrival collapses onto
            # the tenant's first — the instant-queue-spike shape the
            # class-aware shed order must absorb batch-first
            arrivals = arrivals.copy()
            arrivals[adv] = arrivals[adv[0]]
        elif spec.adversary == "oversize":
            plen = spec.adversary_prompt_len \
                or 4 * max(spec.prompt_lens)
            for i in adv:
                prompt_of[i] = arng.integers(1, spec.vocab, plen)
                cls_of[i] = CLASS_BATCH
    if spec.rehit_churn > 0 and spec.shared_prefix_tokens:
        crng = np.random.default_rng(spec.seed + 0x0C0C)
        plen = spec.rehit_churn_len \
            or spec.shared_prefix_tokens + max(spec.prompt_lens)
        a = max(1, (spec.n_requests - spec.rehit_churn) // 2)
        for i in range(a, min(a + spec.rehit_churn, spec.n_requests)):
            prompt_of[i] = crng.integers(1, spec.vocab, plen)

    reqs = [
        Request(
            prompt_ids=prompt_of.get(i, base[i][0]),
            max_new_tokens=base[i][1],
            temperature=spec.temperature,
            seed=base[i][2],
            deadline_s=spec.deadline_s,
            id=request_id(spec.seed, i),
            sla_class=cls_of.get(i, CLASS_INTERACTIVE),
            tenant=tenant_of.get(i),
        )
        for i in range(spec.n_requests)
    ]
    return arrivals, reqs


def run_load(engine, spec: LoadSpec) -> dict:
    """Drive one load run to drain; return the serving report.

    Arrivals follow exponential inter-arrival times (a Poisson
    process) pre-drawn from `spec.seed`; prompt contents/lengths and
    decode budgets come from the same rng. Between engine steps the
    driver submits every request whose arrival time has passed —
    closed-loop, so a slow engine sees a burstier queue, exactly like
    a real ingress under fixed offered load."""
    arrivals, reqs = build_workload(spec)
    if spec.adversary == "slowloris" and spec.adversary_secs > 0:
        # the adversarial client that reads one byte at a time: its own
        # sink stalls on every token. The engine charges the stall to
        # the REQUEST's client_write phase (decode gaps are netted of
        # sink time), so the isolation claim — everyone else's TTFT and
        # TPOT hold — is measurable, not hopeful.
        def _slow_sink(rec, _secs=spec.adversary_secs):
            time.sleep(_secs)

        for r in reqs:
            if r.tenant is not None:
                r.sink = _slow_sink
    if spec.shared_prefix_tokens and hasattr(engine, "tracer"):
        # stamp the workload shape on the stream: `obs doctor` uses
        # this to call out a shared-prefix run whose hit counter
        # stayed at zero (a mis-configured prefix cache, not a slow one)
        engine.tracer.event("serve_workload",
                            shared_prefix_tokens=int(spec.shared_prefix_tokens),
                            n_requests=spec.n_requests)

    t0 = _CLOCK()
    submitted = 0
    rejected = 0
    while submitted < spec.n_requests or not engine.idle:
        now = _CLOCK() - t0
        while submitted < spec.n_requests and arrivals[submitted] <= now:
            ok, _reason = engine.submit(reqs[submitted])
            rejected += 0 if ok else 1
            submitted += 1
        if engine.idle:
            if submitted >= spec.n_requests:
                break  # tail request door-rejected with nothing in flight
            # nothing in flight: sleep to the next arrival instead of
            # spinning the scheduler
            nxt = arrivals[submitted] - (_CLOCK() - t0)
            if nxt > 0:
                time.sleep(min(nxt, 0.05))
            continue
        engine.step()
    elapsed = _CLOCK() - t0

    cache = engine.metrics.summary()
    done = [r for r in reqs if r.status == "done"]
    timed_out = sum(1 for r in reqs if r.status == "timed_out")
    ttft_ms = [
        (r.first_token_at - r.submitted_at) * 1e3
        for r in done if r.first_token_at is not None
    ]
    e2e_ms = [
        (r.finished_at - r.submitted_at) * 1e3
        for r in done if r.finished_at is not None
    ]
    tokens = sum(len(r.tokens) for r in done)

    # per-phase tail attribution over the completed requests (the same
    # numbers `request_finished` events carry; see obs/timeline.py for
    # the phase definitions) — p99s ride the bench serving row so
    # `obs diff` gates WHERE the tail went, not just how long it was
    def _p99_ms(vals) -> float | None:
        vals = [v for v in vals if v is not None]
        return round(percentile(vals, 99), 3) if vals else None

    attribution = {
        f"{p}_p99_ms": _p99_ms([r.phases_s()[p] * 1e3 for r in done])
        for p in PHASES
    }
    # dominant phase with COHORT semantics (the same math as obs
    # trace/doctor: average the requests at-or-beyond the e2e p99) —
    # the independent per-phase p99s above can each come from a
    # different request, and naming their max would let bench disagree
    # with the trace tools about the same run
    dominant = cohort_dominant(
        [r.finished_at - r.submitted_at for r in done],
        [r.phases_s() for r in done])

    # per-SLO-class verdict keys: client-observed TTFT per class (from
    # the requests' own stamps), TPOT p99 from the engine's per-class
    # histograms, and the shed split — the isolation drill's whole
    # claim is interactive_ttft holds while batch_shed absorbs the hit
    by_cls = cache.get("by_class") or {}
    per_class: dict = {}
    for cls in SLA_CLASSES:
        cdone = [r for r in done if r.sla_class == cls]
        cttft = [(r.first_token_at - r.submitted_at) * 1e3
                 for r in cdone if r.first_token_at is not None]
        tpot = (by_cls.get(cls) or {}).get("tpot_ms") or {}
        shed = int((by_cls.get(cls) or {}).get("shed", 0))
        n_cls = sum(1 for r in reqs if r.sla_class == cls)
        per_class[f"{cls}_ttft_p99_ms"] = (
            round(percentile(cttft, 99), 3) if cttft else None)
        per_class[f"{cls}_tpot_p99_ms"] = (
            round(tpot["p99"], 3)
            if isinstance(tpot.get("p99"), (int, float)) else None)
        per_class[f"{cls}_completed"] = len(cdone)
        per_class[f"{cls}_shed"] = shed
        per_class[f"{cls}_shed_rate"] = (
            round(shed / n_cls, 4) if n_cls else 0.0)

    return {
        **per_class,
        "requests": spec.n_requests,
        "completed": len(done),
        "rejected": rejected,
        "timed_out": timed_out,
        "reject_rate": round(rejected / spec.n_requests, 4)
        if spec.n_requests else 0.0,
        "tokens": tokens,
        "tokens_per_s": round(tokens / elapsed, 2) if elapsed > 0 else 0.0,
        "ttft_p50_ms": round(percentile(ttft_ms, 50), 3) if ttft_ms else None,
        "ttft_p99_ms": round(percentile(ttft_ms, 99), 3) if ttft_ms else None,
        "e2e_p50_ms": round(percentile(e2e_ms, 50), 3) if e2e_ms else None,
        "e2e_p99_ms": round(percentile(e2e_ms, 99), 3) if e2e_ms else None,
        "elapsed_s": round(elapsed, 3),
        "arrival_rate_hz": spec.rate_hz,
        "slots": engine.cfg.slots,
        "shared_prefix_tokens": spec.shared_prefix_tokens,
        # paged-cache pressure keys (engine metrics roll-up) — these
        # ride the bench `serving` row so `obs diff` gates cache
        # regressions exactly like throughput regressions
        **{k: cache.get(k)
           for k in ("prefix_hit_rate", "prefill_tokens_saved",
                     "preempted", "cow_copies", "blocks_in_use",
                     "hbm_per_req_mb")},
        # tiered KV cache (serve/hostcache.py): lookup tier split and
        # host-tier motion — the `rehit` workload's verdict keys, gated
        # by `obs diff` (hit rate higher-is-better, saved tokens delta)
        **{k: cache.get(k)
           for k in ("tier_hits_device", "tier_hits_host", "tier_miss",
                     "tier_hit_rate_host", "restore_bytes_per_s",
                     "host_cache_mb")},
        # speculative decoding (serve/draft.py): acceptance quality +
        # effective per-slot advance — `obs diff` gates both as
        # higher-is-better on spec-enabled rows (accept_rate is None
        # on a spec-off run, which diff treats as "not measured")
        "accept_rate": (round(cache["accept_rate"], 4)
                        if cache.get("accept_rate") is not None else None),
        "tokens_per_tick": (round(cache["tokens_per_tick"], 4)
                            if cache.get("tokens_per_tick") is not None
                            else None),
        "spec_drafted": cache.get("spec_drafted", 0),
        "spec_accepted": cache.get("spec_accepted", 0),
        "spec_rejected": cache.get("spec_rejected", 0),
        # overload brownout (PR 8): shed/clamp events as rates so
        # `obs diff` gates them across rounds at any request count
        "shed": cache.get("shed", 0),
        "brownout_clamped": cache.get("brownout_clamped", 0),
        "shed_rate": round(cache.get("shed", 0) / spec.n_requests, 4)
        if spec.n_requests else 0.0,
        "clamp_rate": round(
            cache.get("brownout_clamped", 0) / spec.n_requests, 4)
        if spec.n_requests else 0.0,
        **attribution,
        "dominant_phase_p99": dominant,
        # live-plane keys (PR 10): the WINDOWED p99s `obs top` shows —
        # over the engine's last-60s ring, which for a short probe run
        # is the whole run — and the SLO alert counters, so a bench
        # round that fired alerts says so on its serving row and
        # `obs diff` can gate serve_alerts_raised lower-is-better
        "ttft_p99_windowed_ms": _win_p99(engine, "ttft_ms"),
        "tpot_p99_windowed_ms": _win_p99(engine, "tpot_ms"),
        "alerts_raised": cache.get("alerts_raised", 0),
        "alerts_active": cache.get("alerts_active", 0),
        # compile ledger (obs/ledger.py): post-warmup jit-cache growth
        # during the run — `obs diff` pins this at zero (ZERO_PINNED)
        "recompiles": cache.get("recompiles", 0),
    }


def _win_p99(engine, hist: str,
             window_s: float = DEFAULT_WINDOW_S) -> float | None:
    """Windowed p99 of one engine histogram (obs/registry.py ring) —
    None when the window saw nothing."""
    w = engine.metrics.reg.histogram(hist).windowed(window_s)
    p = w.get("p99")
    return round(p, 3) if isinstance(p, (int, float)) else None


def run_load_socket(socket_path: str, spec: LoadSpec, *,
                    request_timeout_s: float = 300.0,
                    session_every: int = 0) -> dict:
    """Drive a LIVE server or router over its unix socket with the same
    seeded workload `run_load` uses in-process — the real wire path:
    one connection per request, ServeClient connect-retry riding
    through any supervised restarts, client-side TTFT/e2e clocks.

    `session_every > 0` stamps `session_id = req_index // session_every`
    on each request, so a router in front gets a deterministic
    session-affinity workload to be sticky about.

    The report carries the client-observable subset of `run_load`'s
    keys (no engine internals — those belong to the server's own
    telemetry), so `obs diff` reads both shapes."""
    import threading

    from hyperion_tpu.serve.client import ServeClient

    arrivals, reqs = build_workload(spec)
    results: list[dict] = [{} for _ in reqs]

    def drive(i: int) -> None:
        req = reqs[i]
        doc = {
            "id": req.id,
            "prompt_ids": np.asarray(req.prompt_ids).tolist(),
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "seed": int(req.seed),
        }
        if req.deadline_s is not None:
            doc["deadline_s"] = float(req.deadline_s)
        if req.sla_class != CLASS_INTERACTIVE:
            doc["class"] = req.sla_class
        if req.tenant is not None:
            doc["tenant"] = req.tenant
        if session_every > 0:
            doc["session_id"] = f"sess_{i // session_every}"
        # the wire-path slowloris: the tenant's own reader stalls
        # between records, starving its socket buffer exactly like a
        # real one-byte-at-a-time client
        stall = (spec.adversary_secs
                 if spec.adversary == "slowloris" and req.tenant
                 else 0.0)
        res = results[i]
        sent = _CLOCK()
        res["submitted_at"] = sent
        expected = 0  # next stream index owed — dup/gap audit
        try:
            with ServeClient(socket_path,
                             timeout_s=request_timeout_s) as c:
                for rec in c.stream(**doc):
                    ev = rec.get("event")
                    if stall > 0:
                        time.sleep(stall)
                    if ev == "token" and rec.get("token") is not None:
                        res.setdefault("first_token_at", _CLOCK())
                        res["tokens"] = res.get("tokens", 0) + 1
                        # exactly-once audit off the wire's stream
                        # index: an index below the expected one is a
                        # DUPLICATE delivery (a failover/resume dedup
                        # bug) — `obs diff` zero-pins the total
                        si = rec.get("i")
                        if isinstance(si, int):
                            if si < expected:
                                res["dup_tokens"] = \
                                    res.get("dup_tokens", 0) + 1
                            else:
                                expected = si + 1
                    elif ev in ("done", "rejected", "timed_out",
                                "error"):
                        res["status"] = ev
                        res["finished_at"] = _CLOCK()
                        # replica-attributed TTFT rides the done record
                        # (serve/server.py): client TTFT minus this is
                        # the time the router + wire owned the request
                        if isinstance(rec.get("ttft_ms"),
                                      (int, float)):
                            res["replica_ttft_ms"] = float(
                                rec["ttft_ms"])
        except (OSError, ConnectionError) as e:
            res["status"] = "error"
            res["error"] = repr(e)
            res["finished_at"] = _CLOCK()

    t0 = _CLOCK()
    threads: list[threading.Thread] = []
    for i in range(spec.n_requests):
        wait = t0 + arrivals[i] - _CLOCK()
        if wait > 0:
            time.sleep(wait)
        t = threading.Thread(target=drive, args=(i,),
                             name=f"load-{i}", daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=request_timeout_s)
    elapsed = _CLOCK() - t0

    done = [r for r in results if r.get("status") == "done"]
    ttft_ms = [(r["first_token_at"] - r["submitted_at"]) * 1e3
               for r in done if "first_token_at" in r]
    e2e_ms = [(r["finished_at"] - r["submitted_at"]) * 1e3
              for r in done if "finished_at" in r]
    # client-side windowed p99: requests whose first token landed in
    # the run's last exposition window — the socket driver cannot read
    # engine rings, so it computes the same "recent" view from its own
    # clocks
    cut = _CLOCK() - DEFAULT_WINDOW_S
    ttft_win = [(r["first_token_at"] - r["submitted_at"]) * 1e3
                for r in done
                if "first_token_at" in r and r["first_token_at"] >= cut]
    # router overhead the CLIENT observed: its own TTFT minus the
    # replica-attributed TTFT the done record carried. Everything the
    # router + wire added — placement, WAL, dispatch gap, relay copies
    # — and nothing the engine did. Directly comparable across fleet
    # sizes, and gated in `obs diff` as serve_router_overhead_p99_ms.
    overhead_ms = [
        max(0.0, (r["first_token_at"] - r["submitted_at"]) * 1e3
            - r["replica_ttft_ms"])
        for r in done
        if "first_token_at" in r and "replica_ttft_ms" in r]
    tokens = sum(r.get("tokens", 0) for r in done)
    rejected = sum(1 for r in results
                   if r.get("status") in ("rejected", "error"))
    return {
        "mode": "socket",
        "requests": spec.n_requests,
        "completed": len(done),
        "rejected": rejected,
        "timed_out": sum(1 for r in results
                         if r.get("status") == "timed_out"),
        "reject_rate": round(rejected / spec.n_requests, 4)
        if spec.n_requests else 0.0,
        "tokens": tokens,
        # exactly-once delivery audit: stream-indexed duplicates seen
        # across ALL requests (zero unless failover/resume dedup broke)
        "duplicate_tokens": sum(r.get("dup_tokens", 0) for r in results),
        "tokens_per_s": round(tokens / elapsed, 2) if elapsed > 0 else 0.0,
        "ttft_p50_ms": round(percentile(ttft_ms, 50), 3) if ttft_ms else None,
        "ttft_p99_ms": round(percentile(ttft_ms, 99), 3) if ttft_ms else None,
        "e2e_p50_ms": round(percentile(e2e_ms, 50), 3) if e2e_ms else None,
        "e2e_p99_ms": round(percentile(e2e_ms, 99), 3) if e2e_ms else None,
        "ttft_p99_windowed_ms": round(percentile(ttft_win, 99), 3)
        if ttft_win else None,
        "router_overhead_p99_ms": round(percentile(overhead_ms, 99), 3)
        if overhead_ms else None,
        "elapsed_s": round(elapsed, 3),
        "arrival_rate_hz": spec.rate_hz,
        "shared_prefix_tokens": spec.shared_prefix_tokens,
    }
