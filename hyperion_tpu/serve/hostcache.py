"""Tiered KV cache — the host-RAM spill tier behind the radix cache.

The radix prefix cache (`serve/blocks.py`) turns a shared prompt into
shared HBM blocks, but its eviction is terminal: under pool pressure a
cold chain is dropped and a later same-prefix request pays the full
re-prefill. On real chips HBM is the scarcest resource in the serving
system while host RAM is ~10x larger and one DMA away — so eviction
should DEMOTE, not delete. This module is the host half of that tier:

  * `HostBlockStore` — evicted full-block prefix chains as host numpy
    buffers under an LRU `--host-cache-mb` budget. Each entry is keyed
    by the chain's full token prefix (root..block inclusive), so a
    later lookup extends a device match by walking consecutive keys:
    device-hit for the first k blocks, host-hit for the next m, miss
    for the rest. Restoring a hit costs one H2D copy per block through
    the engine's eager block-scatter — bit-identical K/V (same dtype
    down and up), zero new executables.
  * `save`/`load` — the store serializes to `<base_dir>/hostcache/`
    on drain (index.json + one raw chains.bin, written atomically), so
    a spilled chain outlives the process and rides the journal's
    recovery path: restart between evict and rehit still restores.
  * `prefix_root_digest` + `HotRootTracker` — the fleet half's
    vocabulary. Replicas advertise their top-k hot prefix roots
    (sha1 token digests, same construction the router's `p:` affinity
    key uses) on heartbeats; the router's cache-aware scoring steers a
    matching request to the replica whose KV already holds the prefix.

Deliberately jax-free (numpy + stdlib only): the router imports the
digest helpers without paying a backend init, and the property tests
drive spill/restore/persistence without a device.
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import OrderedDict

import numpy as np

# Tokens hashed into a prefix-root digest. Matches the router's
# affinity `prefix_tokens` default so an engine-advertised root and the
# router's request-side digest agree without configuration handshakes.
PREFIX_ROOT_TOKENS = 32

# Roots a replica advertises per heartbeat: enough to cover every hot
# system prompt a ~handful-tenant replica serves, small enough that the
# heartbeat record stays a single atomic write.
TOP_ROOTS = 8

# Tier report keys the serving row PROMISES to `obs diff` — the
# check_diff_gates guard fails tier-1 when any of these is missing
# from the diff gate table (a promised-but-ungated key is a metric
# nobody would ever see regress).
TIER_GATED = (
    "serve_tier_hit_rate_host",
    "serve_restore_bytes_per_s",
    "serve_prefill_tokens_saved",
)

INDEX_NAME = "index.json"
CHAINS_NAME = "chains.bin"


def prefix_root_digest(token_ids, n: int = PREFIX_ROOT_TOKENS) -> str | None:
    """Stable digest of a prompt's first `n` token ids — the unit of
    cache-aware routing. Same construction as the router's `p:`
    affinity key (comma-joined ints, sha1, 16 hex chars) so the two
    vocabularies can never drift; None for an empty prompt."""
    ids = [int(t) for t in list(token_ids)[:n]]
    if not ids:
        return None
    return hashlib.sha1(
        ",".join(str(t) for t in ids).encode()).hexdigest()[:16]


class HotRootTracker:
    """Recency-ordered set of prefix-root digests this engine served —
    what the replica advertises on its heartbeat. Bounded (`cap`) so a
    long-lived engine's tracker never grows with traffic; `top()`
    returns most-recent-first, which is exactly the k the router should
    trust most."""

    def __init__(self, cap: int = 64):
        self.cap = cap
        self._roots: OrderedDict[str, None] = OrderedDict()

    def note(self, digest: str | None) -> None:
        if not digest:
            return
        self._roots.pop(digest, None)
        self._roots[digest] = None
        while len(self._roots) > self.cap:
            self._roots.popitem(last=False)

    def top(self, k: int = TOP_ROOTS) -> list[str]:
        return list(self._roots)[-k:][::-1]

    def __len__(self) -> int:
        return len(self._roots)


class HostBlockStore:
    """Evicted prefix chains in host RAM under an LRU byte budget.

    Keys are the chain's FULL token prefix (a tuple covering every
    position from the root through this block), so consecutive chain
    links are independent entries: `match` extends a device hit of k
    full blocks by probing `tokens[:k*bs+bs]`, `tokens[:k*bs+2*bs]`,
    ... and a mid-chain LRU eviction simply shortens what a given
    device base can restore. Payloads are `[n_layers, 2(k/v),
    block_size, n_kv_heads, head_dim]` host arrays in the pool's own
    dtype — the D2H/H2D round trip is bit-exact, which is what keeps a
    restored stream identical to the never-evicted run.

    Content under a key is immutable by the radix invariant (full
    blocks are never written again), so a re-spill of a key the store
    already holds is a no-op refresh, never an overwrite hazard."""

    def __init__(self, budget_mb: int, block_size: int):
        if budget_mb <= 0:
            raise ValueError(f"host cache budget must be > 0 MB, "
                             f"got {budget_mb}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.budget_bytes = int(budget_mb) * 2 ** 20
        self.block_size = block_size
        self._chains: OrderedDict[tuple[int, ...], np.ndarray] = \
            OrderedDict()
        self.bytes_used = 0
        # lifetime tallies — the store's own evidence for doctor/tests
        self.spills = 0          # chains accepted by put()
        self.restores = 0        # blocks handed back by match()
        self.evictions = 0       # chains LRU-dropped for budget
        self.rejected = 0        # puts refused (payload alone > budget)

    # ------------------------------------------------------------ reads

    def __len__(self) -> int:
        return len(self._chains)

    @property
    def occupancy_mb(self) -> float:
        return self.bytes_used / 2 ** 20

    def match(self, tokens, start: int, limit: int) -> list[np.ndarray]:
        """Consecutive spilled blocks extending a device match: `start`
        is the device full-block coverage in tokens (a multiple of
        block_size), `limit` caps matched positions (callers pass
        len-1, the radix rule: one token must remain to prefill).
        Returns the payloads in chain order; every hit refreshes LRU
        recency. Empty list = the host tier has nothing contiguous."""
        bs = self.block_size
        toks = [int(t) for t in list(tokens)[:limit]]
        out: list[np.ndarray] = []
        pos = start
        while pos + bs <= limit:
            key = tuple(toks[:pos + bs])
            payload = self._chains.get(key)
            if payload is None:
                break
            self._chains.move_to_end(key)
            out.append(payload)
            pos += bs
        self.restores += len(out)
        return out

    def stats(self) -> dict:
        return {
            "chains": len(self._chains),
            "bytes": self.bytes_used,
            "mb": round(self.occupancy_mb, 3),
            "spills": self.spills,
            "restores": self.restores,
            "evictions": self.evictions,
            "rejected": self.rejected,
        }

    # ----------------------------------------------------------- writes

    def put(self, chain_tokens, payload: np.ndarray) -> bool:
        """Accept one evicted block: `chain_tokens` is the FULL prefix
        (length a multiple of block_size, the last block_size ids being
        this block's own), `payload` its host K/V. Returns False when
        the payload alone exceeds the whole budget (counted, never
        raised — spilling is opportunistic)."""
        key = tuple(int(t) for t in list(chain_tokens))
        if not key or len(key) % self.block_size != 0:
            raise ValueError(
                f"chain key length {len(key)} is not a multiple of "
                f"block_size {self.block_size}")
        if key in self._chains:
            # immutable content: refresh recency, keep the incumbent
            self._chains.move_to_end(key)
            return True
        payload = np.asarray(payload)
        if payload.nbytes > self.budget_bytes:
            self.rejected += 1
            return False
        self._chains[key] = payload
        self.bytes_used += payload.nbytes
        self.spills += 1
        while self.bytes_used > self.budget_bytes:
            _, old = self._chains.popitem(last=False)
            self.bytes_used -= old.nbytes
            self.evictions += 1
        return True

    def clear(self) -> None:
        self._chains.clear()
        self.bytes_used = 0

    # ------------------------------------------------------ persistence

    def save(self, dirpath: str) -> dict:
        """Serialize the store to `dirpath` (index.json + chains.bin,
        both written to temp names then renamed — a crash mid-save
        leaves the previous snapshot intact). Chains are written
        oldest-first so `load` rebuilds the exact LRU order. Returns
        the stats dict of what was written."""
        os.makedirs(dirpath, exist_ok=True)
        index: list[dict] = []
        offset = 0
        bin_tmp = os.path.join(dirpath, CHAINS_NAME + ".tmp")
        with open(bin_tmp, "wb") as f:
            for key, payload in self._chains.items():
                raw = payload.tobytes()
                f.write(raw)
                index.append({
                    "tokens": list(key),
                    "shape": list(payload.shape),
                    "dtype": payload.dtype.name,
                    "offset": offset,
                    "nbytes": len(raw),
                })
                offset += len(raw)
        idx_tmp = os.path.join(dirpath, INDEX_NAME + ".tmp")
        with open(idx_tmp, "w") as f:
            json.dump({"v": 1, "block_size": self.block_size,
                       "chains": index}, f)
        os.replace(bin_tmp, os.path.join(dirpath, CHAINS_NAME))
        os.replace(idx_tmp, os.path.join(dirpath, INDEX_NAME))
        return self.stats()

    def load(self, dirpath: str) -> int:
        """Rebuild from a prior `save` (missing/corrupt files load
        nothing — persistence is an optimization, never a crash).
        Entries load oldest-first, re-running the LRU budget, so a
        shrunk `--host-cache-mb` keeps the most recent chains. Returns
        chains loaded."""
        idx_path = os.path.join(dirpath, INDEX_NAME)
        bin_path = os.path.join(dirpath, CHAINS_NAME)
        try:
            with open(idx_path) as f:
                index = json.load(f)
            raw = open(bin_path, "rb").read()
        except (OSError, ValueError):
            return 0
        if index.get("block_size") != self.block_size:
            return 0  # a different pool geometry: the chains are alien
        loaded = 0
        for ent in index.get("chains", []):
            try:
                dtype = np.dtype(ent["dtype"])
            except TypeError:
                # a dtype numpy can't name without its extension module
                # (e.g. bfloat16 via ml_dtypes) — resolve it lazily
                try:
                    import ml_dtypes

                    dtype = np.dtype(getattr(ml_dtypes, ent["dtype"]))
                except (ImportError, AttributeError, TypeError):
                    continue
            off, nb = int(ent["offset"]), int(ent["nbytes"])
            if off + nb > len(raw):
                continue
            payload = np.frombuffer(
                raw[off:off + nb], dtype=dtype).reshape(ent["shape"])
            if self.put(ent["tokens"], payload.copy()):
                loaded += 1
        return loaded


def ungated_tier_keys(diff_metrics: dict) -> list[str]:
    """Tier keys promised by `TIER_GATED` but absent from the obs diff
    gate table — `scripts/check_diff_gates.py` fails tier-1 on any."""
    return sorted(k for k in TIER_GATED if k not in diff_metrics)
