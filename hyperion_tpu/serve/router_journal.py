"""Router WAL — the write-ahead log that makes the ROUTER as
crash-safe as the replicas it fronts.

PR 9 gave every engine replica a request journal; a replica crash
replays owed work bit-identically and the router's stream-indexed
dedup keeps delivery exactly-once. But the router itself held its
dispatch assignments and high-water marks only in memory: a router
crash stranded every in-flight stream even though the replicas behind
it kept serving. This WAL closes that gap with the same append-only
JSONL + batched-fsync + torn-tail discipline as `serve/journal.py`
(it subclasses `RequestJournal` for exactly that plumbing), with a
router-shaped record vocabulary:

    {"k":"dispatch","id":...,"line":"<original wire line>",
     "replica":R,"session":KEY,"n":REDISPATCHES}
                                       one per (re)dispatch; the FIRST
                                       carries the request's original
                                       wire line — everything a new
                                       router life needs to re-dispatch
    {"k":"hwm","id":...,"i":N}         high-water mark: N tokens
                                       forwarded to the client; appended
                                       and kernel-flushed BEFORE the
                                       client write (fsync batched),
                                       mirroring the replica journal's
                                       journal-before-sink ordering
    {"k":"done","id":...,"outcome":..} terminal (done/rejected/...)
    {"k":"close"}                      clean shutdown — recover nothing

Recovery (`recover()`) returns the orphans: requests with a dispatch
record but no terminal one. Each carries its original wire line, the
last replica it was placed on, its session key, and its journaled
high-water mark. A restarted router re-dispatches them through the
existing seed-deterministic recompute + `StreamDedup` path with the
dedup floor seeded from the mark — the union stream across router
lives stays bit-identical and duplicate-free, the same contract PR 9
proved for replica death. (The hwm is written before the client write,
so it can run at most one token AHEAD of what the client actually
received; the client-side `resume {request_id, next_index}` protocol
closes even that window — the client's own index is authoritative
when one reconnects.)

Compaction (`RequestJournal._compact`) applies here too: terminal
streams drop out at recovery when they dominate the file, pending work
preserved byte-exactly.
"""

from __future__ import annotations

import dataclasses
import json

from hyperion_tpu.serve.journal import RequestJournal


@dataclasses.dataclass
class OrphanedDispatch:
    """One in-flight request a dead router life still owes its client."""

    id: str
    line: str            # the original wire line, verbatim
    replica: int | None  # last placement (evidence; re-dispatch re-chooses)
    session: str | None  # affinity key at dispatch time
    hwm: int             # tokens forwarded before the crash
    dispatches: int      # placements so far (failovers included)

    @property
    def doc(self) -> dict | None:
        try:
            doc = json.loads(self.line)
        except (json.JSONDecodeError, TypeError):
            return None
        return doc if isinstance(doc, dict) else None


class RouterJournal(RequestJournal):
    """Append-only router WAL — `RequestJournal`'s write plumbing
    (locked whole-line appends, kernel flush every append, batched
    fsync, OSError degrades instead of crashing, torn final line
    tolerated) under the router's record vocabulary."""

    # ------------------------------------------------------------ write

    def dispatch(self, rid: str, *, line: str, replica: int,
                 session: str | None, n: int = 0,
                 trace: dict | None = None) -> None:
        """One placement decision, durable before the replica sees the
        request. The wire line rides only the first record per request
        (re-dispatches reference it) — the WAL must not grow by the
        prompt length on every failover. The hop context rides every
        record (the stored line stays exactly what the client sent), so
        a WAL post-mortem can cite the same trace ids the fleet trace
        renders."""
        rec = {"k": "dispatch", "id": rid,
               "line": line if n == 0 else None,
               "replica": int(replica), "session": session,
               "n": int(n)}
        if trace is not None:
            rec["trace"] = trace
        self._append(rec, sync=True)

    def hwm(self, rid: str, delivered: int) -> None:
        """High-water mark: `delivered` tokens forwarded. Appended
        ahead of the client write (batched fsync, like `tok`)."""
        self._append({"k": "hwm", "id": rid, "i": int(delivered)},
                     sync=False)

    def done(self, rid: str, outcome: str) -> None:
        self._append({"k": "done", "id": rid, "outcome": outcome},
                     sync=True)

    # ------------------------------------------------------------- read

    def _parse(self):
        """(state_by_id, dispatch_order, clean) — same reader contract
        as the replica journal: torn lines skipped, a `close` marker
        settles everything before it."""
        state: dict[str, dict] = {}
        order: list[str] = []
        clean = False
        try:
            lines = self.path.read_text(encoding="utf-8").splitlines()
        except OSError:
            return {}, [], False
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn write — the crash signature itself
            if not isinstance(rec, dict):
                continue
            k = rec.get("k")
            if k == "close":
                state.clear()
                order.clear()
                clean = True
                continue
            clean = False
            rid = rec.get("id")
            if not rid:
                continue
            st = state.setdefault(
                rid, {"line": None, "replica": None, "session": None,
                      "hwm": 0, "dispatches": 0, "done": None})
            if k == "dispatch":
                if st["dispatches"] == 0:
                    order.append(rid)
                st["dispatches"] += 1
                # a dispatch AFTER a terminal re-opens the request: a
                # client whose wire reset (done "client_gone") resumed
                # it in the same router life
                st["done"] = None
                if rec.get("line") is not None and st["line"] is None:
                    st["line"] = rec["line"]
                st["replica"] = rec.get("replica")
                st["session"] = rec.get("session")
            elif k == "hwm" and rec.get("i") is not None:
                st["hwm"] = max(st["hwm"], int(rec["i"]))
            elif k == "done":
                st["done"] = rec.get("outcome") or "done"
        return state, order, clean

    def recover(self) -> tuple[list[OrphanedDispatch], bool]:
        """Read the WAL; return `(orphans, clean)` — the in-flight
        requests a dead router life still owes, in dispatch order, and
        whether the file ends in a clean close (orphans then empty).
        Terminal-dominated files compact on the way out."""
        state, order, clean = self._parse()
        orphans: list[OrphanedDispatch] = []
        for rid in order:
            st = state[rid]
            if st["done"] is not None or clean or st["line"] is None:
                continue
            rep = st["replica"]
            orphans.append(OrphanedDispatch(
                id=rid, line=st["line"],
                replica=int(rep) if isinstance(rep, int) else None,
                session=st["session"], hwm=int(st["hwm"]),
                dispatches=int(st["dispatches"])))
        self._compact({o.id for o in orphans}, clean=clean)
        return orphans, clean

    def pending_count(self) -> int:
        state, order, clean = self._parse()
        if clean:
            return 0
        return sum(1 for rid in order if state[rid]["done"] is None)

    def tail(self, n: int = 8) -> list[dict]:
        """Last `n` parseable records — the doctor's post-mortem
        evidence (reader-side, works on a dead router's WAL)."""
        try:
            lines = self.path.read_text(encoding="utf-8").splitlines()
        except OSError:
            return []
        out: list[dict] = []
        for line in reversed(lines):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
            if len(out) >= n:
                break
        return list(reversed(out))
