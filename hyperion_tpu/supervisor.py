"""Shared restart-supervisor core — one crash loop, two policies.

PR 3 built `train/supervisor.py` for the training path: run the child,
on a nonzero exit consult `obs doctor`, decide restart-vs-give-up, back
off exponentially, stamp the attempt lineage. The serve path (PR 8)
needs the identical skeleton with a different policy brain — a serving
child has no "preempted with a checkpoint waiting" exit, but it does
have a request journal to replay and a heartbeat file a hung engine
stops writing. So the loop itself lives here, policy-free:

  * `supervise_loop(child_argv, decide=...)` owns the mechanics every
    supervisor shares: the `HYPERION_ATTEMPT` lineage stamp, the
    exit-0 / usage-error fast paths, the restart budget, exponential
    backoff with deterministic jitter, and the give-up exit code.
  * `decide(rc)` is the policy: given the child's exit code it returns
    a `Decision` — stop with a verdict, or restart (optionally "free",
    not burning the budget; optionally "immediate", skipping backoff).
    Consulting the doctor, quarantining checkpoints, printing triage —
    all policy, all in the caller.
  * `heartbeat_watchdog(...)` wraps a child run with liveness: a child
    whose heartbeat file goes stale past `stale_s` is SIGKILLed and
    reported as hung (negative rc), because a wedged serve loop never
    exits on its own — the doctor's staleness rule, enforced live.

The module is deliberately jax-free (it must stay responsive while a
child holds a dead backend) and import-light: `train/supervisor.py`
and `serve/server.py` both build on it without pulling each other in.

Exit-code contract (shared; `scripts/tpu_watch.sh` branches on it):
    0   the (possibly restarted) run finished
    2   usage error passed through — argparse rejections don't heal
    3   gave up: restart budget exhausted; a human should look
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import subprocess
import time
from pathlib import Path
from typing import Callable

EXIT_OK = 0
EXIT_USAGE = 2
EXIT_GAVE_UP = 3
EXIT_HEALTH_ABORT = 4   # trainer: health policy aborted (diverged)
EXIT_PREEMPTED = 75     # trainer: clean preemption checkpoint, resumable

ATTEMPT_ENV = "HYPERION_ATTEMPT"

# synthetic rc the heartbeat watchdog reports after killing a hung
# child: negative like subprocess's signal convention, distinct from
# -SIGKILL so a policy can tell "we killed it for staleness" from
# "the platform killed it"
RC_HUNG = -1000


@dataclasses.dataclass(frozen=True)
class Decision:
    """One policy verdict for one child exit."""
    action: str                 # "stop" | "restart"
    rc: int = EXIT_GAVE_UP      # returned when action == "stop"
    free: bool = False          # restart without burning the budget
    immediate: bool = False     # restart without backoff

    @classmethod
    def stop(cls, rc: int) -> "Decision":
        return cls("stop", rc=rc)

    @classmethod
    def restart(cls, *, free: bool = False,
                immediate: bool = False) -> "Decision":
        return cls("restart", free=free, immediate=immediate)


def run_child(argv: list[str], env: dict) -> int:
    return subprocess.call(argv, env=env)


def strip_flags(argv: list[str], bare: set[str],
                valued: set[str]) -> list[str]:
    """Child command = supervisor command minus the supervision flags —
    a supervised child must never recursively supervise. `bare` flags
    are removed alone; `valued` flags take one argument (both the
    two-token and `--flag=value` spellings are handled)."""
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
        elif a in bare:
            pass
        elif a in valued:
            skip = True
        elif any(a.startswith(f + "=") for f in valued):
            pass
        else:
            out.append(a)
    return out


def heartbeat_watchdog(hb_path: str | Path | None, stale_s: float,
                       poll_s: float = 1.0,
                       log: Callable[[str], None] = print,
                       on_spawn: Callable | None = None,
                       popen_kwargs: dict | None = None,
                       ) -> Callable[[list, dict], int]:
    """A `run_child` that SIGKILLs the child when its heartbeat file
    goes stale — the live half of the doctor's hung verdict. Returns
    `RC_HUNG` for a watchdog kill so the policy can name it. With no
    heartbeat path (telemetry off) it degrades to a plain wait: a hung
    child then hangs the supervisor too, which is at least visible.
    `on_spawn(proc)` observes each child Popen (the router uses it to
    keep a signalling handle on every replica); `popen_kwargs` extends
    the spawn (the router redirects replica stdout to stderr so chaos
    chatter never lands on the client wire)."""
    hb_path = Path(hb_path) if hb_path else None

    def _run(argv: list[str], env: dict) -> int:
        start_wall = time.time()
        proc = subprocess.Popen(argv, env=env, **(popen_kwargs or {}))
        if on_spawn is not None:
            on_spawn(proc)
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            if hb_path is not None and stale_s > 0:
                try:
                    mtime = hb_path.stat().st_mtime
                except OSError:
                    mtime = start_wall  # no beat yet
                # clock from THIS child's start or its newest beat,
                # whichever is later: a stale file the previous
                # (crashed) child left must not get a fresh child
                # killed before its first beat — and a child that
                # wedges before ever beating still dies on time
                age = time.time() - max(mtime, start_wall)
                if age > stale_s:
                    log(f"[supervisor] heartbeat stale "
                        f"({age:.0f}s > {stale_s:.0f}s); killing hung "
                        f"child pid {proc.pid}")
                    proc.send_signal(signal.SIGKILL)
                    proc.wait()
                    return RC_HUNG
            time.sleep(poll_s)

    return _run


def supervise_loop(
    child_argv: list[str],
    *,
    decide: Callable[[int], Decision],
    max_restarts: int = 2,
    backoff_s: float = 1.0,
    max_backoff_s: float = 30.0,
    run_child: Callable[[list, dict], int] = run_child,
    sleep=time.sleep,
    label: str = "supervisor",
    log: Callable[[str], None] | None = None,
) -> int:
    """Run `child_argv` under restart supervision with `decide` as the
    policy. `run_child`/`sleep` are injectable for tests; children are
    stamped `HYPERION_ATTEMPT=<k>` so heartbeats and `train_start`/
    `serve_start` events carry the restart lineage `obs doctor`
    reports. `log` redirects the supervisor's own chatter — the serve
    supervisor MUST log to stderr, because its children's stdout IS the
    client's JSONL wire stream."""
    if log is None:
        def log(msg):  # trainer default: stdout, where the tests grep
            print(msg, flush=True)
    rng = random.Random(0)
    restarts = 0
    attempt = 0
    while True:
        env = {**os.environ, ATTEMPT_ENV: str(attempt)}
        log(f"[{label}] attempt {attempt}: {' '.join(child_argv)}")
        rc = run_child(child_argv, env)
        if rc == EXIT_OK:
            if attempt:
                log(f"[{label}] run completed after {attempt} "
                    "restart(s)")
            return EXIT_OK
        if rc == EXIT_USAGE:
            log(f"[{label}] usage error (exit 2); not restarting")
            return rc

        d = decide(rc)
        if d.action == "stop":
            return d.rc
        if not d.free and restarts >= max_restarts:
            log(f"[{label}] giving up after {restarts} restart(s) "
                f"(--max-restarts {max_restarts}); last exit {rc}")
            return EXIT_GAVE_UP
        if not d.free:
            restarts += 1
        attempt += 1
        if d.immediate:
            delay = 0.0
        else:
            delay = min(backoff_s * (2.0 ** (restarts - 1)), max_backoff_s)
            delay *= 1.0 + rng.uniform(-0.25, 0.25)
        if delay:
            log(f"[{label}] restarting in {delay:.1f}s "
                f"(restart {restarts}/{max_restarts})")
            sleep(delay)
