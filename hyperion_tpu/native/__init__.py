"""Native (C++) runtime components, built on first use (see build.py):
recordio (mmap data store) and coord (host rendezvous/barrier/health)."""
