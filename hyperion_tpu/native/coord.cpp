// coord: host-side rendezvous + barrier + failure detection over TCP.
//
// Role (SURVEY §2.3, §5.3): the reference's host-coordination plane is
// torchrun's env:// rendezvous plus NCCL's watchdog timeouts
// (distributed_utils.py:101-112, run_language_fsdp.sh:8-12). JAX's
// coordinator covers rendezvous for collectives; this in-tree native
// layer adds what the reference *operationally* relied on and JAX does
// not expose: a pre-flight host handshake with hard timeouts, named
// barriers usable outside any JAX context (e.g. around checkpoint IO),
// and peer-death detection (a closed socket fails the barrier rather
// than hanging for the collective timeout).
//
// Protocol: coordinator (process 0) accepts `world-1` connections; each
// worker sends HELLO{rank}. A barrier is BARRIER{seq} from every rank;
// the coordinator replies RELEASE{seq} to all once the set is complete.
// All reads honor a deadline; any socket error marks the peer dead and
// fails subsequent barriers fast. Consumed via ctypes (no pybind11).

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <vector>

namespace {

constexpr uint32_t kHello = 0x48454C4F;    // "HELO"
constexpr uint32_t kBarrier = 0x42415252;  // "BARR"
constexpr uint32_t kRelease = 0x52454C53;  // "RELS"

struct Msg {
  uint32_t kind;
  uint32_t value;
};

int64_t now_ms() {
  using namespace std::chrono;
  return duration_cast<milliseconds>(
             steady_clock::now().time_since_epoch()).count();
}

// Reads exactly n bytes before deadline_ms; 0 ok, -1 error/peer-dead,
// -2 timeout.
int read_full(int fd, void* buf, size_t n, int64_t deadline_ms) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    int64_t left = deadline_ms - now_ms();
    if (left <= 0) return -2;
    pollfd pfd{fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(left));
    if (pr < 0 && errno != EINTR) return -1;
    if (pr == 0) return -2;
    if (pr < 0) continue;
    ssize_t got = ::recv(fd, p, n, 0);
    if (got <= 0) return -1;  // 0 = orderly shutdown → peer dead
    p += got;
    n -= got;
  }
  return 0;
}

int write_full(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t sent = ::send(fd, p, n, MSG_NOSIGNAL);
    if (sent <= 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += sent;
    n -= sent;
  }
  return 0;
}

struct Coord {
  bool is_coordinator = false;
  int world = 0;
  int listen_fd = -1;
  int sock = -1;                  // worker: connection to coordinator
  std::vector<int> peers;         // coordinator: sockets by rank (0 unused)
  std::vector<uint8_t> alive;     // coordinator: liveness by rank
  uint32_t seq = 0;
};

void set_opts(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace

extern "C" {

// Coordinator (rank 0): listen on port and accept world-1 HELLOs.
// Returns handle or null. timeout_ms bounds the whole rendezvous.
void* hypcoord_serve(int port, int world, int timeout_ms) {
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return nullptr;
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(lfd, world) != 0) {
    ::close(lfd);
    return nullptr;
  }
  Coord* c = new Coord();
  c->is_coordinator = true;
  c->world = world;
  c->listen_fd = lfd;
  c->peers.assign(world, -1);
  c->alive.assign(world, 0);
  c->alive[0] = 1;

  int64_t deadline = now_ms() + timeout_ms;
  int joined = 1;  // self
  while (joined < world) {
    int64_t left = deadline - now_ms();
    if (left <= 0) break;
    pollfd pfd{lfd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, static_cast<int>(left));
    if (pr <= 0) {
      if (pr < 0 && errno == EINTR) continue;
      break;
    }
    int fd = ::accept(lfd, nullptr, nullptr);
    if (fd < 0) continue;
    set_opts(fd);
    Msg m{};
    if (read_full(fd, &m, sizeof(m), deadline) != 0 || m.kind != kHello ||
        m.value >= static_cast<uint32_t>(world) || c->peers[m.value] != -1) {
      ::close(fd);
      continue;
    }
    c->peers[m.value] = fd;
    c->alive[m.value] = 1;
    ++joined;
  }
  if (joined < world) {
    for (int fd : c->peers) if (fd >= 0) ::close(fd);
    ::close(lfd);
    delete c;
    return nullptr;
  }
  return c;
}

// Worker (rank > 0): connect + HELLO. Returns handle or null.
void* hypcoord_connect(const char* host, int port, int rank, int timeout_ms) {
  int64_t deadline = now_ms() + timeout_ms;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return nullptr;
  int fd = -1;
  while (now_ms() < deadline) {  // retry until the coordinator is up
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) break;
    ::close(fd);
    fd = -1;
    ::usleep(50 * 1000);
  }
  if (fd < 0) return nullptr;
  set_opts(fd);
  Msg hello{kHello, static_cast<uint32_t>(rank)};
  if (write_full(fd, &hello, sizeof(hello)) != 0) {
    ::close(fd);
    return nullptr;
  }
  Coord* c = new Coord();
  c->world = 0;  // unknown/unneeded on workers
  c->sock = fd;
  return c;
}

// Named barrier. 0 ok, -1 peer failure, -2 timeout, -3 bad handle.
int hypcoord_barrier(void* handle, int timeout_ms) {
  Coord* c = static_cast<Coord*>(handle);
  if (!c) return -3;
  int64_t deadline = now_ms() + timeout_ms;
  uint32_t seq = ++c->seq;
  if (c->is_coordinator) {
    for (int rank = 1; rank < c->world; ++rank) {
      if (!c->alive[rank]) return -1;
      Msg m{};
      int rc = read_full(c->peers[rank], &m, sizeof(m), deadline);
      if (rc != 0 || m.kind != kBarrier || m.value != seq) {
        if (rc == -2) return -2;
        c->alive[rank] = 0;  // dead peer: fail fast from now on
        return -1;
      }
    }
    Msg rel{kRelease, seq};
    int ret = 0;
    for (int rank = 1; rank < c->world; ++rank) {
      if (write_full(c->peers[rank], &rel, sizeof(rel)) != 0) {
        c->alive[rank] = 0;
        ret = -1;
      }
    }
    return ret;
  }
  Msg m{kBarrier, seq};
  if (write_full(c->sock, &m, sizeof(m)) != 0) return -1;
  Msg rel{};
  int rc = read_full(c->sock, &rel, sizeof(rel), deadline);
  if (rc == -2) return -2;
  if (rc != 0 || rel.kind != kRelease || rel.value != seq) return -1;
  return 0;
}

// Coordinator-side liveness count (workers return -1).
int hypcoord_alive_count(void* handle) {
  Coord* c = static_cast<Coord*>(handle);
  if (!c || !c->is_coordinator) return -1;
  int n = 0;
  for (uint8_t a : c->alive) n += a;
  return n;
}

void hypcoord_close(void* handle) {
  Coord* c = static_cast<Coord*>(handle);
  if (!c) return;
  if (c->sock >= 0) ::close(c->sock);
  for (int fd : c->peers) if (fd >= 0) ::close(fd);
  if (c->listen_fd >= 0) ::close(c->listen_fd);
  delete c;
}

}  // extern "C"
