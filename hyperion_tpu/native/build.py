"""Build-on-first-use for the native (C++) runtime components.

No pybind11 in the image (task environment), so the extensions are plain
C-ABI shared objects compiled with g++ and loaded via ctypes. Artifacts
are cached next to the sources in `_build/` keyed by a source hash, so a
source edit triggers a rebuild and an unchanged tree never recompiles.
A `Makefile` in this directory builds the same objects for ahead-of-time
packaging.
"""

from __future__ import annotations

import ctypes
import hashlib
import subprocess
from pathlib import Path

_DIR = Path(__file__).parent
_BUILD = _DIR / "_build"
_CXX_FLAGS = ["-O2", "-std=c++17", "-shared", "-fPIC", "-Wall"]


class NativeBuildError(RuntimeError):
    pass


def _source_hash(src: Path) -> str:
    return hashlib.sha256(src.read_bytes()).hexdigest()[:16]


def shared_object(name: str) -> Path:
    """Compile `<name>.cpp` → cached `.so`; return its path."""
    src = _DIR / f"{name}.cpp"
    if not src.exists():
        raise NativeBuildError(f"no such native source: {src}")
    out = _BUILD / f"{name}-{_source_hash(src)}.so"
    if out.exists():
        return out
    _BUILD.mkdir(exist_ok=True)
    cmd = ["g++", *_CXX_FLAGS, "-o", str(out), str(src)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"g++ failed for {src.name}:\n{proc.stderr[-2000:]}"
        )
    # drop stale builds of the same unit
    for old in _BUILD.glob(f"{name}-*.so"):
        if old != out:
            old.unlink(missing_ok=True)
    return out


def load(name: str) -> ctypes.CDLL:
    return ctypes.CDLL(str(shared_object(name)))
