// recordio: memory-mapped fixed-record binary storage for training data.
//
// Role (SURVEY §2.3): the reference's tokenized corpora live in HF/Arrow
// files whose zero-copy reads come from the Arrow C++ core; this is the
// in-tree native equivalent — an mmap-backed record file the Python data
// layer reads without copying, with per-host shard windows for
// multi-host input pipelines.
//
// Format (little-endian):
//   [0:8)    magic "HYPREC01"
//   [8:16)   u64 record_count
//   [16:24)  u64 record_bytes       (fixed-size records)
//   [24:32)  u64 reserved
//   [32:...) payload, record_count * record_bytes
//
// The C ABI below is consumed via ctypes (no pybind11 in the image).
// Thread-safety: handles are immutable after open; concurrent reads are
// safe (mmap + pread semantics).

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'H', 'Y', 'P', 'R', 'E', 'C', '0', '1'};
constexpr uint64_t kHeaderBytes = 32;

struct Header {
  char magic[8];
  uint64_t count;
  uint64_t record_bytes;
  uint64_t reserved;
};

struct Reader {
  int fd = -1;
  const uint8_t* base = nullptr;  // whole-file mapping
  uint64_t file_bytes = 0;
  uint64_t count = 0;
  uint64_t record_bytes = 0;
};

}  // namespace

extern "C" {

// Writes a complete record file in one call. Returns 0 on success.
int hyprec_write(const char* path, const void* data, uint64_t count,
                 uint64_t record_bytes) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  Header h{};
  std::memcpy(h.magic, kMagic, 8);
  h.count = count;
  h.record_bytes = record_bytes;
  int ok = std::fwrite(&h, sizeof(h), 1, f) == 1 &&
           (count == 0 ||
            std::fwrite(data, record_bytes, count, f) == count);
  return std::fclose(f) == 0 && ok ? 0 : -2;
}

// Opens and mmaps a record file. Returns a handle (heap pointer) or null.
void* hyprec_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 || static_cast<uint64_t>(st.st_size) < kHeaderBytes) {
    ::close(fd);
    return nullptr;
  }
  void* mapped = ::mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (mapped == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const Header* h = static_cast<const Header*>(mapped);
  if (std::memcmp(h->magic, kMagic, 8) != 0 ||
      kHeaderBytes + h->count * h->record_bytes !=
          static_cast<uint64_t>(st.st_size)) {
    ::munmap(mapped, st.st_size);
    ::close(fd);
    return nullptr;
  }
  Reader* r = new Reader();
  r->fd = fd;
  r->base = static_cast<const uint8_t*>(mapped);
  r->file_bytes = st.st_size;
  r->count = h->count;
  r->record_bytes = h->record_bytes;
  // training access is random (shuffled epochs)
  ::madvise(mapped, st.st_size, MADV_RANDOM);
  return r;
}

uint64_t hyprec_count(const void* handle) {
  return handle ? static_cast<const Reader*>(handle)->count : 0;
}

uint64_t hyprec_record_bytes(const void* handle) {
  return handle ? static_cast<const Reader*>(handle)->record_bytes : 0;
}

// Pointer to record i inside the mapping (zero-copy; valid until close).
const void* hyprec_record(const void* handle, uint64_t i) {
  const Reader* r = static_cast<const Reader*>(handle);
  if (!r || i >= r->count) return nullptr;
  return r->base + kHeaderBytes + i * r->record_bytes;
}

// Gathers `n` records by index into `out` (n * record_bytes). The batch
// assembly loop the Python layer would otherwise do row-by-row. -1 on
// any out-of-range index.
int hyprec_gather(const void* handle, const uint64_t* indices, uint64_t n,
                  void* out) {
  const Reader* r = static_cast<const Reader*>(handle);
  if (!r) return -1;
  uint8_t* dst = static_cast<uint8_t*>(out);
  const uint8_t* payload = r->base + kHeaderBytes;
  for (uint64_t j = 0; j < n; ++j) {
    if (indices[j] >= r->count) return -1;
    std::memcpy(dst + j * r->record_bytes,
                payload + indices[j] * r->record_bytes, r->record_bytes);
  }
  return 0;
}

void hyprec_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r) return;
  ::munmap(const_cast<uint8_t*>(r->base), r->file_bytes);
  ::close(r->fd);
  delete r;
}

}  // extern "C"
