"""Metrics logging and scaling reports."""

from hyperion_tpu.metrics.csv_logger import SCHEMAS, CsvLogger, run_id

__all__ = ["SCHEMAS", "CsvLogger", "run_id"]
