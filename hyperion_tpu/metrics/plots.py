"""PNG artifacts for the benchmark suites — the reference's plot layer.

Reference plot inventory this mirrors:
  * compile-tier speedup + memory bars — `compilation_optimization.py`
    `plot_speed`/`plot_mem` (:159-229)
  * matmul TFLOPS per dtype/size + bandwidth curve —
    `01_hardware_exploration.ipynb cell 1` (save at :180-184)
  * baseline model benchmark panels (time decomposition, peak memory,
    throughput, batch scaling) — `baseline_performance.ipynb cell 0`
    visualizations (:236-292, :350-400)

Every function takes the benchmark's row dicts (the exact CSV rows) and
writes one PNG; matplotlib is imported lazily with the Agg backend so
headless benchmark boxes work, and every caller treats plotting as
best-effort (a missing matplotlib never fails a benchmark run).
"""

from __future__ import annotations

from pathlib import Path


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _finite(rows, key):
    out = []
    for r in rows:
        try:
            v = float(r[key])
        except (KeyError, TypeError, ValueError):
            continue
        if v == v:
            out.append((r, v))
    return out


def plot_compile_tiers(rows: list[dict], out_path: str | Path) -> Path | None:
    """Two-panel bars: per-model tier latency, and speedup vs the jit
    tier (the reference's plot_speed/plot_mem pair, adapted to the
    jit-centric tier table). Variants are derived from the rows so a new
    tier in compile_bench.VARIANTS shows up without touching this file."""
    plt = _plt()
    models = sorted({r["model"] for r in rows})
    order = {"op_by_op": 0, "jit": 1, "jit_pallas": 2}
    variants = sorted({r["variant"] for r in rows},
                      key=lambda v: order.get(v, 99))
    fig, (ax1, ax2, ax3) = plt.subplots(1, 3, figsize=(18, 5))

    def grouped_bars(ax, variants, key):
        width = 0.8 / max(len(variants), 1)
        any_bar = False
        for vi, variant in enumerate(variants):
            xs, ys = [], []
            offset = (vi - (len(variants) - 1) / 2) * width
            for mi, m in enumerate(models):
                sub = [r for r in rows
                       if r["model"] == m and r["variant"] == variant]
                vals = _finite(sub, key)
                if vals:
                    xs.append(mi + offset)
                    ys.append(vals[0][1])
            if xs:
                ax.bar(xs, ys, width, label=variant)
                any_bar = True
        ax.set_xticks(range(len(models)))
        ax.set_xticklabels(models, rotation=20, ha="right", fontsize=8)
        if any_bar:
            ax.legend()

    grouped_bars(ax1, variants, "median_ms")
    ax1.set_ylabel("latency (ms)")
    ax1.set_yscale("log")
    ax1.set_title("compilation tiers: latency")

    for mi, m in enumerate(models):
        sub = {r["variant"]: r for r in rows if r["model"] == m}
        base = _finite([sub.get("jit", {})], "median_ms")
        pallas = _finite([sub.get("jit_pallas", {})], "median_ms")
        if base and pallas and pallas[0][1] > 0:
            ax2.bar(mi, base[0][1] / pallas[0][1], 0.5, color="tab:green")
    ax2.axhline(1.0, color="gray", lw=1, ls="--")
    ax2.set_xticks(range(len(models)))
    ax2.set_xticklabels(models, rotation=20, ha="right", fontsize=8)
    ax2.set_ylabel("speedup of jit+pallas over jit (x)")
    ax2.set_title("pallas-kernel speedup")

    # the reference's plot_mem analogue: per-program temp memory
    # (op_by_op has no single compiled program, so it has no bar)
    grouped_bars(ax3, [v for v in variants if v != "op_by_op"],
                 "temp_memory_gb")
    ax3.set_ylabel("compiled temp memory (GB)")
    ax3.set_title("per-program temp memory")

    fig.tight_layout()
    out_path = Path(out_path)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_matmul_tflops(rows: list[dict], out_path: str | Path) -> Path | None:
    """TFLOPS vs matrix size, one line per dtype, with the chip's
    nominal peak marked (the reference's precision sweep plot, plus the
    MFU context it lacked)."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(7, 5))
    dtypes = sorted({r["dtype"] for r in rows})
    peaks: dict[float, list[str]] = {}
    for dt in dtypes:
        pts = sorted(
            (int(r["size"]), v)
            for r, v in _finite([r for r in rows if r["dtype"] == dt], "tflops")
        )
        if pts:
            ax.plot(*zip(*pts), marker="o", label=dt)
        for r, v in _finite([r for r in rows if r["dtype"] == dt], "peak_tflops"):
            peaks.setdefault(v, []).append(dt)
    # one dashed line per distinct nominal peak, labeled with the dtypes
    # it bounds (int8 peaks 2x bf16 — a single "bf16 peak" label would lie)
    for v, dts in sorted(peaks.items()):
        ax.axhline(v, color="gray", ls="--", lw=1,
                   label=f"nominal peak {v:.0f} ({','.join(sorted(set(dts)))})")
    ax.set_xscale("log", base=2)
    ax.set_xlabel("matrix size N (NxN @ NxN)")
    ax.set_ylabel("sustained TFLOPS")
    ax.set_title("MXU matmul throughput")
    ax.legend()
    fig.tight_layout()
    out_path = Path(out_path)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_bandwidth(rows: list[dict], out_path: str | Path) -> Path | None:
    plt = _plt()
    fig, ax = plt.subplots(figsize=(7, 5))
    hbm = [(int(r["elements"]), v) for r, v in _finite(rows, "gb_per_s")
           if not r.get("note")]
    cached = [(int(r["elements"]), v) for r, v in _finite(rows, "gb_per_s")
              if r.get("note")]
    if hbm:
        ax.plot(*zip(*sorted(hbm)), marker="o", label="HBM-resident")
    if cached:
        ax.plot(*zip(*sorted(cached)), marker="x", ls=":",
                label="cache-resident (not HBM)")
    ax.set_xscale("log")
    ax.set_xlabel("elements")
    ax.set_ylabel("GB/s (12 B/element accounting)")
    ax.set_title("memory bandwidth (z = x + y)")
    ax.legend()
    fig.tight_layout()
    out_path = Path(out_path)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_baseline_models(rows: list[dict], out_path: str | Path) -> Path | None:
    """Three panels per model: stacked fwd/bwd/opt time, peak memory,
    throughput (the reference's remaining panel — batch scaling — is
    `plot_batch_scaling`)."""
    plt = _plt()
    fig, axes = plt.subplots(1, 3, figsize=(15, 5))
    models = [r["model"] for r in rows]
    x = range(len(models))

    fwd = [float(r["forward_ms"]) for r in rows]
    bwd = [float(r["backward_ms"]) for r in rows]
    opt = [float(r["optimizer_ms"]) for r in rows]
    axes[0].bar(x, fwd, label="forward")
    axes[0].bar(x, bwd, bottom=fwd, label="backward")
    axes[0].bar(x, opt, bottom=[a + b for a, b in zip(fwd, bwd)],
                label="optimizer")
    axes[0].set_ylabel("ms / step")
    axes[0].set_title("train-step decomposition")
    axes[0].legend()

    axes[1].bar(x, [float(r["peak_memory_mb"]) for r in rows],
                color="tab:purple")
    axes[1].set_ylabel("peak memory (MB)")
    axes[1].set_title("peak device memory")

    axes[2].bar(x, [float(r["samples_per_s"]) for r in rows],
                color="tab:green")
    axes[2].set_ylabel("samples / s")
    axes[2].set_title("throughput")

    for ax in axes:
        ax.set_xticks(list(x))
        ax.set_xticklabels(models, rotation=20, ha="right", fontsize=8)
    fig.tight_layout()
    out_path = Path(out_path)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def plot_batch_scaling(
    sweeps: dict[str, list[dict]], out_path: str | Path
) -> Path | None:
    """Throughput and memory vs batch size, one line per model (the
    reference's batch-scaling viz)."""
    plt = _plt()
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(12, 5))
    for model, rows in sorted(sweeps.items()):
        bs = [int(r["batch_size"]) for r in rows]
        ax1.plot(bs, [float(r["samples_per_s"]) for r in rows],
                 marker="o", label=model)
        ax2.plot(bs, [float(r["peak_memory_mb"]) for r in rows],
                 marker="o", label=model)
    ax1.set_xlabel("batch size")
    ax1.set_ylabel("samples / s")
    ax1.set_xscale("log", base=2)
    ax1.set_title("batch-size scaling: throughput")
    ax1.legend()
    ax2.set_xlabel("batch size")
    ax2.set_ylabel("peak memory (MB)")
    ax2.set_xscale("log", base=2)
    ax2.set_title("batch-size scaling: memory")
    fig.tight_layout()
    out_path = Path(out_path)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def try_plot(fn, *args, **kwargs):
    """Best-effort wrapper: benchmarks never fail because of plotting."""
    try:
        return fn(*args, **kwargs)
    except Exception as e:  # noqa: BLE001
        print(f"[plots] skipped {getattr(fn, '__name__', fn)}: {e}")
        return None
