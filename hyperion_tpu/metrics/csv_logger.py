"""Per-epoch CSV metrics logging — the reference's observability backbone.

Reference (SURVEY §5.5): rank-0 appends one CSV row per epoch; run ids are
`{job}_{world}gpus_{timestamp}` (`distributed_utils.py:140,215,301,438`);
schemas per trainer:
    LM (DDP/FSDP):  epoch, loss, duration_s, gpus        (:147,306)
    CIFAR:          epoch, loss, accuracy, duration_s, gpus  (:222)
    Llama:          epoch, loss, duration_s, gpus, mode  (:442-444)
Artifacts land in `{base_dir}/distributed/{run_id}_metrics.csv` and feed
`create_scaling_report`. We keep the format byte-compatible (same columns,
same filename shape) so the reference's downstream tooling — and ours —
reads either. "gpus" is kept as the column name for that compatibility;
on TPU it counts chips.
"""

from __future__ import annotations

import csv
import datetime
from pathlib import Path

from hyperion_tpu.runtime import dist

SCHEMAS: dict[str, tuple[str, ...]] = {
    "language_ddp": ("epoch", "loss", "duration_s", "gpus"),
    "language_fsdp": ("epoch", "loss", "duration_s", "gpus"),
    "cifar_ddp": ("epoch", "loss", "accuracy", "duration_s", "gpus"),
    "llama": ("epoch", "loss", "duration_s", "gpus", "mode"),
}


def run_id(job: str, n_devices: int, when: datetime.datetime | None = None) -> str:
    """`{job}_{n}gpus_{YYYYmmdd_HHMMSS}` — the reference's run-id format."""
    when = when or datetime.datetime.now()
    return f"{job}_{n_devices}gpus_{when:%Y%m%d_%H%M%S}"


class CsvLogger:
    """Append-per-epoch CSV writer, active only on the primary process
    (the reference's `if rank == 0:` guard around every CSV touch)."""

    def __init__(
        self,
        job: str,
        n_devices: int,
        base_dir: str | Path = "data",
        schema: tuple[str, ...] | None = None,
        run: str | None = None,
    ):
        self.job = job
        self.schema = schema or SCHEMAS.get(job)
        if self.schema is None:
            raise ValueError(f"no schema for job {job!r}; pass schema=")
        self.active = dist.is_primary()
        self.run = run or run_id(job, n_devices)
        self.path = Path(base_dir) / "distributed" / f"{self.run}_metrics.csv"
        if self.active:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with self.path.open("w", newline="") as f:
                csv.writer(f).writerow(self.schema)

    def log(self, **row) -> None:
        if not self.active:
            return
        missing = set(self.schema) - row.keys()
        if missing:
            raise ValueError(f"row missing columns {sorted(missing)}")
        with self.path.open("a", newline="") as f:
            csv.writer(f).writerow([_fmt(row[c]) for c in self.schema])

    def read(self) -> list[dict]:
        with self.path.open() as f:
            return list(csv.DictReader(f))


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6f}"
    return str(v)
