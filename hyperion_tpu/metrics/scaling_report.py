"""Scaling report: speedup/efficiency across device counts — C9.

Reference: `create_scaling_report` (`distributed_utils.py:563-773`) globs
`*_metrics.csv`, infers the model type from the filename, discards the
first third of epochs as warmup, averages epoch durations, computes
speedup = t1/tn and efficiency = speedup/n against the 1-GPU run, and
writes `scaling_analysis.{csv,png}`. (MI250X: LM DDP 3.42x/85.6% at 4
GPUs — BASELINE.md.)

Differences kept deliberately: no hardcoded sample-data fallback (the
reference fabricates plausible numbers when no CSVs exist,
`distributed_utils.py:590-637` — a benchmarking anti-feature); an empty
directory here produces an empty report and says so.
"""

from __future__ import annotations

import csv
import re
from collections import defaultdict
from pathlib import Path

_RUN = re.compile(r"^(?P<job>.+?)_(?P<n>\d+)gpus_(?P<ts>\d{8}_\d{6})_metrics\.csv$")


def parse_run_name(filename: str) -> tuple[str, int] | None:
    m = _RUN.match(Path(filename).name)
    if not m:
        return None
    return m.group("job"), int(m.group("n"))


def _mean_epoch_duration(path: Path) -> float | None:
    with path.open() as f:
        rows = list(csv.DictReader(f))
    if not rows:
        return None
    durations = [float(r["duration_s"]) for r in rows if "duration_s" in r]
    if not durations:
        return None
    # warmup discard: first third of epochs (reference :656-658) — first
    # epochs carry compilation/cache-warming noise on any backend
    skip = len(durations) // 3
    return sum(durations[skip:]) / len(durations[skip:])


def create_scaling_report(
    metrics_dir: str | Path = "data/distributed",
    out_dir: str | Path | None = None,
) -> list[dict]:
    """Build the speedup/efficiency table; write CSV (+PNG when
    matplotlib is available). Returns the table rows."""
    metrics_dir = Path(metrics_dir)
    out_dir = Path(out_dir) if out_dir else metrics_dir

    per_job: dict[str, dict[int, list[float]]] = defaultdict(lambda: defaultdict(list))
    for f in sorted(metrics_dir.glob("*_metrics.csv")):
        parsed = parse_run_name(f.name)
        if parsed is None:
            continue
        job, n = parsed
        d = _mean_epoch_duration(f)
        if d is not None:
            per_job[job][n].append(d)

    rows: list[dict] = []
    for job, by_n in sorted(per_job.items()):
        means = {n: sum(v) / len(v) for n, v in by_n.items()}
        if 1 not in means:
            # no single-device baseline → report absolute times only
            for n in sorted(means):
                rows.append({
                    "model": job, "gpus": n,
                    "epoch_time_s": round(means[n], 3),
                    "speedup": "", "efficiency_pct": "",
                })
            continue
        t1 = means[1]
        for n in sorted(means):
            speedup = t1 / means[n]
            rows.append({
                "model": job, "gpus": n,
                "epoch_time_s": round(means[n], 3),
                "speedup": round(speedup, 3),
                "efficiency_pct": round(100.0 * speedup / n, 1),
            })

    out_dir.mkdir(parents=True, exist_ok=True)
    out_csv = out_dir / "scaling_analysis.csv"
    with out_csv.open("w", newline="") as f:
        w = csv.DictWriter(
            f, fieldnames=["model", "gpus", "epoch_time_s", "speedup",
                           "efficiency_pct"])
        w.writeheader()
        w.writerows(rows)

    if rows:
        _plot(rows, out_dir / "scaling_analysis.png")
        for r in rows:
            print(f"[scaling_report] {r}")
    else:
        print(f"[scaling_report] no *_metrics.csv runs under {metrics_dir}")
    return rows


def _plot(rows: list[dict], path: Path) -> None:
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # noqa: BLE001 — plotting is optional
        return
    jobs = sorted({r["model"] for r in rows})
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    for job in jobs:
        sub = [r for r in rows if r["model"] == job and r["speedup"] != ""]
        if not sub:
            continue
        ns = [r["gpus"] for r in sub]
        ax1.plot(ns, [r["speedup"] for r in sub], marker="o", label=job)
        ax2.plot(ns, [r["efficiency_pct"] for r in sub], marker="o", label=job)
    if jobs:
        lim = max((r["gpus"] for r in rows), default=1)
        ax1.plot([1, lim], [1, lim], "k--", alpha=0.4, label="ideal")
    ax1.set_xlabel("devices"); ax1.set_ylabel("speedup"); ax1.legend()
    ax2.set_xlabel("devices"); ax2.set_ylabel("efficiency (%)")
    ax2.axhline(100, color="k", ls="--", alpha=0.4)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
