#!/usr/bin/env bash
# LoRA fine-tune a Llama, export base+adapters merged, and generate —
# including weight-only int8 decode.
#
#   examples/lora_finetune.sh [workdir] [size]
#
# size: tiny (default — runs anywhere) or 7b (one v5e chip with the
# auto-enabled full remat; put local HF weights in <workdir>/llama2_hf
# to start from Llama-2 instead of random init).
set -euo pipefail
cd "$(dirname "$0")/.."
WORK="${1:-data/example_lora}"
SIZE="${2:-tiny}"

# 1. Fine-tune: frozen base + r16/a32 adapters on q/k/v/o, optimizer
#    state for adapters only. --export-merged also writes the folded
#    base+adapter weights for the generation CLI.
python -m hyperion_tpu.cli.main \
  --model llama --llama_size "$SIZE" --lora --epochs 2 \
  --base_dir "$WORK" --export-merged

# 2. A tokenizer for sampling: the quick path trains a small ByteBPE on
#    a few lines (replace with your corpus; skipped if one exists).
if [ ! -f "$WORK/tokenizer/vocab.json" ]; then
  python - "$WORK" <<'EOF'
import sys
from hyperion_tpu.data.bpe import train_bpe
tok = train_bpe(["the quick brown fox jumps over the lazy dog"] * 8,
                vocab_size=256, verbose=False)  # <= tiny llama vocab
tok.save(sys.argv[1] + "/tokenizer")
EOF
fi

# 3. Generate from the merged checkpoint — float, then weight-only int8
#    (same weights, int8 MXU matmuls, half the weight HBM traffic).
CKPT="$WORK/checkpoints/llama_lora_bf16_merged.npz"
python -m hyperion_tpu.infer \
  --prompt "the quick" --max-new-tokens 16 --max-len 64 \
  --ckpt "$CKPT" --tokenizer-dir "$WORK/tokenizer"
python -m hyperion_tpu.infer \
  --prompt "the quick" --max-new-tokens 16 --max-len 64 \
  --ckpt "$CKPT" --tokenizer-dir "$WORK/tokenizer" --quant int8
