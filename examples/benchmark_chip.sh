#!/usr/bin/env bash
# Reproduce the perf story on your chip: hardware sweep, model
# baselines, compile tiers, decode throughput, headline JSON line.
#
#   examples/benchmark_chip.sh [outdir]
#
# Every suite uses chained data-dependent iterations fenced by a host
# fetch (utils/timing.py) — a lazy backend yields a rejected
# measurement, never a fake number. Compare against the MI250X
# reference rows with scripts/compare_to_reference.py.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT="${1:-results/benchmarks_local}"

python -m hyperion_tpu.bench.hw_explore --out "$OUT/hardware"
python -m hyperion_tpu.bench.baseline --scaling \
  --precisions float32 bfloat16 --out "$OUT/baseline"
python -m hyperion_tpu.bench.compile_bench --train-step --out "$OUT/compilation"
python -m hyperion_tpu.bench.decode_bench --out "$OUT/decode"
python bench.py

python scripts/compare_to_reference.py --root "$OUT"
