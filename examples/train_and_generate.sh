#!/usr/bin/env bash
# Train the toy LM on your own text, then sample from it.
#
#   examples/train_and_generate.sh [workdir] [raw.txt]
#
# With no raw text file the data pipeline falls back to the
# deterministic synthetic corpus — the script still runs end to end.
set -euo pipefail
cd "$(dirname "$0")/.."
WORK="${1:-data/example_lm}"
RAW="${2:-}"

# 1. Tokenizer + corpus prep (in-tree byte-level BPE -> native recordio).
#    Skipped when no raw text is given; training then uses the synthetic
#    fallback corpus with the GPT-2-sized vocab.
if [ -n "$RAW" ]; then
  python -m hyperion_tpu.data.prepare \
    --input "$RAW" --split-name train --base-dir "$WORK" --vocab-size 8192
fi

# 2. Train: DDP over every local chip (one process, mesh under the hood),
#    per-epoch validation, CSV metrics, orbax checkpoints + .npz export.
python -m hyperion_tpu.cli.main \
  --model language_ddp --epochs 3 --base_dir "$WORK"

# 3. Generate from the exported checkpoint. The tokenizer dir only
#    exists if step 1 ran; otherwise point --tokenizer-dir at any
#    trained ByteBPE directory.
if [ -d "$WORK/tokenizer" ]; then
  python -m hyperion_tpu.infer \
    --prompt "The quick" --max-new-tokens 32 \
    --ckpt "$WORK/checkpoints/language_ddp_final.npz" \
    --tokenizer-dir "$WORK/tokenizer"
else
  echo "(no tokenizer trained — pass a raw text file to sample text)"
fi
