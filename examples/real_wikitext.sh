#!/usr/bin/env bash
# Train on the REAL WikiText-2 tokens committed in-repo and read the
# resulting loss/perplexity evidence.
#
#   examples/real_wikitext.sh [outdir]
#
# The repo carries the reference snapshot's real GPT-2-tokenized
# validation/test arrows (data/wikitext2_tokenized/ — its train arrow
# was never shipped; see that README). Training therefore uses the
# real TEST split (2,891 x 128 tokens) and validates on the real
# validation split: loss and val_ppl below are measured on real text.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT="${1:-results/real_wikitext_example}"

# 1. DDP training on the real tokens. --data_dir points at the
#    committed arrows; --base_dir keeps run outputs separate.
python -m hyperion_tpu.cli.main --model language_ddp --epochs 3 \
  --train-split test --data_dir data --base_dir "$OUT"

# 2. FSDP over the same corpus (ZeRO-3 sharding when >1 chip).
python -m hyperion_tpu.cli.main --model language_fsdp --epochs 3 \
  --train-split test --data_dir data --base_dir "$OUT"

# 3. The evidence: per-epoch CSVs (reference schema) with val_loss /
#    val_ppl measured on the real validation arrow.
echo "=== runs ==="
ls "$OUT"/distributed/
tail -2 "$OUT"/distributed/language_*_metrics.csv
