"""train/supervisor.py — unit tests for the restart policy, plus the
chaos-driven subprocess integration tests (tier-1, CPU): a supervised
run killed mid-epoch twice resumes to the same final state as an
uninterrupted run, and a corrupted latest checkpoint falls back to the
prior verified step."""

import csv
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from hyperion_tpu.train import supervisor
from hyperion_tpu.train.supervisor import (
    ATTEMPT_ENV,
    EXIT_GAVE_UP,
    EXIT_HEALTH_ABORT,
    EXIT_PREEMPTED,
    supervise,
)

# ------------------------------------------------------------ unit half


class FakeChild:
    def __init__(self, rcs):
        self.rcs = list(rcs)
        self.attempts = []

    def __call__(self, argv, env):
        self.attempts.append(env[ATTEMPT_ENV])
        return self.rcs.pop(0)


class TestRestartPolicy:
    def test_restarts_until_success_with_backoff(self, tmp_path):
        child = FakeChild([1, 1, 0])
        sleeps = []
        rc = supervise(["job"], base_dir=tmp_path, max_restarts=3,
                       backoff_s=1.0, run_child=child, sleep=sleeps.append)
        assert rc == 0
        assert child.attempts == ["0", "1", "2"]  # lineage stamped
        assert len(sleeps) == 2 and sleeps[1] > sleeps[0]  # exponential

    def test_gives_up_after_max_restarts(self, tmp_path):
        child = FakeChild([1, 1, 1, 1])
        rc = supervise(["job"], base_dir=tmp_path, max_restarts=2,
                       run_child=child, sleep=lambda s: None)
        assert rc == EXIT_GAVE_UP
        assert child.attempts == ["0", "1", "2"]  # initial + 2 restarts

    def test_usage_errors_never_restart(self, tmp_path):
        child = FakeChild([2])
        assert supervise(["job"], base_dir=tmp_path, max_restarts=5,
                         run_child=child, sleep=lambda s: None) == 2
        assert child.attempts == ["0"]

    def test_preemption_restarts_without_backoff(self, tmp_path):
        child = FakeChild([EXIT_PREEMPTED, 0])
        sleeps = []
        rc = supervise(["job"], base_dir=tmp_path, max_restarts=2,
                       run_child=child, sleep=sleeps.append)
        assert rc == 0 and sleeps == []  # the capacity event is over

    def test_progressing_preemptions_dont_burn_budget(self, tmp_path,
                                                      monkeypatch):
        """N capacity events over a long preemptible run are normal
        life: a preemption whose doctor evidence shows forward progress
        must not count against --max-restarts."""
        steps = iter([10, 20, 30])
        monkeypatch.setattr(
            supervisor, "_consult_doctor",
            lambda b, prefer_diverged=False: {
                "verdict": "healthy", "last_step": next(steps),
                "run": "job_1gpus_1", "reason": "preempted"})
        child = FakeChild([EXIT_PREEMPTED] * 3 + [0])
        rc = supervise(["job"], base_dir=tmp_path, max_restarts=0,
                       run_child=child, sleep=lambda s: None)
        # max_restarts=0: only progress-free preemption restarts could
        # carry the run through all three capacity events
        assert rc == 0 and child.attempts == ["0", "1", "2", "3"]

    def test_diverged_quarantines_newest_checkpoint(self, tmp_path):
        newest = tmp_path / "checkpoints" / "llama_8dev" / "step_00000008"
        older = tmp_path / "checkpoints" / "llama_8dev" / "step_00000004"
        for d in (older, newest):
            d.mkdir(parents=True)
            (d / "data.bin").write_bytes(b"x")
        child = FakeChild([EXIT_HEALTH_ABORT, 0])
        rc = supervise(["job"], base_dir=tmp_path, max_restarts=1,
                       run_child=child, sleep=lambda s: None)
        assert rc == 0
        assert (newest.parent / "step_00000008.corrupt").is_dir()
        assert not newest.exists() and older.exists()


# ----------------------------------------------------- integration half

TRAIN_ARGS = [
    "--model", "llama", "--llama_size", "tiny", "--steps-per-epoch", "4",
    "--batch_size", "8", "--seq_len", "16", "--no-validate", "--seed", "0",
]


def run_cli(*args, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONFAULTHANDLER="1")
    # hermetic children: a persistent compile cache shared across test
    # subprocesses is both unrealistic for these scenarios and broken on
    # this CPU backend (reloading a cached executable aborts) — and any
    # test that imports bench.py must not be able to leak one in here
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return subprocess.run(
        [sys.executable, "-m", "hyperion_tpu.cli.main", *args],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=str(Path(__file__).resolve().parents[1]),
    )


def epoch_losses(base_dir) -> dict[int, float]:
    """epoch -> loss across every attempt's CSV (a killed attempt never
    logs a partial row, so epochs appear exactly once per lineage)."""
    out: dict[int, float] = {}
    for p in sorted(Path(base_dir).glob("distributed/*_metrics.csv")):
        with p.open() as f:
            for row in csv.DictReader(f):
                out[int(row["epoch"])] = float(row["loss"])
    return out


@pytest.fixture(scope="module")
def uninterrupted(tmp_path_factory):
    """The control arm: 3 epochs x 4 steps, no faults."""
    base = tmp_path_factory.mktemp("plain")
    r = run_cli(*TRAIN_ARGS, "--epochs", "3", "--base_dir", str(base))
    assert r.returncode == 0, r.stderr[-2000:]
    return base


class TestChaosIntegration:
    def test_supervised_run_survives_two_kills(self, uninterrupted,
                                               tmp_path):
        """Acceptance: SIGKILL mid-epoch at global steps 6 and 10;
        --supervise resumes through both to the same final step count
        and losses as the uninterrupted run — no batch trained twice or
        skipped (the resumed epochs replay the same seeded permutation
        from the restored step)."""
        from hyperion_tpu import checkpoint as ckpt
        from hyperion_tpu.obs.doctor import diagnose

        base = tmp_path / "chaos"
        r = run_cli(*TRAIN_ARGS, "--epochs", "3", "--base_dir", str(base),
                    "--supervise", "--max-restarts", "3",
                    "--chaos", "kill@step=6,kill@step=10")
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        assert r.stdout.count("[chaos] firing kill") == 2
        assert "resumed from step 4" in r.stdout
        assert "resumed from step 8" in r.stdout

        plain_dir = str(uninterrupted / "checkpoints" / "llama_8dev")
        chaos_dir = str(base / "checkpoints" / "llama_8dev")
        assert ckpt.latest_step(chaos_dir) == ckpt.latest_step(plain_dir) == 12
        # per-epoch losses identical: every batch trained exactly once,
        # in order, on both arms
        plain, chaotic = epoch_losses(uninterrupted), epoch_losses(base)
        assert set(chaotic) == {1, 2, 3}
        for ep in (1, 2, 3):
            assert chaotic[ep] == pytest.approx(plain[ep], rel=1e-5), ep
        # the final exports are bit-comparable
        a = np.load(uninterrupted / "checkpoints" / "llama_fsdp_bf16_final.npz")
        b = np.load(base / "checkpoints" / "llama_fsdp_bf16_final.npz")
        for k in a.files:
            np.testing.assert_allclose(a[k], b[k], rtol=1e-6, atol=1e-7)
        # doctor reports the restart lineage across the stream
        d = diagnose(base)
        assert d["attempts"] == [0, 1, 2] and d["verdict"] == "healthy"

    def test_corrupt_latest_falls_back_to_prior_verified(self, tmp_path):
        """Acceptance: with checkpoints at steps 4 and 8, corrupt the
        latest; the next run quarantines it as step_X.corrupt (reason
        file included) and resumes from the prior verified step 4."""
        base = tmp_path / "corrupt"
        r1 = run_cli(*TRAIN_ARGS, "--epochs", "2", "--base_dir", str(base))
        assert r1.returncode == 0, r1.stderr[-2000:]
        job_dir = base / "checkpoints" / "llama_8dev"
        assert sorted(p.name for p in job_dir.iterdir()) == [
            "step_00000004", "step_00000008"]

        r2 = run_cli(*TRAIN_ARGS, "--epochs", "3", "--base_dir", str(base),
                     "--chaos", "corrupt_ckpt@latest")
        assert r2.returncode == 0, r2.stdout[-2000:] + r2.stderr[-2000:]
        assert "quarantined step_00000008" in r2.stdout
        assert "resumed from step 4" in r2.stdout
        corrupt = job_dir / "step_00000008.corrupt"
        assert corrupt.is_dir()
        assert "size mismatch" in (corrupt / "QUARANTINE_REASON.txt").read_text()
        from hyperion_tpu import checkpoint as ckpt

        assert ckpt.latest_step(job_dir) == 12  # retrained through the end

    def test_supervised_divergence_quarantines_then_resumes(self, tmp_path):
        """The doctor-guided arm: a NaN loss under --health-policy abort
        exits 4; the supervisor confirms 'diverged' with obs doctor,
        quarantines the newest checkpoint, and the restart resumes from
        the PRIOR verified step to a clean finish."""
        base = tmp_path / "nan"
        r = run_cli(*TRAIN_ARGS, "--epochs", "3", "--base_dir", str(base),
                    "--health-policy", "abort",
                    "--supervise", "--max-restarts", "2",
                    "--chaos", "nan_loss@step=10")
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        assert "doctor verdict: diverged" in r.stdout
        assert "quarantined step_00000008" in r.stdout
        assert "resumed from step 4" in r.stdout
        job_dir = base / "checkpoints" / "llama_8dev"
        assert (job_dir / "step_00000008.corrupt").is_dir()
        from hyperion_tpu import checkpoint as ckpt

        assert ckpt.latest_step(job_dir) == 12


class TestSuperviseFlagStripping:
    def test_child_argv_never_supervises(self):
        from hyperion_tpu.cli.main import _strip_supervise_flags

        argv = ["--model", "llama", "--supervise", "--max-restarts", "3",
                "--epochs", "2"]
        assert _strip_supervise_flags(argv) == [
            "--model", "llama", "--epochs", "2"]
        assert _strip_supervise_flags(["--max-restarts=3", "--supervise"]) == []

    def test_compile_cache_flag_rides_through_to_children(self):
        """Supervised children re-exec the same argv minus supervision
        flags — --compile-cache must survive so each restart points
        itself (in-process, per backend) at the shared cache and skips
        the recompile."""
        from hyperion_tpu.cli.main import _strip_supervise_flags

        argv = ["--model", "llama", "--supervise",
                "--compile-cache", "/tmp/cc", "--max-restarts", "2"]
        assert _strip_supervise_flags(argv) == [
            "--model", "llama", "--compile-cache", "/tmp/cc"]


class TestCompileCache:
    def test_per_backend_subdir_and_in_process_config(self, tmp_path,
                                                      monkeypatch):
        import jax

        from hyperion_tpu.cli.main import setup_compile_cache

        monkeypatch.delenv("HYPERION_COMPILE_CACHE", raising=False)
        before = dict(os.environ)
        assert setup_compile_cache("") is None  # off by default
        d = setup_compile_cache(str(tmp_path / "cache"))
        try:
            assert d == str(tmp_path / "cache" / "cpu")
            assert (tmp_path / "cache" / "cpu").is_dir()
            assert jax.config.jax_compilation_cache_dir == d
            # the import-leak lesson: configuration is in-process only,
            # never a mutated environment later children would inherit
            assert dict(os.environ) == before
        finally:
            jax.config.update("jax_compilation_cache_dir", None)

    def test_env_var_fallback(self, tmp_path, monkeypatch):
        import jax

        from hyperion_tpu.cli.main import setup_compile_cache

        monkeypatch.setenv("HYPERION_COMPILE_CACHE",
                           str(tmp_path / "envcache"))
        try:
            d = setup_compile_cache("")
            assert d and (tmp_path / "envcache" / "cpu").is_dir()
        finally:
            jax.config.update("jax_compilation_cache_dir", None)

    def test_cli_threads_overlap_knobs(self):
        from hyperion_tpu.cli.main import build_parser, make_config

        args = build_parser().parse_args([
            "--model", "llama", "--prefetch-depth", "4",
            "--no-async-checkpoint", "--compile-cache", "/tmp/cc"])
        cfg = make_config(args, "llama")
        assert cfg.train.prefetch_depth == 4
        assert cfg.train.async_checkpoint is False
        assert cfg.optimization.compile_cache == "/tmp/cc"
        # defaults: prefetch on at depth 2, async saves on
        dflt = make_config(build_parser().parse_args([]), "language_ddp")
        assert dflt.train.prefetch_depth == 2
        assert dflt.train.async_checkpoint is True


def test_exit_code_contract():
    """scripts/tpu_watch.sh branches on these — they are API."""
    assert supervisor.EXIT_OK == 0
    assert supervisor.EXIT_USAGE == 2
    assert EXIT_GAVE_UP == 3
    assert EXIT_HEALTH_ABORT == 4
    assert EXIT_PREEMPTED == 75
