"""End-to-end training tests on the simulated 8-device mesh.

The reference verified training by eyeballing 25-epoch notebook runs
(SURVEY §4.2); these tests assert the same properties mechanically: loss
decreases, metrics aggregate globally, checkpoints round-trip, resume
actually resumes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.config import Config
from hyperion_tpu.models.transformer_lm import TransformerLM, simple_lm_config
from hyperion_tpu.train import (
    create_train_state,
    make_optimizer,
    make_train_step,
    next_token_loss,
)
from hyperion_tpu.train.losses import classification_loss


def tiny_cfg(**over) -> Config:
    cfg = Config()
    cfg.train.epochs = 2
    cfg.train.batch_size = 16
    cfg.train.seq_len = 32
    cfg.train.learning_rate = 1e-3
    return cfg


@pytest.fixture()
def lm_setup(mesh8):
    cfg = simple_lm_config(vocab_size=256, d_model=32, n_heads=2, n_layers=1,
                           ff_dim=64, max_len=16, dropout=0.0)
    model = TransformerLM(cfg)
    opt = make_optimizer(1e-2, grad_clip_norm=1.0)
    state, sharding = create_train_state(
        lambda r: {"params": model.init_params(r)}, opt, mesh8,
        jax.random.key(0), policy="bf16",
    )

    def loss_fn(params, batch_stats, batch, rngs):
        logits = model.apply({"params": params}, batch["input_ids"],
                             padding_mask=batch["attention_mask"])
        loss = next_token_loss(logits, batch["input_ids"], batch["attention_mask"])
        return loss, ({"loss": loss}, batch_stats)

    return model, opt, state, sharding, loss_fn


def make_batch(mesh, n=16, t=16, vocab=256, seed=0):
    from hyperion_tpu.runtime.mesh import batch_sharding

    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, (n, t)).astype(np.int32)
    mask = np.ones((n, t), np.int8)
    sh = batch_sharding(mesh)
    return {
        "input_ids": jax.device_put(ids, sh),
        "attention_mask": jax.device_put(mask, sh),
    }


class TestTrainStep:
    def test_loss_decreases(self, lm_setup, mesh8):
        model, opt, state, sharding, loss_fn = lm_setup
        step = make_train_step(loss_fn, opt, sharding, donate=False)
        batch = make_batch(mesh8)
        rng = jax.random.key(1)
        losses = []
        for _ in range(20):
            state, metrics = step(state, batch, rng)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.7, losses
        assert int(state.step) == 20

    def test_state_stays_sharded(self, lm_setup, mesh8):
        model, opt, state, sharding, loss_fn = lm_setup
        step = make_train_step(loss_fn, opt, sharding, donate=False)
        state2, _ = step(state, make_batch(mesh8), jax.random.key(1))
        for p, sh in zip(jax.tree.leaves(state2.params),
                         jax.tree.leaves(sharding.tree.params)):
            assert p.sharding.spec == sh.spec

    @pytest.mark.slow
    def test_grad_accum_matches_full_batch(self, lm_setup, mesh8):
        model, opt, state, sharding, loss_fn = lm_setup
        batch = make_batch(mesh8)
        full = make_train_step(loss_fn, opt, sharding, grad_accum=1, donate=False)
        accum = make_train_step(loss_fn, opt, sharding, grad_accum=2, donate=False)
        rng = jax.random.key(1)
        s_full, m_full = full(state, batch, rng)
        s_acc, m_acc = accum(state, batch, rng)
        # same data split in halves: averaged grads ≈ full-batch grads
        for a, b in zip(jax.tree.leaves(s_full.params), jax.tree.leaves(s_acc.params)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32), atol=2e-3)

    def test_grad_clip_bounds_grad_norm(self, lm_setup, mesh8):
        model, opt, state, sharding, loss_fn = lm_setup
        step = make_train_step(loss_fn, opt, sharding, donate=False)
        _, metrics = step(state, make_batch(mesh8), jax.random.key(1))
        assert float(metrics["grad_norm"]) > 0


class TestTrainerDrivers:
    @pytest.mark.slow
    def test_language_trainer_end_to_end(self, tmp_path, mesh_dp, monkeypatch):
        from hyperion_tpu.train.trainer import train_language_model

        cfg = Config()
        cfg.train.epochs = 2
        cfg.train.batch_size = 32
        cfg.train.seq_len = 32
        cfg.train.steps_per_epoch = 12
        cfg.train.base_dir = str(tmp_path)
        cfg.train.learning_rate = 1e-2
        res = train_language_model(cfg)
        assert len(res.history) == 2
        assert np.isfinite(res.final_loss)
        assert res.history[1].loss < res.history[0].loss
        rows = [r for r in open(res.csv_path)]
        assert rows[0].strip() == "epoch,loss,duration_s,gpus,val_loss,val_ppl"
        assert len(rows) == 3
        assert (tmp_path / "checkpoints" / "language_ddp_final.npz").exists()

    @pytest.mark.slow
    def test_language_trainer_resumes(self, tmp_path, mesh_dp):
        from hyperion_tpu.train.trainer import train_language_model

        cfg = Config()
        cfg.train.epochs = 1
        cfg.train.batch_size = 32
        cfg.train.seq_len = 32
        cfg.train.steps_per_epoch = 6
        cfg.train.base_dir = str(tmp_path)
        res1 = train_language_model(cfg)
        # second run with more epochs resumes from the checkpoint
        cfg2 = cfg.override(**{"train.epochs": 2})
        res2 = train_language_model(cfg2)
        assert len(res2.history) == 1  # only the one remaining epoch ran
        assert res2.history[0].epoch == 2

    @pytest.mark.slow
    def test_cifar_trainer_end_to_end(self, tmp_path, mesh_dp):
        from hyperion_tpu.train.trainer import train_cifar_model

        cfg = Config()
        cfg.train.epochs = 1
        cfg.train.batch_size = 64
        cfg.train.steps_per_epoch = 4
        cfg.train.learning_rate = 1e-3
        cfg.train.base_dir = str(tmp_path)
        res = train_cifar_model(cfg)
        assert np.isfinite(res.final_loss)
        rows = [r for r in open(res.csv_path)]
        assert rows[0].strip() == ("epoch,loss,accuracy,duration_s,gpus,"
                                   "val_loss,val_accuracy")
        acc = float(rows[1].split(",")[2])
        assert 0.0 <= acc <= 100.0


class TestLrSchedules:
    """make_optimizer's schedule arm (beyond the reference's fixed LR)."""

    def _update_mags(self, opt, n):
        import optax

        params = {"w": jnp.ones((4,))}
        state = opt.init(params)
        mags = []
        for _ in range(n):
            upd, state = opt.update({"w": jnp.ones((4,))}, state, params)
            params = optax.apply_updates(params, upd)
            mags.append(float(jnp.abs(upd["w"]).mean()))
        return mags

    def test_warmup_then_decay(self):
        from hyperion_tpu.train.state import make_optimizer

        opt = make_optimizer(1e-2, schedule="warmup_cosine",
                             warmup_steps=5, total_steps=20)
        mags = self._update_mags(opt, 20)
        assert mags[0] < mags[4] < mags[5] * 1.5   # ramping up
        assert mags[19] < mags[6]                  # decaying down

    def test_cosine_decays(self):
        from hyperion_tpu.train.state import make_optimizer

        mags = self._update_mags(
            make_optimizer(1e-2, schedule="cosine", total_steps=10), 10
        )
        assert mags[-1] < mags[0]

    def test_schedule_validation(self):
        from hyperion_tpu.train.state import make_optimizer

        with pytest.raises(ValueError, match="total_steps"):
            make_optimizer(1e-2, schedule="cosine")
        with pytest.raises(ValueError, match="unknown schedule"):
            make_optimizer(1e-2, schedule="linear")


class TestCheckpoint:
    def test_roundtrip_and_resume_layout(self, lm_setup, tmp_path):
        from hyperion_tpu import checkpoint as ckpt

        model, opt, state, sharding, loss_fn = lm_setup
        path = ckpt.save(tmp_path / "ck", state)
        assert path.exists()
        restored = ckpt.restore(tmp_path / "ck", state)
        assert int(restored.step) == int(state.step)
        for a, b in zip(jax.tree.leaves(restored.params),
                        jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))
            # sharding preserved
        assert restored.params["tok_emb"]["embedding"].sharding.spec == \
            state.params["tok_emb"]["embedding"].sharding.spec

    def test_gathered_export_roundtrip(self, lm_setup, tmp_path):
        from hyperion_tpu import checkpoint as ckpt

        model, opt, state, sharding, loss_fn = lm_setup
        p = ckpt.export_gathered(tmp_path / "full.npz", state.params)
        loaded = ckpt.load_gathered(p)
        np.testing.assert_array_equal(
            loaded["tok_emb"]["embedding"],
            np.asarray(state.params["tok_emb"]["embedding"]),
        )


class TestHealthEvidence:
    def test_evidence_snapshot_lands_in_health_subdir(self, lm_setup,
                                                      tmp_path):
        """A health-policy 'checkpoint' reaction must not pollute the
        resume namespace: the snapshot would both evict an epoch
        checkpoint from prune(keep=2) and get picked by latest_step as
        the resume point. It lives under `health/` instead."""
        from types import SimpleNamespace

        from hyperion_tpu import checkpoint as ckpt
        from hyperion_tpu.obs import trace as obs_trace
        from hyperion_tpu.obs.health import Anomaly
        from hyperion_tpu.train import trainer as trainer_mod

        model, opt, state, sharding, loss_fn = lm_setup
        anom = Anomaly(kind="loss_spike", step=3, value=9.9, detail={},
                       fatal=False)
        monitor = SimpleNamespace(last_escalated=[anom], anomalies=[anom])
        ckpt_dir = str(tmp_path / "ck")
        aborted = trainer_mod._health_react(
            "job", "checkpoint", monitor, state, ckpt_dir,
            obs_trace.null_tracer(),
        )
        assert not aborted
        assert ckpt.latest_step(ckpt_dir) is None  # resume namespace clean
        assert ckpt.latest_step(f"{ckpt_dir}/health") == int(state.step)


class TestLosses:
    def test_pad_positions_ignored(self):
        logits = np.random.default_rng(0).normal(size=(2, 8, 16)).astype(np.float32)
        ids = np.ones((2, 8), np.int32)
        mask_full = np.ones((2, 8), np.int8)
        mask_half = mask_full.copy()
        mask_half[:, 4:] = 0
        l_full = next_token_loss(jnp.asarray(logits), jnp.asarray(ids), jnp.asarray(mask_full))
        l_half = next_token_loss(jnp.asarray(logits), jnp.asarray(ids), jnp.asarray(mask_half))
        # padding changes the loss (different denominators/numerators)
        assert not np.isclose(float(l_full), float(l_half))
        # all-pad → loss 0 (guarded denominator), not NaN
        l_none = next_token_loss(jnp.asarray(logits), jnp.asarray(ids),
                                 jnp.zeros((2, 8), jnp.int8))
        assert float(l_none) == 0.0

    def test_classification_counts(self):
        logits = jnp.asarray([[9.0, 0.0], [0.0, 9.0], [9.0, 0.0]])
        labels = jnp.asarray([0, 1, 1])
        loss, counts = classification_loss(logits, labels)
        assert float(counts["correct"]) == 2.0
        assert float(counts["total"]) == 3.0


class TestSeqParallelTraining:
    @pytest.mark.slow
    def test_language_trainer_with_ring_attention(self, tmp_path, monkeypatch):
        """End-to-end sequence-parallel training: mesh (data=2, seq=4),
        batches seq-sharded, ring attention inside the train step."""
        from hyperion_tpu.train.trainer import train_language_model

        cfg = Config()
        cfg.train.epochs = 1
        cfg.train.batch_size = 16
        cfg.train.seq_len = 32
        cfg.train.steps_per_epoch = 4
        cfg.train.base_dir = str(tmp_path)
        cfg.train.learning_rate = 1e-2
        cfg.train.validate = False
        cfg.distributed.data = 2
        cfg.distributed.seq = 4
        cfg.optimization.attention_impl = "ring"
        res = train_language_model(cfg)
        assert np.isfinite(res.final_loss)


class TestPipelineTraining:
    @pytest.mark.slow
    def test_language_trainer_with_fsdp_pipeline(self, tmp_path):
        """End-to-end pipeline training with FSDP inside each stage:
        mesh (data=1, fsdp=2, pipe=4), per-layer gather in the tick
        (gpipe_apply_layers), dropout live via per-tick RNG threading."""
        from hyperion_tpu.train.trainer import train_language_model

        cfg = Config()
        cfg.train.epochs = 1
        cfg.train.batch_size = 8
        cfg.train.seq_len = 16
        cfg.train.steps_per_epoch = 2
        cfg.train.base_dir = str(tmp_path)
        cfg.train.validate = False
        cfg.distributed.data = 1
        cfg.distributed.fsdp = 2
        cfg.distributed.pipe = 4
        res = train_language_model(cfg, "language_fsdp")
        assert np.isfinite(res.final_loss)


class TestDryInit:
    """--dry-init / plan_train_state: the eval_shape-only memory plan
    must account bytes correctly and never touch device memory (it is
    how the 7B config is validated on boxes without a chip)."""

    def test_plan_matches_real_state(self, mesh8):
        import optax

        from hyperion_tpu.models.llama import Llama, llama_tiny_config
        from hyperion_tpu.train.state import plan_train_state

        model = Llama(llama_tiny_config())
        shapes, sharding, plan = plan_train_state(
            lambda r: {"params": model.init_params(r)},
            optax.adamw(1e-4), mesh8, jax.random.key(0),
            policy="bf16_full", fsdp=True,
        )
        n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes.params))
        assert plan["param_count"] == n > 0
        # bf16_full stores params in bf16: bytes = 2 * count
        assert plan["params_gb"] == round(2 * n / 1e9, 4)
        # adamw keeps two moments per param (plus scalar counts)
        assert plan["opt_state_gb"] >= plan["params_gb"] * 1.9
        assert plan["total_gb"] > 0
        # fsdp over the mesh: per-device strictly below the global total
        if mesh8.shape["fsdp"] > 1:
            assert plan["per_device_gb"] < plan["total_gb"]

    def test_cli_dry_init_runs_no_training(self, tmp_path, capsys):
        from hyperion_tpu.cli import main as cli

        cli.main([
            "--model", "llama", "--llama_size", "tiny", "--lora",
            "--epochs", "1", "--batch_size", "8", "--no-validate",
            "--dry-init", "--base_dir", str(tmp_path),
        ])
        out = capsys.readouterr().out
        assert "dry-init memory plan" in out
        assert "param_count" in out
        # no metrics CSV was written: nothing trained
        assert not list((tmp_path / "distributed").glob("*_metrics.csv"))

    def test_abstract_mesh_plans_beyond_local_devices(self, tmp_path, capsys):
        from hyperion_tpu.cli import main as cli

        # fsdp=16 exceeds the 8 simulated CPU devices: planning must use
        # an AbstractMesh and never ask the backend for devices
        cli.main([
            "--model", "llama", "--llama_size", "tiny", "--lora",
            "--epochs", "1", "--batch_size", "16", "--no-validate",
            "--dry-init", "--mesh", "1,16,1,1", "--base_dir", str(tmp_path),
        ])
        out = capsys.readouterr().out
        assert '"fsdp": 16' in out and "dry-init memory plan" in out


class TestPreemption:
    """Graceful preemption (utils/preemption.py + the epoch loop):
    SIGTERM latches, the loop checkpoints mid-epoch, and the next run
    resumes within the interrupted epoch (no batch trained twice)."""

    def test_guard_latches_sigterm(self):
        import os
        import signal as sig

        from hyperion_tpu.utils.preemption import PreemptionGuard

        before = sig.getsignal(sig.SIGTERM)
        with PreemptionGuard() as g:
            assert not g.triggered
            os.kill(os.getpid(), sig.SIGTERM)
            assert g.triggered  # latched, process alive
            # second signal falls through to the previous handler
            with pytest.raises(KeyboardInterrupt):
                g._handle(sig.SIGTERM, None)
        assert sig.getsignal(sig.SIGTERM) == before  # restored

    def test_trigger_is_programmatic(self):
        from hyperion_tpu.utils.preemption import PreemptionGuard

        g = PreemptionGuard()
        assert not g.triggered
        g.trigger()
        assert g.triggered

    def test_on_latch_observer_fires_on_first_signal(self):
        """The epoch loop points on_latch at the trace/heartbeat so a
        preemption is on disk the moment it lands (obs doctor reads the
        preempt_signal event) — and a broken observer must never break
        the graceful-exit path it observes."""
        import os
        import signal as sig

        from hyperion_tpu.utils.preemption import PreemptionGuard

        seen = []
        with PreemptionGuard(on_latch=seen.append) as g:
            os.kill(os.getpid(), sig.SIGTERM)
            assert g.triggered and seen == [sig.SIGTERM]
        broken = PreemptionGuard(on_latch=lambda s: 1 / 0)
        with broken:
            os.kill(os.getpid(), sig.SIGTERM)
            assert broken.triggered  # latched despite the observer crash

    def test_batches_resume_same_permutation(self, mesh8):
        from hyperion_tpu.data.sharding import ShardedBatches

        data = {"x": np.arange(64, dtype=np.int32).reshape(64, 1)}
        b = ShardedBatches(data, 8, mesh8, shuffle=True, seed=3)
        full = [np.asarray(x["x"]).ravel().tolist() for x in b.epoch(5)]
        tail = [np.asarray(x["x"]).ravel().tolist()
                for x in b.epoch(5, start_step=3)]
        assert tail == full[3:]  # same permutation, prefix skipped

    @pytest.mark.slow
    def test_preempt_then_resume_trains_every_batch_once(
        self, tmp_path, mesh_dp, monkeypatch
    ):
        from hyperion_tpu.train import trainer as trainer_mod
        from hyperion_tpu.train.trainer import train_language_model
        from hyperion_tpu import checkpoint as ckpt

        cfg = Config()
        cfg.train.epochs = 2
        cfg.train.batch_size = 32
        cfg.train.seq_len = 32
        cfg.train.steps_per_epoch = 6
        cfg.train.base_dir = str(tmp_path)
        cfg.train.validate = False

        class FakeGuard:
            """Triggers after the 4th step-boundary check — mid-epoch."""

            def __init__(self):
                self.checks = 0

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                pass

            @property
            def triggered(self):
                self.checks += 1
                return self.checks > 4

            def trigger(self):
                pass

        monkeypatch.setattr(trainer_mod, "PreemptionGuard", FakeGuard)
        res1 = train_language_model(cfg)
        assert res1.history == []  # preempted inside epoch 1
        ckpt_dir = f"{tmp_path}/checkpoints/language_ddp_8dev"
        step = ckpt.latest_step(ckpt_dir)
        assert step is not None and 0 < step < 6  # mid-epoch checkpoint

        monkeypatch.undo()
        res2 = train_language_model(cfg)  # resumes at (epoch 0, step)
        assert [r.epoch for r in res2.history] == [1, 2]
        final = ckpt.latest_step(ckpt_dir)
        assert final == 12  # every batch of both epochs trained exactly once
