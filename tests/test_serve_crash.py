"""Crash-safe serving, host half: the request journal's WAL + replay
semantics, the poison-pill rule, the brownout governor's hysteresis,
the drain door, deadline-aware shedding, the new serve-scoped chaos
clauses, and the serve supervisor loop — all jax-free and fast.

The engine-integrated halves (bit-identical replay, drain under load,
shed/clamp through a live engine, the supervised SIGKILL subprocess
round trip) live in tests/test_serve.py, where the compiled tiny-llama
shapes are shared with the rest of the suite.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from hyperion_tpu.serve.journal import RequestJournal
from hyperion_tpu.serve.queue import (
    REJECT_DRAINING,
    AdmissionQueue,
    BrownoutGovernor,
    Request,
)
from hyperion_tpu.testing import chaos


def _req(n=4, rid="", **kw):
    kw.setdefault("max_new_tokens", 4)
    return Request(prompt_ids=np.arange(1, n + 1, dtype=np.int32),
                   id=rid, **kw)


# ------------------------------------------------------------- journal


class TestJournal:
    def test_round_trip_resumes_unfinished_in_admit_order(self, tmp_path):
        """Admitted-but-unfinished requests come back with their
        journaled tokens riding along (the recompute-resume payload),
        sampling params intact, in original admit order."""
        jp = tmp_path / "j.jsonl"
        j = RequestJournal(jp)
        a = _req(5, "a", max_new_tokens=8, temperature=0.7, top_k=5,
                 top_p=0.9, seed=42)
        b = _req(3, "b", max_new_tokens=6)
        j.admit(a)
        j.admit(b)
        j.token("a", 17)
        j.token("a", 21)
        j.close()

        resume, finished, poisoned, clean = RequestJournal(jp).recover()
        assert not clean and not finished and not poisoned
        assert [r.id for r in resume] == ["a", "b"]
        ra, rb = resume
        assert ra.tokens == [17, 21] and rb.tokens == []
        assert ra.prompt_ids.tolist() == a.prompt_ids.tolist()
        assert (ra.max_new_tokens, ra.temperature, ra.top_k, ra.top_p,
                ra.seed) == (8, 0.7, 5, 0.9, 42)
        assert ra.replays == 1  # this recovery marked itself

    def test_finished_requests_never_replayed(self, tmp_path):
        jp = tmp_path / "j.jsonl"
        j = RequestJournal(jp)
        j.admit(_req(4, "a"))
        j.token("a", 9)
        j.finish("a", "done")
        j.close()
        resume, finished, poisoned, clean = RequestJournal(jp).recover()
        assert resume == [] and finished == [] and poisoned == []

    def test_clean_close_means_empty_replay_set(self, tmp_path):
        """The drain contract: a cleanly closed journal owes nothing,
        even if (pathologically) records precede the close marker."""
        jp = tmp_path / "j.jsonl"
        j = RequestJournal(jp)
        j.admit(_req(4, "a"))
        j.token("a", 9)
        j.close_clean()
        assert j.clean_closed
        resume, finished, poisoned, clean = RequestJournal(jp).recover()
        assert clean and resume == [] and poisoned == []
        assert RequestJournal(jp).pending_count() == 0

    def test_torn_tail_tolerated(self, tmp_path):
        """The record a SIGKILL'd process never finished writing must
        not abort recovery — it IS the crash signature."""
        jp = tmp_path / "j.jsonl"
        j = RequestJournal(jp)
        j.admit(_req(4, "a"))
        j.token("a", 7)
        j.close()
        with jp.open("a") as f:
            f.write('{"k":"tok","id":"a","to')  # torn mid-write
        resume, _, _, _ = RequestJournal(jp).recover()
        assert [r.id for r in resume] == ["a"]
        assert resume[0].tokens == [7]

    def test_complete_output_recovers_as_finished_not_resumed(
            self, tmp_path):
        """All budgeted tokens journaled but the terminal record lost:
        nothing to compute — re-prefilling would sample an EXTRA token
        past the budget. The request lands in `finished` (the client is
        owed only its done line) and gets its terminal record now."""
        jp = tmp_path / "j.jsonl"
        j = RequestJournal(jp)
        j.admit(_req(4, "a", max_new_tokens=3))
        for t in (5, 6, 7):
            j.token("a", t)
        j.close()
        resume, finished, _, _ = RequestJournal(jp).recover()
        assert resume == [] and [r.id for r in finished] == ["a"]
        assert finished[0].tokens == [5, 6, 7]
        # the terminal record was backfilled: the next recovery owes nothing
        assert RequestJournal(jp).pending_count() == 0

    def test_eos_terminated_output_recovers_as_finished(self, tmp_path):
        jp = tmp_path / "j.jsonl"
        j = RequestJournal(jp)
        j.admit(_req(4, "a", max_new_tokens=10))
        j.token("a", 5)
        j.token("a", 2)  # eos
        j.close()
        resume, finished, _, _ = RequestJournal(jp).recover(eos_id=2)
        assert resume == [] and [r.id for r in finished] == ["a"]

    def test_poison_rule_quarantines_after_max_replays(self, tmp_path):
        """Three recoveries with the same unfinished request: replay,
        replay, POISON — the adversarial request stops crash-looping
        the replica, and later recoveries skip it permanently."""
        jp = tmp_path / "j.jsonl"
        j = RequestJournal(jp)
        j.admit(_req(4, "evil"))
        j.close()
        r1, _, p1, _ = RequestJournal(jp).recover(max_replays=2)
        assert [r.id for r in r1] == ["evil"] and p1 == []
        r2, _, p2, _ = RequestJournal(jp).recover(max_replays=2)
        assert [r.id for r in r2] == ["evil"] and p2 == []
        r3, _, p3, _ = RequestJournal(jp).recover(max_replays=2)
        assert r3 == [] and [r.id for r in p3] == ["evil"]
        assert p3[0].replays == 2
        # permanently: the fourth recovery does not resurrect it
        r4, _, p4, _ = RequestJournal(jp).recover(max_replays=2)
        assert r4 == [] and p4 == []

    def test_io_failure_disables_never_raises(self, tmp_path):
        fails = {"n": 0}

        def fault(tag):
            fails["n"] += 1
            raise OSError("disk on fire")

        j = RequestJournal(tmp_path / "j.jsonl", fault=fault)
        j.admit(_req(4, "a"))  # must not raise
        assert not j.enabled and "disk on fire" in (j.error or "")
        j.token("a", 1)  # disabled: silent no-op, no second fault call
        assert fails["n"] == 1

    def test_journal_io_fail_chaos_clause(self, tmp_path):
        plan = chaos.ChaosPlan(chaos.parse_plan("journal_io_fail@p=1.0"))
        j = RequestJournal(tmp_path / "j.jsonl", fault=plan.journal_io)
        j.admit(_req(4, "a"))
        assert not j.enabled and "journal_io_fail" in (j.error or "")
        # p=0 never fires
        plan0 = chaos.ChaosPlan(chaos.parse_plan("journal_io_fail@p=0.0"))
        j0 = RequestJournal(tmp_path / "j0.jsonl", fault=plan0.journal_io)
        j0.admit(_req(4, "a"))
        assert j0.enabled

    def test_records_after_close_start_a_new_life(self, tmp_path):
        """A journal reused after a clean close (same path, next serve
        run) replays the NEW run's unfinished work — including when a
        client REUSES a request id: the old life's done marker must not
        skip the new life's replay, and the old life's tokens must not
        leak into the resume payload."""
        jp = tmp_path / "j.jsonl"
        j = RequestJournal(jp)
        j.admit(_req(4, "old"))
        j.finish("old", "done")
        j.admit(_req(4, "reused"))
        j.token("reused", 99)  # old life's token: settled history
        j.finish("reused", "done")
        j.close_clean()
        j2 = RequestJournal(jp)
        j2.admit(_req(4, "new"))
        j2.admit(_req(5, "reused"))  # same id, new life, unfinished
        j2.token("reused", 7)
        j2.close()
        resume, _, _, clean = RequestJournal(jp).recover()
        assert not clean
        assert [r.id for r in resume] == ["new", "reused"]
        (reused,) = [r for r in resume if r.id == "reused"]
        assert reused.tokens == [7]  # not [99, 7]
        assert reused.prompt_len == 5  # the NEW life's admit record


# ----------------------------------------------------- router journal


class TestRouterJournal:
    """serve/router_journal.py at the file level: the dispatch/hwm/done
    vocabulary, orphan recovery, re-open-after-terminal, and the tail
    reader the doctor's post-mortem cites."""

    @staticmethod
    def _wire(rid, n=8):
        return json.dumps({"id": rid, "prompt_ids": [1, 2],
                           "max_new_tokens": n})

    def test_orphan_carries_line_replica_session_hwm(self, tmp_path):
        from hyperion_tpu.serve.router_journal import RouterJournal

        jp = tmp_path / "rj.jsonl"
        j = RouterJournal(jp)
        j.dispatch("a", line=self._wire("a"), replica=1, session="s1")
        j.hwm("a", 2)
        j.hwm("a", 3)
        j.dispatch("b", line=self._wire("b"), replica=0, session=None)
        j.done("b", "done")
        j.close()
        orphans, clean = RouterJournal(jp).recover()
        assert not clean and [o.id for o in orphans] == ["a"]
        (o,) = orphans
        assert o.line == self._wire("a")  # wire line verbatim
        assert o.doc["max_new_tokens"] == 8
        assert (o.replica, o.session, o.hwm, o.dispatches) == (1, "s1",
                                                               3, 1)

    def test_redispatch_keeps_first_line_counts_placements(self,
                                                           tmp_path):
        """Failovers journal a dispatch per placement but the wire line
        rides only the first record — the WAL must not grow by the
        prompt on every failover."""
        from hyperion_tpu.serve.router_journal import RouterJournal

        jp = tmp_path / "rj.jsonl"
        j = RouterJournal(jp)
        j.dispatch("a", line=self._wire("a"), replica=0, session=None)
        j.dispatch("a", line=self._wire("a"), replica=1, session=None,
                   n=1)
        j.close()
        recs = [json.loads(line) for line in
                jp.read_text().splitlines()]
        assert recs[0]["line"] is not None and recs[1]["line"] is None
        orphans, _ = RouterJournal(jp).recover()
        assert orphans[0].dispatches == 2
        assert orphans[0].line == self._wire("a")
        assert orphans[0].replica == 1  # the LAST placement is evidence

    def test_dispatch_after_done_reopens(self, tmp_path):
        """A same-life resume after a client_gone terminal re-dispatches
        the id; a router death after that must recover it — the done
        marker is history, not a tombstone."""
        from hyperion_tpu.serve.router_journal import RouterJournal

        jp = tmp_path / "rj.jsonl"
        j = RouterJournal(jp)
        j.dispatch("a", line=self._wire("a"), replica=0, session=None)
        j.hwm("a", 2)
        j.done("a", "client_gone")
        j.dispatch("a", line=self._wire("a"), replica=1, session=None,
                   n=1)
        j.hwm("a", 5)
        j.close()
        orphans, _ = RouterJournal(jp).recover()
        assert [o.id for o in orphans] == ["a"]
        assert orphans[0].hwm == 5
        # ...and a terminal AFTER the re-open settles it again
        j2 = RouterJournal(jp)
        j2.done("a", "done")
        j2.close()
        orphans, _ = RouterJournal(jp).recover()
        assert orphans == []

    def test_clean_close_and_pending_count(self, tmp_path):
        from hyperion_tpu.serve.router_journal import RouterJournal

        jp = tmp_path / "rj.jsonl"
        j = RouterJournal(jp)
        j.dispatch("a", line=self._wire("a"), replica=0, session=None)
        assert RouterJournal(jp).pending_count() == 1
        j.done("a", "done")
        assert RouterJournal(jp).pending_count() == 0
        j.close_clean()
        orphans, clean = RouterJournal(jp).recover()
        assert clean and orphans == []
        assert RouterJournal(jp).pending_count() == 0

    def test_torn_tail_and_tail_reader(self, tmp_path):
        from hyperion_tpu.serve.router_journal import RouterJournal

        jp = tmp_path / "rj.jsonl"
        j = RouterJournal(jp)
        j.dispatch("a", line=self._wire("a"), replica=0, session=None)
        j.hwm("a", 1)
        j.close()
        with jp.open("a") as f:
            f.write('{"k":"hwm","id":"a","i')  # torn mid-write
        tail = RouterJournal(jp).tail(2)
        assert [r["k"] for r in tail] == ["dispatch", "hwm"]  # torn skipped
        orphans, _ = RouterJournal(jp).recover()
        assert orphans[0].hwm == 1

    def test_recovery_compacts_terminal_majority(self, tmp_path):
        """The compaction satellite on the router WAL: terminal streams
        drop out at recovery when they dominate the file; the orphan's
        records survive byte-exactly."""
        from hyperion_tpu.serve.router_journal import RouterJournal

        jp = tmp_path / "rj.jsonl"
        j = RouterJournal(jp)
        for i in range(8):
            j.dispatch(f"d{i}", line=self._wire(f"d{i}"), replica=0,
                       session=None)
            j.hwm(f"d{i}", 8)
            j.done(f"d{i}", "done")
        j.dispatch("live", line=self._wire("live"), replica=1,
                   session="sx")
        j.hwm("live", 4)
        j.close()
        before = jp.stat().st_size
        live_lines = [line for line in jp.read_text().splitlines()
                      if '"live"' in line]
        orphans, _ = RouterJournal(jp).recover()
        assert [o.id for o in orphans] == ["live"]
        after = jp.read_text()
        assert jp.stat().st_size < before
        assert "d0" not in after and "d7" not in after
        for line in live_lines:  # pending work preserved byte-exactly
            assert line in after


# --------------------------------------------- WAL byte-boundary fuzz


class TestWalByteFuzz:
    """The property satellite: a WAL truncated at EVERY byte boundary
    (any crash point) must recover to exactly the state its complete-
    line prefix describes — no phantom request, no duplicate or phantom
    token, hwm never past what was durably written — for BOTH the
    replica journal and the router WAL."""

    @staticmethod
    def _complete_lines(prefix: bytes):
        """The records recovery may legally see: every newline-
        terminated line, plus the torn last line iff it parses — a
        strict prefix of a JSON dict is only valid at its final `}`, so
        this admits exactly the case where the crash ate only the
        trailing newline."""
        segs = prefix.split(b"\n")
        out = []
        for raw in segs[:-1]:
            if raw.strip():
                out.append(json.loads(raw))
        if segs[-1].strip():
            try:
                out.append(json.loads(segs[-1]))
            except ValueError:
                pass
        return out

    def test_replica_journal_recovers_exact_prefix(self, tmp_path):
        import random

        rng = random.Random(7)
        jp = tmp_path / "full.jsonl"
        j = RequestJournal(jp)
        live: list[str] = []
        nxt = iter(f"r{i}" for i in range(99))
        for _ in range(18):
            roll = rng.random()
            if roll < 0.3 or not live:
                rid = next(nxt)
                j.admit(_req(3, rid, max_new_tokens=50))
                live.append(rid)
            elif roll < 0.85:
                j.token(rng.choice(live), rng.randrange(1000))
            else:
                j.finish(live.pop(rng.randrange(len(live))), "done")
        j.close()
        blob = jp.read_bytes()

        for cut in range(len(blob) + 1):
            tp = tmp_path / "t.jsonl"
            tp.write_bytes(blob[:cut])
            admits, toks, dones = [], {}, set()
            for rec in self._complete_lines(blob[:cut]):
                if rec["k"] == "admit":
                    admits.append(rec["id"])
                elif rec["k"] == "tok":
                    toks.setdefault(rec["id"], []).append(rec["tok"])
                elif rec["k"] == "done":
                    dones.add(rec["id"])
            resume, finished, poisoned, clean = \
                RequestJournal(tp).recover()
            assert not clean and not finished and not poisoned, cut
            want = [rid for rid in admits if rid not in dones]
            assert [r.id for r in resume] == want, cut
            for r in resume:  # prefix-consistent payload, no dup/phantom
                assert r.tokens == toks.get(r.id, []), (cut, r.id)

    def test_router_journal_recovers_exact_prefix(self, tmp_path):
        import random

        from hyperion_tpu.serve.router_journal import RouterJournal

        rng = random.Random(11)
        jp = tmp_path / "full.jsonl"
        j = RouterJournal(jp)
        live: list[str] = []
        nxt = iter(f"q{i}" for i in range(99))
        for _ in range(18):
            roll = rng.random()
            if roll < 0.3 or not live:
                rid = next(nxt)
                j.dispatch(rid, line=json.dumps({"id": rid}),
                           replica=rng.randrange(2), session=None)
                live.append(rid)
            elif roll < 0.85:
                j.hwm(rng.choice(live), rng.randrange(12))
            else:
                j.done(live.pop(rng.randrange(len(live))), "done")
        j.close()
        blob = jp.read_bytes()

        for cut in range(len(blob) + 1):
            tp = tmp_path / "t.jsonl"
            tp.write_bytes(blob[:cut])
            order, lines, hwms, dones = [], {}, {}, set()
            for rec in self._complete_lines(blob[:cut]):
                if rec["k"] == "dispatch":
                    if rec["id"] not in lines:
                        order.append(rec["id"])
                        lines[rec["id"]] = rec["line"]
                    dones.discard(rec["id"])  # re-open semantics
                elif rec["k"] == "hwm":
                    hwms[rec["id"]] = max(hwms.get(rec["id"], 0),
                                          rec["i"])
                elif rec["k"] == "done":
                    dones.add(rec["id"])
            orphans, clean = RouterJournal(tp).recover()
            assert not clean, cut
            want = [rid for rid in order if rid not in dones]
            assert [o.id for o in orphans] == want, cut
            for o in orphans:
                assert o.line == lines[o.id], cut  # no phantom payload
                assert o.hwm == hwms.get(o.id, 0), (cut, o.id)


# ---------------------------------------------------- brownout governor


class TestBrownoutGovernor:
    def test_depth_hysteresis_no_flap(self):
        g = BrownoutGovernor(depth_high=8)  # low defaults to 4
        assert g.update(7) is None and not g.active
        assert g.update(8) == "enter" and g.active
        # between the watermarks: stays active, no transition spam
        for d in (7, 6, 5):
            assert g.update(d) is None and g.active
        assert g.update(4) == "exit" and not g.active
        # between the watermarks from below: stays OFF — the half the
        # hysteresis exists for
        for d in (5, 6, 7):
            assert g.update(d) is None and not g.active
        assert g.update(9) == "enter"

    def test_wait_watermark_enters_and_exits(self):
        g = BrownoutGovernor(depth_high=0, wait_high_s=1.0)
        for _ in range(10):
            g.observe_wait(2.0)
        assert g.update(0) == "enter"
        # exit clears the stale window, so recovery is immediate once
        # the observed waits are gone
        assert g.update(0) is None  # p95 still 2.0 > low 0.5
        g._waits.clear()
        g.observe_wait(0.1)
        assert g.update(0) == "exit"
        assert g.update(0) is None

    def test_both_signals_must_clear_to_exit(self):
        g = BrownoutGovernor(depth_high=4, wait_high_s=1.0)
        for _ in range(5):
            g.observe_wait(2.0)
        assert g.update(10) == "enter"
        assert g.update(0) is None  # depth fine, wait p95 still high
        g._waits.clear()
        g.observe_wait(0.0)
        assert g.update(10) is None  # wait fine, depth still high
        assert g.update(0) == "exit"

    def test_needs_a_watermark(self):
        with pytest.raises(ValueError):
            BrownoutGovernor(depth_high=0)


# ----------------------------------------------------- drain + shedding


class TestDrainDoor:
    def test_closed_queue_rejects_with_draining(self):
        q = AdmissionQueue(4, max_total_tokens=64)
        assert q.submit(_req(4)) == (True, None)
        q.close()
        ok, reason = q.submit(_req(4))
        assert not ok and reason == REJECT_DRAINING
        assert q.closed
        # already-accepted work still pops: drain finishes what it owes
        admit, _ = q.pop_ready(2)
        assert len(admit) == 1

    def test_shed_doomed_is_deadline_aware(self):
        q = AdmissionQueue(8, max_total_tokens=64)
        doomed = _req(4, "doomed", deadline_s=0.05)
        winner = _req(4, "winner", deadline_s=60.0)
        no_slo = _req(4, "no_slo")  # no deadline: never shed
        for r in (doomed, winner, no_slo):
            q.submit(r)
        now = time.monotonic()
        # est wait 1 s: doomed (50 ms headroom) cannot win; winner can
        shed = q.shed_doomed(now, est_wait_s=1.0)
        assert [r.id for r in shed] == ["doomed"]
        assert doomed.status == "rejected"
        assert len(q) == 2

    def test_shed_orders_most_doomed_first(self):
        q = AdmissionQueue(8, max_total_tokens=64)
        late = _req(4, "late", deadline_s=0.08)
        soon = _req(4, "soon", deadline_s=0.01)
        q.submit(late)
        q.submit(soon)
        shed = q.shed_doomed(time.monotonic(), est_wait_s=5.0)
        assert [r.id for r in shed] == ["soon", "late"]


# ------------------------------------------------------- chaos grammar


class TestServeChaosGrammar:
    def test_new_clauses_parse_with_keys(self):
        faults = chaos.parse_plan(
            "crash@tick=3,journal_io_fail@p=0.25,poison_request@id=req_7")
        assert [f.key for f in faults] == [
            "crash@tick=3", "journal_io_fail@p=0.25",
            "poison_request@id=req_7"]
        assert faults[0].unit == "tick"
        assert faults[2].rid == "req_7"

    def test_crash_is_tick_scoped_only(self):
        with pytest.raises(ValueError, match="unknown chaos clause"):
            chaos.parse_plan("crash@step=3")

    def test_crash_dispatch_clause_parses_router_scoped(self):
        (f,) = chaos.parse_plan("crash@dispatch=3")
        assert (f.kind, f.unit, f.step) == ("crash", "dispatch", 3)
        assert f.key == "crash@dispatch=3"
        # dispatch-scoped: the serve tick hook must NOT fire it (it
        # would os._exit — surviving the call IS the assertion)
        plan = chaos.ChaosPlan([f])
        plan.on_tick(3)
        plan.on_step(3)
        plan.on_dispatch(2)  # wrong count: no fire
        assert not plan._fired

    def test_conn_reset_clause_validates_and_draws_own_stream(self):
        with pytest.raises(ValueError, match="outside"):
            chaos.parse_plan("conn_reset@p=1.5")
        plan = chaos.ChaosPlan(chaos.parse_plan("conn_reset@p=1.0"))
        with pytest.raises(ConnectionResetError):
            plan.conn_reset("route_client_write")
        never = chaos.ChaosPlan(chaos.parse_plan("conn_reset@p=0.0"))
        for _ in range(64):
            never.conn_reset("route_client_write")
        # its own RNG stream: adding a reset plan must not shift the
        # io_fail draw sequence other tests pinned
        a = chaos.ChaosPlan(chaos.parse_plan("io_fail@p=0.5"), seed=3)
        b = chaos.ChaosPlan(
            chaos.parse_plan("io_fail@p=0.5,conn_reset@p=0.5"), seed=3)
        seq_a, seq_b = [], []
        for _ in range(32):
            for plan, seq in ((a, seq_a), (b, seq_b)):
                try:
                    plan.io_fail("t")
                    seq.append(0)
                except OSError:
                    seq.append(1)
            try:
                b.conn_reset("t")
            except ConnectionResetError:
                pass
        assert seq_a == seq_b

    def test_crash_dispatch_fires_once_per_lineage(self, tmp_path):
        """The supervised-router contract: a restarted life (same state
        path) passing the same dispatch count again must NOT re-die —
        proven in-process via the fire record, since the fire itself is
        os._exit."""
        state = tmp_path / "chaos_state.json"
        plan = chaos.ChaosPlan(chaos.parse_plan("crash@dispatch=3"),
                               state_path=state)
        plan._mark(plan.faults[0])  # what the dying life wrote
        life2 = chaos.ChaosPlan(chaos.parse_plan("crash@dispatch=3"),
                                state_path=state)
        life2.on_dispatch(3)  # surviving the call IS the assertion
        assert "crash@dispatch=3" in life2._fired

    def test_journal_p_validated(self):
        with pytest.raises(ValueError, match="outside"):
            chaos.parse_plan("journal_io_fail@p=1.5")

    def test_poison_only_fires_on_matching_request(self):
        """Unit isolation: poison_request must not fire from step/tick
        hooks nor for other request ids (on a match it would SIGKILL —
        reaching the assertion IS the test)."""
        plan = chaos.ChaosPlan(chaos.parse_plan("poison_request@id=evil"))
        plan.on_step(0)
        plan.on_tick(0)
        plan.on_request("innocent")
        assert not plan._fired  # poison is exempt from the fire record

    def test_journal_io_uses_its_own_rng_stream(self):
        """Adding a journal clause must not shift the io_fail@p draw
        sequence the checkpoint-retry tests pinned."""
        a = chaos.ChaosPlan(chaos.parse_plan("io_fail@p=0.5"), seed=3)
        b = chaos.ChaosPlan(
            chaos.parse_plan("io_fail@p=0.5,journal_io_fail@p=0.5"),
            seed=3)
        seq_a, seq_b = [], []
        for _ in range(32):
            for plan, seq in ((a, seq_a), (b, seq_b)):
                try:
                    plan.io_fail("t")
                    seq.append(0)
                except OSError:
                    seq.append(1)
            try:
                b.journal_io("t")  # interleave journal draws into b
            except OSError:
                pass
        assert seq_a == seq_b


# --------------------------------------------------- supervisor (serve)


class TestServeSupervisor:
    def test_loop_restarts_on_crash_and_gives_up(self):
        from hyperion_tpu.supervisor import (
            EXIT_GAVE_UP,
            Decision,
            supervise_loop,
        )

        rcs = [70, 70, 70, 70]
        attempts = []

        def child(argv, env):
            attempts.append(env["HYPERION_ATTEMPT"])
            return rcs.pop(0)

        rc = supervise_loop(["serve"], decide=lambda rc: Decision.restart(),
                            max_restarts=2, run_child=child,
                            sleep=lambda s: None, label="serve-supervisor")
        assert rc == EXIT_GAVE_UP
        assert attempts == ["0", "1", "2"]

    def test_loop_stops_on_success_and_usage(self):
        from hyperion_tpu.supervisor import Decision, supervise_loop

        assert supervise_loop(
            ["x"], decide=lambda rc: Decision.restart(), max_restarts=5,
            run_child=lambda a, e: 0, sleep=lambda s: None) == 0
        assert supervise_loop(
            ["x"], decide=lambda rc: Decision.restart(), max_restarts=5,
            run_child=lambda a, e: 2, sleep=lambda s: None) == 2

    def test_heartbeat_watchdog_kills_stale_child(self, tmp_path):
        """A child that never beats (wedged before its first beat) is
        SIGKILLed once the stale window passes and reported as hung."""
        from hyperion_tpu.supervisor import RC_HUNG, heartbeat_watchdog

        runner = heartbeat_watchdog(tmp_path / "heartbeat.json",
                                    stale_s=0.5, poll_s=0.05)
        t0 = time.monotonic()
        rc = runner([sys.executable, "-c", "import time; time.sleep(60)"],
                    None)
        assert rc == RC_HUNG
        assert time.monotonic() - t0 < 30

    def test_heartbeat_watchdog_fresh_child_exits_normally(self, tmp_path):
        from hyperion_tpu.supervisor import heartbeat_watchdog

        hb = tmp_path / "heartbeat.json"
        hb.write_text("{}")
        runner = heartbeat_watchdog(hb, stale_s=30.0, poll_s=0.05)
        assert runner([sys.executable, "-c", "raise SystemExit(7)"],
                      None) == 7

    def test_serve_strip_supervise_flags(self):
        from hyperion_tpu.serve.server import _strip_supervise_flags

        argv = ["--ckpt", "m.npz", "--supervise", "--max-restarts", "3",
                "--hang-timeout", "5", "--journal", "j.jsonl"]
        assert _strip_supervise_flags(argv) == [
            "--ckpt", "m.npz", "--journal", "j.jsonl"]
        assert _strip_supervise_flags(
            ["--max-restarts=3", "--hang-timeout=5", "--supervise"]) == []


# ------------------------------------------- socket-path crash handling


class TestStaleSocket:
    def test_stale_socket_unlinked_live_socket_refused(self, tmp_path):
        import socket as socket_mod

        from hyperion_tpu.serve.server import prepare_socket_path

        # nonexistent: no-op
        prepare_socket_path(str(tmp_path / "none.sock"))

        # stale file a crashed server left behind: unlinked
        stale = tmp_path / "stale.sock"
        s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
        s.bind(str(stale))
        s.close()  # bound then closed without listen: connect refuses
        assert stale.exists()
        prepare_socket_path(str(stale))
        assert not stale.exists()

        # live listener: refused loudly, file untouched
        live = tmp_path / "live.sock"
        srv = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
        srv.bind(str(live))
        srv.listen(1)
        try:
            with pytest.raises(RuntimeError, match="live server"):
                prepare_socket_path(str(live))
            assert live.exists()
        finally:
            srv.close()


# ------------------------------------------------ doctor + diff (files)


class TestObsIntegration:
    def _stream(self, tmp_path, counters, gauges=None, events=()):
        run = "serve_rb"
        recs = [
            {"v": 1, "kind": "event", "name": "serve_start", "run": run,
             "proc": 0, "t_wall": 100.0, "t_mono": 1.0},
            {"v": 1, "kind": "span", "name": "serve_tick", "run": run,
             "proc": 0, "step": 1, "t_wall": 100.5, "t_mono": 1.5,
             "dur_ms": 2.0},
        ]
        for name, attrs in events:
            recs.append({"v": 1, "kind": "event", "name": name,
                         "run": run, "proc": 0, "t_wall": 101.0,
                         "t_mono": 2.0, **attrs})
        recs.append({
            "v": 1, "kind": "snapshot", "name": "metrics", "run": run,
            "proc": 0, "t_wall": 102.0, "t_mono": 3.0,
            "metrics": {"counters": {"serve_ticks": 5, **counters},
                        "gauges": {"queue_depth": 0.0, **(gauges or {})},
                        "histograms": {}},
        })
        recs.append({"v": 1, "kind": "event", "name": "serve_end",
                     "run": run, "proc": 0, "t_wall": 103.0,
                     "t_mono": 4.0, "completed": 3})
        p = tmp_path / "telemetry.jsonl"
        p.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        return tmp_path

    def test_doctor_names_brownout_overload(self, tmp_path):
        from hyperion_tpu.obs import doctor

        d = doctor.diagnose(self._stream(
            tmp_path, {"serve_shed": 4, "serve_brownout_clamped": 2},
            gauges={"serve_brownout_active": 1.0}))
        assert d["verdict"] == "healthy"
        assert d["overload"], "brownout left no named incident"
        assert any("shed 4" in o for o in d["overload"])
        assert any("clamped" in o for o in d["overload"])
        assert any("ACTIVE" in o for o in d["overload"])
        assert "serving robustness" in d["reason"]
        md = doctor.render_markdown(d)
        assert "serve robustness" in md and "overload" in md

    def test_doctor_names_poisoned_request_and_journal_error(
            self, tmp_path):
        from hyperion_tpu.obs import doctor

        d = doctor.diagnose(self._stream(
            tmp_path,
            {"serve_poisoned": 1, "serve_journal_errors": 1,
             "serve_replayed": 2},
            events=[("request_poisoned",
                     {"request": "evil_1", "replays": 2})]))
        assert d["poisoned_requests"] == ["evil_1"]
        assert any("poison pill" in o and "evil_1" in o
                   for o in d["overload"])
        assert any("journal" in o for o in d["overload"])

    def test_diff_gates_shed_and_clamp_rates(self, tmp_path):
        from hyperion_tpu.obs import diff as obs_diff

        def line(shed, clamp):
            return {"metric": "matmul_bf16_8192_tflops", "value": 100.0,
                    "serving": {"tokens_per_s": 500.0,
                                "shed_rate": shed, "clamp_rate": clamp}}

        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(line(0.01, 0.01)))
        b.write_text(json.dumps(line(0.4, 0.5)))
        d = obs_diff.diff(obs_diff.load_summary(a),
                          obs_diff.load_summary(b))
        assert {"serve_shed_rate", "serve_clamp_rate"} \
            <= set(d["regressions"])

    def test_smoke_script_has_kill_and_resume_round_trip(self):
        """The CI satellite: serve_smoke.sh must carry the supervised
        kill-and-resume leg (its flags are drift-guarded by
        test_serve.py's parser check like every other invocation)."""
        script = (Path(__file__).resolve().parents[1] / "scripts"
                  / "serve_smoke.sh").read_text()
        assert "--supervise" in script and "crash@tick" in script
        assert "--journal" in script


# -------------------------------------------- flight-record post-mortem


class TestFlightPostMortem:
    """Flight recorder × doctor: a serve loop that died without a
    terminal event must have its verdict cite the flight record's
    final ticks — the only evidence of what the loop was doing."""

    def _dead_stream(self, tmp_path, run="serve_fl"):
        recs = [
            {"v": 1, "kind": "event", "name": "serve_start", "run": run,
             "proc": 0, "t_wall": 100.0, "t_mono": 1.0},
        ]
        for i in range(6):
            recs.append({"v": 1, "kind": "span", "name": "serve_tick",
                         "run": run, "proc": 0, "step": i,
                         "t_wall": 100.0 + 0.1 * i,
                         "t_mono": 1.0 + 0.1 * i, "dur_ms": 2.0})
        # no serve_end: the loop died mid-flight
        (tmp_path / "telemetry.jsonl").write_text(
            "\n".join(json.dumps(r) for r in recs) + "\n")
        return tmp_path

    def test_hung_verdict_cites_flight_final_tick(self, tmp_path):
        from hyperion_tpu.obs import doctor
        from hyperion_tpu.obs.tickprof import FLIGHT_NAME, FLIGHT_SCHEMA

        run = "serve_fl"
        self._dead_stream(tmp_path, run)
        flight = {
            "v": FLIGHT_SCHEMA, "run": run, "pid": 4242,
            "t_wall": 100.6, "reason": "periodic", "tick": 41,
            "spills": 3, "active": 2, "queue": 5, "events": [],
            "ticks": [{"tick": 40, "total": 0.002},
                      {"tick": 41, "total": 0.002}],
            "tickprof": {"dominant": "journal", "dominant_frac": 0.61,
                         "ticks": 2},
        }
        (tmp_path / FLIGHT_NAME).write_text(json.dumps(flight))

        d = doctor.diagnose(tmp_path, now=100.6 + 10_000)
        assert d["verdict"] in ("hung", "crashed"), d["reason"]
        fl = d["flight"]
        assert fl and fl["final_tick"] == 41 and fl["spills"] == 3
        assert "flight record: last spill at tick 41" in d["reason"]
        assert "2 active + 5 queued" in d["reason"]
        assert "dominant segment journal 61%" in d["reason"]
        md = doctor.render_markdown(d)
        assert "| flight record |" in md and "`journal`" in md

    def test_other_runs_flight_record_is_ignored(self, tmp_path):
        """A stale flight.json from an earlier run in the same dir must
        not pollute this run's verdict (same run-filter contract as the
        heartbeat)."""
        from hyperion_tpu.obs import doctor
        from hyperion_tpu.obs.tickprof import FLIGHT_NAME

        self._dead_stream(tmp_path, "serve_fl")
        (tmp_path / FLIGHT_NAME).write_text(json.dumps(
            {"v": 1, "run": "somebody_else", "tick": 9, "reason": "x"}))
        d = doctor.diagnose(tmp_path, now=110_000.0)
        assert d["flight"] is None
        assert "flight record" not in d["reason"]

    def test_smoke_script_asserts_flight_and_dominant_segment(self):
        """The CI satellite: serve_smoke.sh's kill drill must assert
        flight.json lands, and its obs-top leg must check the
        dominant-segment column."""
        script = (Path(__file__).resolve().parents[1] / "scripts"
                  / "serve_smoke.sh").read_text()
        assert "flight.json" in script
        assert "flight_final_tick" in script
        assert "dominant_segment" in script
