"""Test harness: simulated 8-device TPU-shaped mesh on CPU.

The reference has no test suite (SURVEY §4); its answer to "multi-node
without a cluster" was unsolved. Ours: force the CPU backend with 8
virtual devices (`--xla_force_host_platform_device_count=8`) so every
sharding/collective path runs under pytest on any machine. The axon/TPU
sitecustomize may have already imported jax with JAX_PLATFORMS=tpu, so
the platform is overridden via jax.config, not env vars.
"""

import os
import tempfile

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ.setdefault("HF_HUB_OFFLINE", "1")
os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")
# Persistent XLA compile cache, shared with the subprocess CLI tests
# (supervisor/serve spawn `python -m hyperion_tpu.cli.main ...`, which
# inherits this env): the trainer re-jits an identical step function
# per call, and without the cache each integration test pays the same
# ~35s XLA compile again. Content-keyed, so correctness is unaffected;
# compile-count assertions count traces, not XLA wall time.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "hyperion_tpu_xla_cache"),
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# sitecustomize may have imported jax before the env var landed; the
# runtime config update covers the in-process half either way
jax.config.update(
    "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    ds = jax.devices()
    assert len(ds) == 8, f"expected 8 simulated devices, got {len(ds)}"
    return ds


@pytest.fixture(scope="session")
def mesh8():
    from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data=2, fsdp=4))


@pytest.fixture(scope="session")
def mesh_dp():
    from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data=-1))
