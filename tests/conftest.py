"""Test harness: simulated 8-device TPU-shaped mesh on CPU.

The reference has no test suite (SURVEY §4); its answer to "multi-node
without a cluster" was unsolved. Ours: force the CPU backend with 8
virtual devices (`--xla_force_host_platform_device_count=8`) so every
sharding/collective path runs under pytest on any machine. The axon/TPU
sitecustomize may have already imported jax with JAX_PLATFORMS=tpu, so
the platform is overridden via jax.config, not env vars.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ.setdefault("HF_HUB_OFFLINE", "1")
os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    ds = jax.devices()
    assert len(ds) == 8, f"expected 8 simulated devices, got {len(ds)}"
    return ds


@pytest.fixture(scope="session")
def mesh8():
    from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data=2, fsdp=4))


@pytest.fixture(scope="session")
def mesh_dp():
    from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(data=-1))
