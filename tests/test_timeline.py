"""`obs trace` consumer: timeline reconstruction from the golden serve
fixture, Chrome trace-event export validity, tail-attribution math, the
doctor's named serving incidents, and the new `obs diff` attribution
gates. Everything here is host-only JSONL parsing — zero jit compiles
(the live producer↔consumer round trip lives in tests/test_serve.py,
riding shapes the suite already compiled).
"""

from __future__ import annotations

import json
import math
from pathlib import Path

import pytest

from hyperion_tpu.obs import timeline
from hyperion_tpu.obs.diff import diff as obs_diff
from hyperion_tpu.obs.diff import normalize
from hyperion_tpu.obs.doctor import diagnose
from hyperion_tpu.obs.report import read_records

FIXTURES = Path(__file__).resolve().parent / "data" / "telemetry"
SERVE_DIR = FIXTURES / "serve"


@pytest.fixture(scope="module")
def serve_records():
    return read_records(SERVE_DIR / "telemetry.jsonl")


@pytest.fixture(scope="module")
def serve_reqs(serve_records):
    return timeline.requests_from_records(serve_records)


# ---------------------------------------------------- reconstruction


class TestReconstruction:
    def test_all_requests_reconstructed(self, serve_reqs):
        by_id = {r.id: r for r in serve_reqs}
        assert len(by_id) == 8
        assert sum(1 for r in serve_reqs if r.status == "done") == 6
        assert by_id["r6"].status == "rejected"
        assert by_id["r7"].status == "timed_out"

    def test_phase_totals_from_finished_event(self, serve_reqs):
        r0 = next(r for r in serve_reqs if r.id == "r0")
        assert r0.phases["queue_wait"] == pytest.approx(0.30)
        assert r0.phases["prefill"] == pytest.approx(0.020)
        assert r0.phases["decode"] == pytest.approx(0.050)
        assert r0.e2e_s == pytest.approx(0.373)
        assert r0.ttft_s == pytest.approx(0.320)
        # the explicit remainder keeps the decomposition exact
        assert r0.other_s == pytest.approx(
            r0.e2e_s - sum(r0.phases.values()))

    def test_preemption_replay_reconstructed(self, serve_reqs):
        r3 = next(r for r in serve_reqs if r.id == "r3")
        assert r3.preempts == 1
        assert r3.phases["preempt_replay"] == pytest.approx(0.080)
        assert ("preempted" in {m[0] for m in r3.marks})
        names = [s[0] for s in r3.segments]
        assert "replay_wait" in names and "replay_prefill" in names

    def test_waterfall_segments_ordered(self, serve_reqs):
        """Segments within a request must be non-overlapping and in
        time order — the property that makes the waterfall readable."""
        for r in serve_reqs:
            end = -math.inf
            for _name, t0, dur in sorted(r.segments, key=lambda s: s[1]):
                assert dur >= 0
                assert t0 >= end - 1e-9, f"{r.id} segments overlap"
                end = t0 + dur

    def test_rejected_and_timed_out_carry_queued(self, serve_reqs):
        by_id = {r.id: r for r in serve_reqs}
        assert by_id["r6"].queued_s == 0.0
        assert by_id["r7"].queued_s == pytest.approx(0.6)


# -------------------------------------------------------- attribution


class TestAttribution:
    def test_components_sum_to_measured_latency(self, serve_reqs):
        """The acceptance property: per-phase components + other ==
        the measured value, exactly, for every attribution row."""
        att = timeline.attribution(serve_reqs)
        assert att["rows"], "no attribution rows"
        for row in att["rows"]:
            total = sum(row["components_ms"].values()) + row["other_ms"]
            assert total == pytest.approx(row["value_ms"], abs=0.01)
            # and the NAMED phases carry the value (other is slack,
            # not a dumping ground): within 5% on this fixture
            assert sum(row["components_ms"].values()) >= 0.95 * row["value_ms"]

    def test_queue_wait_dominates_fixture(self, serve_reqs):
        att = timeline.attribution(serve_reqs)
        by_key = {(r["metric"], r["q"]): r for r in att["rows"]}
        assert by_key[("ttft", 99)]["dominant"] == "queue_wait"
        assert by_key[("ttft", 99)]["dominant_frac"] > 0.5
        assert by_key[("e2e", 99)]["dominant"] == "queue_wait"
        # the preempted request IS the e2e p99 cohort: replay visible
        assert by_key[("e2e", 99)]["components_ms"][
            "preempt_replay"] == pytest.approx(80.0)

    def test_rejects_and_timeouts_in_tables(self, serve_reqs):
        """Satellite contract: dead requests appear in the attribution
        output instead of vanishing from tail analysis."""
        att = timeline.attribution(serve_reqs)
        assert att["rejected"]["count"] == 1
        assert att["timed_out"]["count"] == 1
        assert att["timed_out"]["queued_p99_ms"] == pytest.approx(600.0)

    def test_worst_requests_include_timeouts(self, serve_reqs):
        worst = timeline.worst_requests(serve_reqs, k=3)
        done = [w for w in worst if w["status"] == "done"]
        assert len(done) == 3
        assert done == sorted(done, key=lambda w: -w["e2e_ms"])
        assert any(w["status"] == "timed_out" for w in worst)


# ------------------------------------------------------ Chrome export


class TestChromeExport:
    def test_export_is_valid_trace_event_json(self, serve_reqs,
                                              serve_records, tmp_path):
        doc = timeline.chrome_trace(serve_reqs, serve_records,
                                    run="fix_serve")
        # JSON round trip: what a real viewer loads
        doc = json.loads(json.dumps(doc))
        evs = doc["traceEvents"]
        assert evs
        for e in evs:
            assert e["ph"] in ("X", "i", "M")
            if e["ph"] != "M":
                assert isinstance(e["ts"], (int, float))
                assert math.isfinite(e["ts"]) and e["ts"] >= 0
            if e["ph"] == "X":
                assert math.isfinite(e["dur"]) and e["dur"] >= 0

    def test_every_request_owns_a_thread(self, serve_reqs, serve_records):
        evs = timeline.chrome_trace(
            serve_reqs, serve_records, run="fix_serve")["traceEvents"]
        names = {e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        for rid in ("r0", "r3", "r7"):
            assert any(rid in n for n in names), f"{rid} missing: {names}"
        # engine ticks ride their own track
        assert any(e["name"] == "serve_tick" for e in evs)
        # one tid per request: segments of different requests never
        # share a thread row
        tid_by_req = {}
        for e in evs:
            rid = e.get("args", {}).get("request")
            if rid and e["ph"] == "X" and e["name"] != "serve_prefill":
                tid_by_req.setdefault(rid, set()).add(e["tid"])
        assert all(len(tids) == 1 for tids in tid_by_req.values())
        tids = [next(iter(t)) for t in tid_by_req.values()]
        assert len(set(tids)) == len(tids)


# ------------------------------------------------------- doctor + diff


class TestDoctorIncidents:
    def test_queue_wait_dominated_run_raises_named_incident(self):
        d = diagnose(SERVE_DIR)
        assert d["verdict"] == "healthy"
        assert d["tail_incidents"], "no incident on queue-dominated run"
        assert any("queue wait" in i and "--slots" in i
                   for i in d["tail_incidents"])
        assert "queue wait" in d["reason"]
        assert d["tail_attribution"]

    def test_heartbeat_payload_surfaced(self):
        """Satellite contract: the serve loop's heartbeat payload (tick
        / active slots / queue depth) reaches the doctor's evidence."""
        d = diagnose(SERVE_DIR)
        assert d["heartbeat"] is not None
        assert d["heartbeat"]["active"] is not None
        assert d["heartbeat"]["queue"] is not None

    def test_non_serve_runs_have_no_tail_rows(self):
        d = diagnose(FIXTURES / "healthy")
        assert d["verdict"] == "healthy"
        assert d["tail_attribution"] == []
        assert d["tail_incidents"] == []


class TestDiffGates:
    def _serving_doc(self, **over):
        srv = {"tokens_per_s": 500.0, "ttft_p50_ms": 10.0,
               "ttft_p99_ms": 40.0, "reject_rate": 0.0,
               "queue_wait_p99_ms": 30.0, "gate_wait_p99_ms": 1.0,
               "prefill_p99_ms": 5.0, "decode_p99_ms": 8.0,
               "preempt_replay_p99_ms": 2.0, "client_write_p99_ms": 0.5}
        srv.update(over)
        return {"metric": "matmul_8192_tflops", "value": 100.0,
                "serving": srv}

    def test_attribution_keys_normalized(self):
        m = normalize(self._serving_doc())
        for k in ("serve_queue_wait_p99_ms", "serve_prefill_p99_ms",
                  "serve_decode_p99_ms", "serve_preempt_replay_p99_ms",
                  "serve_client_write_p99_ms", "serve_gate_wait_p99_ms"):
            assert k in m, f"{k} not normalized"

    def test_tail_moving_between_phases_is_gated(self):
        """A tail that MOVES (queue doubles, prefill halves, aggregate
        ttft flat) must still regress — the reason the components are
        gated at all."""
        a = {"label": "a", "metrics": normalize(self._serving_doc())}
        b = {"label": "b", "metrics": normalize(self._serving_doc(
            queue_wait_p99_ms=65.0, prefill_p99_ms=2.0))}
        d = obs_diff(a, b, threshold=0.10)
        assert "serve_queue_wait_p99_ms" in d["regressions"]
        assert "serve_ttft_p99_ms" not in d["regressions"]

    def test_improvement_not_flagged(self):
        a = {"label": "a", "metrics": normalize(self._serving_doc())}
        b = {"label": "b", "metrics": normalize(self._serving_doc(
            queue_wait_p99_ms=10.0))}
        assert not obs_diff(a, b, threshold=0.10)["regressions"]


# --------------------------------------------------------- CLI + drift


class TestCli:
    def test_trace_cli_round_trip(self, tmp_path, capsys):
        export = tmp_path / "t.json"
        rc = timeline.main([str(SERVE_DIR), "--export", str(export),
                            "--top", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Tail attribution" in out and "queue_wait" in out
        doc = json.loads(export.read_text())
        assert doc["traceEvents"]

    def test_trace_cli_json_mode(self, tmp_path, capsys):
        rc = timeline.main([str(SERVE_DIR), "--export", "none", "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["attribution"]["rows"]
        assert doc["export"] is None

    def test_trace_cli_empty_stream_exits_2(self, tmp_path, capsys):
        (tmp_path / "telemetry.jsonl").write_text(
            '{"v":1,"kind":"event","name":"train_start","run":"x"}\n')
        assert timeline.main([str(tmp_path)]) == 2

    def test_smoke_script_trace_invocation_parses(self):
        """Flag-drift guard (the serve-invocation pattern): the
        `obs trace` call in scripts/serve_smoke.sh must parse against
        the real arg surface."""
        import re
        import shlex

        script = (Path(__file__).resolve().parents[1] / "scripts"
                  / "serve_smoke.sh").read_text()
        script = re.sub(r"\\\n\s*", " ", script)
        calls = re.findall(
            r"python -m hyperion_tpu\.cli\.main obs trace\s+(.*)", script)
        assert calls, "serve_smoke.sh lost its obs trace round trip"
        for call in calls:
            toks = shlex.split(call.split(">")[0])
            args = timeline.build_parser().parse_args(
                [re.sub(r"\$\{?\w+\}?", "x", t) for t in toks])
            assert args.export is not None


def test_dominant_of_shared_rule():
    """The one definition of "dominant phase" (argmax + other-demotion)
    that both `_cohort_row` and loadgen's bench row use."""
    assert timeline.dominant_of({}, 1.0) is None
    assert timeline.dominant_of({"queue_wait": 5.0, "decode": 2.0},
                                4.0) == "queue_wait"
    assert timeline.dominant_of({"queue_wait": 3.0, "decode": 2.0},
                                4.0) == "other"


def test_cohort_dominant_matches_attribution(serve_reqs):
    """loadgen's bench path (`cohort_dominant`) and `attribution()`
    must name the same phase for the same requests."""
    done = [r for r in serve_reqs if r.status == "done" and r.phases]
    named = timeline.cohort_dominant(
        [r.e2e_s for r in done], [r.phases for r in done])
    att = timeline.attribution(serve_reqs)
    e2e99 = next(r for r in att["rows"]
                 if r["metric"] == "e2e" and r["q"] == 99)
    assert named == e2e99["dominant"] == "queue_wait"
    assert timeline.cohort_dominant([], []) is None


def test_requeue_event_restarts_queue_segment():
    """An allocation-race bounce (`request_requeued`) must restart the
    waterfall's queue segment — the renewed wait can't vanish."""
    recs = [
        {"run": "r", "kind": "event", "name": "request_admitted",
         "request": "a", "t_mono": 1.0, "prompt_len": 4},
        {"run": "r", "kind": "event", "name": "request_scheduled",
         "request": "a", "t_mono": 2.0, "queue_wait_s": 1.0,
         "gate_wait_s": 0.0, "replay_wait_s": 0.0},
        {"run": "r", "kind": "event", "name": "request_requeued",
         "request": "a", "t_mono": 2.0, "reason": "alloc_race"},
        {"run": "r", "kind": "event", "name": "request_scheduled",
         "request": "a", "t_mono": 5.0, "queue_wait_s": 3.0,
         "gate_wait_s": 0.0, "replay_wait_s": 0.0},
    ]
    (rt,) = timeline.requests_from_records(recs)
    queue_segs = [s for s in rt.segments if s[0] == "queue"]
    assert len(queue_segs) == 2
    assert queue_segs[1][1] == pytest.approx(2.0)   # restarts at bounce
    assert queue_segs[1][2] == pytest.approx(3.0)   # renewed wait visible
    assert ("requeued", 2.0) in rt.marks


def test_loadgen_request_ids_seed_derived():
    from hyperion_tpu.serve.loadgen import request_id

    assert request_id(0, 3) == "load_s0_003"
    assert request_id(7, 3) != request_id(0, 3)
    # stable across calls — the property fixtures and bench rows need
    assert request_id(5, 11) == request_id(5, 11)
