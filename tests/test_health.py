"""obs/health + obs/heartbeat — the watchdog half of the telemetry layer.

Unit tests are device-free (the monitor consumes python floats by
contract; the heartbeat is pure file IO). The trainer integration tests
run the real language driver on the simulated mesh: a diverging run
under the `abort` policy must stop, record the `health` event in
telemetry.jsonl, and skip exports — and instrumentation must add ZERO
host fences inside the step loop (counted the same way the epoch
boundary's one honest fence is counted).
"""

from __future__ import annotations

import json
import math

import numpy as np
import pytest

from hyperion_tpu.obs.health import (
    ACTIONS,
    HealthConfig,
    HealthMonitor,
    worst,
)
from hyperion_tpu.obs.heartbeat import (
    Heartbeat,
    heartbeat_age_s,
    null_heartbeat,
    read_heartbeat,
)
from hyperion_tpu.obs.trace import Tracer
from hyperion_tpu.utils.clock import VirtualClock


class TestHealthMonitor:
    def test_quiet_run_stays_quiet(self):
        mon = HealthMonitor(HealthConfig(policy="abort"))
        for i in range(100):
            assert mon.observe_step(i, loss=4.0 - i * 0.01, grad_norm=1.0,
                                    step_time_s=0.01) == "none"
        assert mon.anomalies == []

    @pytest.mark.parametrize("bad", [float("nan"), float("inf"),
                                     float("-inf")])
    def test_nonfinite_loss_is_fatal(self, bad):
        mon = HealthMonitor(HealthConfig(policy="abort"))
        assert mon.observe_step(0, loss=4.0) == "none"
        assert mon.observe_step(1, loss=bad) == "abort"
        (anom,) = mon.anomalies
        assert anom.kind == "nonfinite_loss" and anom.fatal

    def test_nonfinite_grad_is_fatal(self):
        mon = HealthMonitor(HealthConfig(policy="abort"))
        assert mon.observe_step(0, grad_norm=float("nan")) == "abort"
        assert mon.anomalies[0].kind == "nonfinite_grad"

    def test_policy_caps_fatal_action(self):
        for policy, expect in [("warn", "warn"), ("checkpoint", "checkpoint"),
                               ("abort", "abort")]:
            mon = HealthMonitor(HealthConfig(policy=policy))
            assert mon.observe_step(0, loss=float("nan")) == expect
        assert HealthMonitor(HealthConfig(policy="off")).observe_step(
            0, loss=float("nan")) == "none"

    def test_loss_spike_z_score(self):
        cfg = HealthConfig(policy="abort", min_window=8)
        mon = HealthMonitor(cfg)
        rng = np.random.default_rng(0)
        for i in range(32):  # noisy but sane window
            assert mon.observe_step(
                i, loss=4.0 + 0.05 * float(rng.standard_normal())) == "none"
        # a 100x jump is a spike; statistical anomalies cap below abort
        action = mon.observe_step(32, loss=400.0)
        assert action == "checkpoint"  # capped: never aborts on a spike
        assert mon.anomalies[-1].kind == "loss_spike"
        assert not mon.anomalies[-1].fatal

    def test_spike_on_flat_window_uses_relative_jump(self):
        mon = HealthMonitor(HealthConfig(policy="warn", min_window=4))
        for i in range(8):
            mon.observe_step(i, loss=1.0)  # zero-variance window
        assert mon.observe_step(8, loss=50.0) == "warn"
        assert mon.anomalies[-1].kind == "loss_spike"

    def test_grad_explosion(self):
        mon = HealthMonitor(HealthConfig(policy="warn", min_window=4))
        for i in range(16):
            assert mon.observe_step(i, grad_norm=1.0 + 0.01 * i) == "none"
        assert mon.observe_step(16, grad_norm=100.0) == "warn"
        assert mon.anomalies[-1].kind == "grad_explosion"

    def test_step_stall_vs_ema(self):
        mon = HealthMonitor(HealthConfig(policy="warn", min_window=4))
        for i in range(16):
            assert mon.observe_step(i, step_time_s=0.01) == "none"
        assert mon.observe_step(16, step_time_s=1.0) == "warn"
        assert mon.anomalies[-1].kind == "step_stall"

    def test_step_stall_caps_at_warn_even_under_checkpoint_policy(self):
        # step time is the one HOST-LOCAL signal (loss/grads are
        # replicated): a stall must never trigger the barrier-fenced
        # checkpoint path, or one host of a multi-host run enters the
        # barrier while its peers keep training
        mon = HealthMonitor(HealthConfig(policy="checkpoint",
                                         min_window=4))
        for i in range(16):
            mon.observe_step(i, step_time_s=0.01)
        assert mon.observe_step(16, step_time_s=1.0) == "warn"

    def test_cofired_fatal_and_stall_expose_the_fatal(self):
        # one step can fire a non-fatal stall AND a fatal NaN together;
        # last_escalated carries the whole batch so a caller gating a
        # checkpoint on "not fatal" cannot be fooled by anomalies[-1]
        mon = HealthMonitor(HealthConfig(policy="checkpoint",
                                         min_window=2))
        for i in range(8):
            mon.observe_step(i, loss=1.0, step_time_s=0.01)
        action = mon.observe_step(8, loss=float("nan"), step_time_s=1.0)
        assert action == "checkpoint"  # fatal capped by the policy
        kinds = {a.kind for a in mon.last_escalated}
        assert kinds == {"nonfinite_loss", "step_stall"}
        assert any(a.fatal for a in mon.last_escalated)

    def test_cooldown_rate_limits_repeats(self):
        mon = HealthMonitor(HealthConfig(policy="warn", cooldown_steps=10))
        assert mon.observe_step(0, loss=float("nan")) == "warn"
        # a NaN-every-step run must not log one event per step
        for i in range(1, 10):
            assert mon.observe_step(i, loss=float("nan")) == "none"
        assert mon.observe_step(10, loss=float("nan")) == "warn"
        assert len(mon.anomalies) == 2

    def test_events_land_in_trace_with_anomaly_field(self, tmp_path):
        t = Tracer(tmp_path / "t.jsonl", run="r", proc=0)
        mon = HealthMonitor(HealthConfig(policy="abort"), tracer=t)
        mon.observe_step(7, loss=float("nan"))
        t.close()
        (rec,) = [json.loads(line)
                  for line in (tmp_path / "t.jsonl").read_text().splitlines()]
        assert rec["kind"] == "event" and rec["name"] == "health"
        # "kind" is a reserved tracer key — the anomaly class must
        # survive under its own field
        assert rec["anomaly"] == "nonfinite_loss"
        assert rec["step"] == 7 and rec["fatal"] is True
        assert rec["action"] == "abort"

    def test_epoch_granularity_check(self):
        mon = HealthMonitor(HealthConfig(policy="abort"))
        assert mon.observe_epoch(1, 100, 4.0) == "none"
        assert mon.observe_epoch(2, 200, float("nan")) == "abort"

    def test_summary_tallies(self):
        mon = HealthMonitor(HealthConfig(policy="warn", cooldown_steps=1))
        mon.observe_step(0, loss=float("nan"))
        mon.observe_step(1, loss=float("nan"))
        s = mon.summary()
        assert s["anomalies"] == {"nonfinite_loss": 2}
        assert s["fatal"] == 2 and s["steps_observed"] == 2

    def test_invalid_policy_rejected(self):
        with pytest.raises(ValueError, match="policy"):
            HealthConfig(policy="explode")

    def test_worst_ordering(self):
        assert worst("none", "warn") == "warn"
        assert worst("abort", "checkpoint") == "abort"
        assert list(ACTIONS) == ["none", "warn", "checkpoint", "abort"]


class TestHeartbeat:
    def make(self, tmp_path, **kw):
        clk, wall = VirtualClock(100.0), VirtualClock(1_000_000.0)
        kw.setdefault("every", 5)
        hb = Heartbeat(tmp_path / "heartbeat.json", run="r1", proc=2,
                       clock=clk, wall=wall, **kw)
        return hb, clk, wall

    def test_pulse_writes_schema(self, tmp_path):
        hb, _, _ = self.make(tmp_path)
        hb.pulse(step=3, phase="train", epoch=1)
        rec = read_heartbeat(tmp_path / "heartbeat.json")
        assert rec["v"] == 1 and rec["run"] == "r1" and rec["proc"] == 2
        assert rec["step"] == 3 and rec["phase"] == "train"
        assert rec["epoch"] == 1 and rec["beats"] == 1
        assert isinstance(rec["pid"], int)
        assert rec["t_wall"] == 1_000_000.0 and rec["t_mono"] == 100.0
        # atomic replace leaves no temp litter
        assert list(tmp_path.iterdir()) == [tmp_path / "heartbeat.json"]

    def test_beat_rate_limited_by_steps(self, tmp_path):
        hb, _, _ = self.make(tmp_path, every=5)
        for i in range(12):
            hb.beat(step=i, phase="train")
        rec = read_heartbeat(tmp_path / "heartbeat.json")
        # writes at steps 0, 5, 10 — not 12 times
        assert rec["step"] == 10 and rec["beats"] == 3

    def test_beat_fires_on_elapsed_time_despite_slow_steps(self, tmp_path):
        hb, clk, _ = self.make(tmp_path, every=1000, interval_s=15.0)
        hb.beat(step=0, phase="train")
        clk.advance(20.0)  # one slow step, far under the step cadence
        hb.beat(step=1, phase="train")
        assert read_heartbeat(tmp_path / "heartbeat.json")["step"] == 1

    def test_beat_fires_on_phase_change(self, tmp_path):
        hb, _, _ = self.make(tmp_path, every=1000)
        hb.beat(step=0, phase="train")
        hb.beat(step=1, phase="eval")
        rec = read_heartbeat(tmp_path / "heartbeat.json")
        assert rec["phase"] == "eval" and rec["beats"] == 2

    def test_close_records_terminal_phase(self, tmp_path):
        hb, _, _ = self.make(tmp_path)
        hb.beat(step=9, phase="train")
        hb.close(phase="done")
        rec = read_heartbeat(tmp_path / "heartbeat.json")
        assert rec["phase"] == "done" and rec["step"] == 9

    def test_null_heartbeat_noops(self, tmp_path):
        hb = null_heartbeat()
        hb.beat(step=0, phase="train")
        hb.pulse(phase="x")
        hb.close()
        assert not hb.enabled

    def test_read_missing_or_corrupt_is_none(self, tmp_path):
        assert read_heartbeat(tmp_path / "nope.json") is None
        (tmp_path / "torn.json").write_text('{"v": 1, "run"')
        assert read_heartbeat(tmp_path / "torn.json") is None

    def test_age_math(self):
        assert heartbeat_age_s({"t_wall": 100.0}, now=160.0) == 60.0
        assert heartbeat_age_s({}, now=160.0) is None

    def test_for_tracer_policy(self, tmp_path, monkeypatch):
        from hyperion_tpu.obs import heartbeat as hb_mod
        from hyperion_tpu.obs.trace import null_tracer

        t = Tracer(tmp_path / "telemetry.jsonl", run="r9", proc=1)
        hb = Heartbeat.for_tracer(t)
        assert hb.enabled and hb.run == "r9" and hb.proc == 1
        assert hb.path == tmp_path / "heartbeat.json"
        assert not Heartbeat.for_tracer(null_tracer()).enabled
        monkeypatch.setenv(hb_mod.ENV_VAR, "0")
        assert not Heartbeat.for_tracer(t).enabled
        monkeypatch.setenv(hb_mod.ENV_VAR, str(tmp_path / "elsewhere.json"))
        hb = Heartbeat.for_tracer(null_tracer())
        assert hb.enabled and hb.path == tmp_path / "elsewhere.json"


def _train_cfg(tmp_path, **over):
    from hyperion_tpu.config import Config

    cfg = Config()
    cfg.train.epochs = 1
    cfg.train.batch_size = 8
    cfg.train.seq_len = 16
    cfg.train.steps_per_epoch = 2
    cfg.train.base_dir = str(tmp_path)
    cfg.train.validate = False
    cfg.train.learning_rate = 1e-2
    for k, v in over.items():
        setattr(cfg.train, k, v)
    return cfg


class TestTrainerIntegration:
    def test_abort_policy_stops_diverged_run(self, tmp_path, mesh_dp):
        from hyperion_tpu.train.trainer import train_language_model

        # lr=1e30 is the divergence injection: step 0 trains, the
        # update overflows the params, step 1's loss is non-finite
        cfg = _train_cfg(tmp_path, learning_rate=1e30,
                         health_policy="abort")
        res = train_language_model(cfg)
        assert res.history == []  # the epoch never completed
        # no export: a poisoned tree must not become *_final.npz
        assert not (tmp_path / "checkpoints"
                    / "language_ddp_final.npz").exists()
        # the health event and the abort trail are in the stream
        recs = [json.loads(line) for line in
                (tmp_path / "telemetry.jsonl").read_text().splitlines()]
        health = [r for r in recs if r.get("name") == "health"]
        assert health and health[0]["anomaly"] == "nonfinite_loss"
        assert health[0]["fatal"] is True
        names = {r.get("name") for r in recs}
        assert "health_abort" in names
        end = [r for r in recs if r.get("name") == "train_end"]
        assert end and end[0]["preempted"] == "health_abort"
        # heartbeat froze in its terminal phase
        hb = read_heartbeat(tmp_path / "heartbeat.json")
        assert hb is not None and hb["phase"] == "aborted"
        # and the doctor reads the post-mortem as divergence
        from hyperion_tpu.obs.doctor import diagnose

        d = diagnose(tmp_path)
        assert d["verdict"] == "diverged"

    def test_healthy_run_zero_added_fences_and_heartbeat(
        self, tmp_path, mesh_dp, monkeypatch
    ):
        import hyperion_tpu.train.trainer as trainer_mod

        calls = {"n": 0}
        real_fence = trainer_mod.host_fence

        def counting_fence(tree):
            calls["n"] += 1
            return real_fence(tree)

        monkeypatch.setattr(trainer_mod, "host_fence", counting_fence)
        cfg = _train_cfg(tmp_path, steps_per_epoch=3)
        res = trainer_mod.train_language_model(cfg)
        assert len(res.history) == 1
        assert math.isfinite(res.final_loss)
        # the ONE honest fence per epoch — heartbeat + health monitor
        # added none (the sync-discipline acceptance bar)
        assert calls["n"] == cfg.train.epochs
        recs = [json.loads(line) for line in
                (tmp_path / "telemetry.jsonl").read_text().splitlines()]
        assert not [r for r in recs if r.get("name") == "health"]
        hb = read_heartbeat(tmp_path / "heartbeat.json")
        assert hb["phase"] == "done" and hb["run"] == res.run_id
        assert hb["beats"] >= 2  # at least first step + terminal pulse
        from hyperion_tpu.obs.doctor import diagnose

        assert diagnose(tmp_path)["verdict"] == "healthy"

    def test_no_telemetry_means_no_heartbeat_file(self, tmp_path, mesh_dp):
        from hyperion_tpu.train.trainer import train_language_model

        cfg = _train_cfg(tmp_path, steps_per_epoch=2, telemetry=False)
        train_language_model(cfg)
        assert not (tmp_path / "heartbeat.json").exists()
        assert not (tmp_path / "telemetry.jsonl").exists()
