"""Model-layer tests: shapes, causality, grads, remat equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.models.resnet import resnet18, resnet50
from hyperion_tpu.models.transformer_lm import (
    TransformerLM,
    gpt2_lm_config,
    simple_lm_config,
)
from hyperion_tpu.ops.attention import causal_mask, dot_product_attention


def small_cfg(**kw):
    base = dict(vocab_size=128, d_model=32, n_heads=4, n_layers=2, ff_dim=64, max_len=16)
    base.update(kw)
    return simple_lm_config(**base)


class TestAttention:
    def test_causal_mask_shape_and_alignment(self):
        m = causal_mask(3, 5)
        assert m.shape == (3, 5)
        # last query row attends to everything; first row to first 3 kv
        assert m[2].all() and m[0, :3].all() and not m[0, 3:].any()

    def test_matches_naive_softmax(self):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(2, 5, 3, 8)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, 5, 3, 8)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, 5, 3, 8)), jnp.float32)
        out = dot_product_attention(q, k, v)
        logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(8)
        w = jax.nn.softmax(jnp.asarray(logits), axis=-1)
        expect = np.einsum("bhqk,bkhd->bqhd", w, v)
        np.testing.assert_allclose(out, expect, atol=1e-5)

    def test_padding_mask_blocks_pad_tokens(self):
        rng = np.random.default_rng(1)
        q, k, v = (jnp.asarray(rng.normal(size=(1, 4, 2, 8)), jnp.float32) for _ in range(3))
        pad = jnp.array([[1, 1, 0, 0]], jnp.int8)
        out = dot_product_attention(q, k, v, padding_mask=pad)
        # changing masked-out kv positions must not change the output
        k2 = k.at[:, 2:].set(99.0)
        v2 = v.at[:, 2:].set(99.0)
        out2 = dot_product_attention(q, k2, v2, padding_mask=pad)
        np.testing.assert_allclose(out, out2, atol=1e-6)


class TestTransformerLM:
    def test_forward_shape_fp32_logits(self):
        cfg = small_cfg()
        model = TransformerLM(cfg)
        params = model.init_params(jax.random.key(0))
        ids = jnp.ones((3, cfg.max_len), jnp.int32)
        logits = model.apply({"params": params}, ids)
        assert logits.shape == (3, cfg.max_len, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causal(self):
        """Future tokens must not affect past logits."""
        cfg = small_cfg(dropout=0.0)
        model = TransformerLM(cfg)
        params = model.init_params(jax.random.key(0))
        ids = jnp.arange(cfg.max_len, dtype=jnp.int32)[None] % cfg.vocab_size
        base = model.apply({"params": params}, ids)
        ids2 = ids.at[0, -1].set((ids[0, -1] + 1) % cfg.vocab_size)
        pert = model.apply({"params": params}, ids2)
        np.testing.assert_allclose(base[0, :-1], pert[0, :-1], atol=1e-5)
        assert not np.allclose(base[0, -1], pert[0, -1])

    def test_remat_matches(self):
        ids = jnp.ones((2, 16), jnp.int32)
        p = TransformerLM(small_cfg()).init_params(jax.random.key(1))
        out = TransformerLM(small_cfg(dropout=0.0)).apply({"params": p}, ids)
        out_r = TransformerLM(small_cfg(dropout=0.0, remat=True)).apply({"params": p}, ids)
        np.testing.assert_allclose(out, out_r, atol=1e-6)

    def test_grads_flow_everywhere(self):
        cfg = small_cfg(dropout=0.0)
        model = TransformerLM(cfg)
        params = model.init_params(jax.random.key(0))
        ids = jnp.ones((2, cfg.max_len), jnp.int32)

        def loss(p):
            return model.apply({"params": p}, ids).mean()

        grads = jax.grad(loss)(params)
        flat = jax.tree.leaves(jax.tree.map(lambda g: float(jnp.abs(g).max()), grads))
        assert all(np.isfinite(flat))
        # >90% of tensors receive gradient (pos_emb rows past T=max_len
        # would be exempt if T < max_len; here T == max_len)
        nonzero = [g > 0 for g in flat]
        assert np.mean(nonzero) > 0.9

    def test_gpt2_preset_dims(self):
        cfg = gpt2_lm_config()
        assert (cfg.d_model, cfg.n_heads, cfg.n_layers, cfg.ff_dim) == (768, 12, 4, 3072)
        assert cfg.activation == "gelu"

    def test_bf16_compute_finite(self):
        cfg = small_cfg(dtype="bfloat16", dropout=0.0)
        model = TransformerLM(cfg)
        params = model.init_params(jax.random.key(0))
        logits = model.apply({"params": params}, jnp.ones((2, 16), jnp.int32))
        assert logits.dtype == jnp.float32 and bool(jnp.isfinite(logits).all())


class TestResNet:
    @pytest.mark.slow
    def test_resnet18_cifar(self):
        model = resnet18(num_classes=10)
        variables = model.init_variables(jax.random.key(0))
        imgs = jnp.ones((2, 32, 32, 3), jnp.float32)
        logits, updates = model.apply(
            variables, imgs, train=True, mutable=["batch_stats"]
        )
        assert logits.shape == (2, 10)
        assert "batch_stats" in updates

    def test_resnet18_eval_deterministic(self):
        model = resnet18()
        variables = model.init_variables(jax.random.key(0))
        imgs = jnp.ones((2, 32, 32, 3), jnp.float32)
        a = model.apply(variables, imgs, train=False)
        b = model.apply(variables, imgs, train=False)
        np.testing.assert_array_equal(a, b)

    @pytest.mark.slow
    def test_resnet50_imagenet_shape(self):
        model = resnet50(num_classes=1000)
        variables = model.init_variables(jax.random.key(0), image_size=64)
        imgs = jnp.ones((1, 64, 64, 3), jnp.float32)
        logits = model.apply(variables, imgs, train=False)
        assert logits.shape == (1, 1000)

    def test_param_counts_resnet18(self):
        """torchvision resnet18 ≈ 11.7M params (ImageNet head 1000).
        Ours with CIFAR stem + 10 classes should be ~11.2M."""
        model = resnet18(num_classes=10)
        variables = model.init_variables(jax.random.key(0))
        n = sum(x.size for x in jax.tree.leaves(variables["params"]))
        assert 10.5e6 < n < 12e6, n
