"""obs doctor / obs diff + the telemetry record contract.

The golden fixture streams under tests/data/telemetry/ (regenerable via
gen_fixtures.py there) are the compatibility anchor: the schema test
pins every span/event/snapshot/heartbeat field that `doctor`, `diff`,
and `summarize` read, so a producer-side refactor that would silently
break offline tooling fails HERE, in tier-1, not in a post-mortem.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from hyperion_tpu.obs import diff as obs_diff
from hyperion_tpu.obs import doctor, report
from hyperion_tpu.obs.heartbeat import read_heartbeat
from hyperion_tpu.obs.registry import MetricsRegistry
from hyperion_tpu.obs.trace import Tracer
from hyperion_tpu.utils.clock import VirtualClock

FIXTURES = Path(__file__).parent / "data" / "telemetry"
REPO = Path(__file__).resolve().parents[1]

ALL_FIXTURES = ("healthy", "nan", "stalled", "hung", "crashed", "serve",
                "slo")
# the fleet fixture is three streams in one layout (router + two
# replicas); each joins the record contract individually — the fleet
# join (tests/test_fleet_trace.py) only works if every constituent
# stream honors the same envelope the single-process tools read
FLEET_FIXTURES = ("fleet", "fleet/replica_0", "fleet/replica_1")
# the flight-simulator fixture (serve/simulate.py) is events+snapshots
# only — like the fleet router stream it has no tick spans, so it joins
# the envelope and heartbeat contracts but not the span contract
SIM_FIXTURES = ("sim",)


def write_run(path, run: str, step_ms: float, *, steps: int = 8,
              tokens_per_s: float = 4096.0, wall0: float = 1_000.0,
              terminal: bool = True):
    """One synthetic healthy-shaped run appended to `path`."""
    clk, wall = VirtualClock(100.0), VirtualClock(wall0)
    t = Tracer(path, run=run, proc=0, clock=clk, wall=wall)
    t.event("train_start", job="language_ddp")
    with t.span("epoch", step=0) as ep:
        for i in range(steps):
            with t.span("train_step", step=i):
                clk.advance(step_ms / 1e3)
                wall.advance(step_ms / 1e3)
        ep.set(epoch=1, steps=steps)
    reg = MetricsRegistry()
    reg.gauge("tokens_per_s").set(tokens_per_s)
    reg.gauge("mfu").set(0.3)
    reg.gauge("hbm_peak_mb").set(512.0)
    t.snapshot(reg, step=steps)
    if terminal:
        t.event("train_end", preempted=False)
    t.close()


def write_input_wait_run(path, run: str, frac: float, wait_s: float = 8.0):
    """A finished run whose last snapshot carries the input-wait gauges
    (`observe_input_wait`) — the evidence `doctor` reads for the
    input-bound call."""
    clk, wall = VirtualClock(100.0), VirtualClock(1_000.0)
    t = Tracer(path, run=run, proc=0, clock=clk, wall=wall)
    t.event("train_start", job="language_ddp")
    reg = MetricsRegistry()
    reg.gauge("input_wait_s").set(wait_s)
    reg.gauge("input_wait_frac").set(frac)
    t.snapshot(reg, step=8)
    t.event("train_end", preempted=False)
    t.close()


# --------------------------------------------------------------- doctor


class TestDoctorFixtures:
    """The tier-1 smoke required by the issue: `hyperion_tpu obs doctor`
    over every committed fixture stream, through the real CLI."""

    @pytest.mark.parametrize("name,verdict,rc", [
        ("healthy", "healthy", 0),
        ("nan", "diverged", 1),
        ("stalled", "stalled", 1),
        ("hung", "hung", 1),
        ("crashed", "crashed", 1),
    ])
    def test_cli_classifies_fixture(self, name, verdict, rc, capsys):
        from hyperion_tpu.cli.main import main as cli_main

        args = ["obs", "doctor", str(FIXTURES / name)]
        if name == "stalled":
            # "stalled" means alive-and-degrading: judge it from a
            # vantage point where the committed heartbeat is fresh
            # (staleness outranks the stall pattern — see the hung
            # cross-check below)
            hb = read_heartbeat(FIXTURES / name / "heartbeat.json")
            args += ["--now", str(hb["t_wall"] + 30)]
        code = cli_main(args)
        out = capsys.readouterr().out
        assert f"verdict: {verdict}" in out, out
        assert code == rc

    def test_stalled_then_dead_is_hung(self):
        # the SAME degraded stream, judged long after the last beat:
        # the process is gone, so staleness wins — with the stall
        # history kept as evidence in the reason
        d = doctor.diagnose(FIXTURES / "stalled")  # real now: very stale
        assert d["verdict"] == "hung"
        assert "degraded" in d["reason"]
        assert d["stall"] is not None

    def test_nan_fixture_evidence(self):
        d = doctor.diagnose(FIXTURES / "nan")
        assert d["verdict"] == "diverged"
        assert any(h["anomaly"] == "nonfinite_loss"
                   for h in d["health_events"])
        assert d["heartbeat"]["phase"] == "aborted"

    def test_stalled_fixture_evidence(self):
        hb = read_heartbeat(FIXTURES / "stalled" / "heartbeat.json")
        d = doctor.diagnose(FIXTURES / "stalled", now=hb["t_wall"] + 30)
        assert d["verdict"] == "stalled"
        assert d["stall"]["ratio"] >= doctor.STALL_RATIO
        assert d["heartbeat"]["phase"] == "train"

    def test_crashed_fixture_evidence(self):
        d = doctor.diagnose(FIXTURES / "crashed")
        assert d["verdict"] == "crashed"
        assert d["truncated_tail"] is True and d["bad_lines"] == 1

    def test_hung_fixture_goes_running_when_fresh(self):
        # the SAME stream classifies as running when "now" is close to
        # its timestamps — hung is purely a staleness verdict
        hb = read_heartbeat(FIXTURES / "hung" / "heartbeat.json")
        d = doctor.diagnose(FIXTURES / "hung", now=hb["t_wall"] + 10)
        assert d["verdict"] == "running"
        d = doctor.diagnose(FIXTURES / "hung", now=hb["t_wall"] + 10_000)
        assert d["verdict"] == "hung"

    def test_healthy_fixture_summary_fields(self):
        d = doctor.diagnose(FIXTURES / "healthy")
        assert d["verdict"] == "healthy"
        assert d["steps"] == 8 and d["hbm_peak_mb"] == 900.0
        assert d["heartbeat"]["phase"] == "done"

    def test_missing_target_exits_2(self, tmp_path, capsys):
        assert doctor.main([str(tmp_path / "nope")]) == 2
        assert "no telemetry stream" in capsys.readouterr().err

    def test_empty_stream_is_empty_verdict(self, tmp_path, capsys):
        (tmp_path / "telemetry.jsonl").write_text("")
        assert doctor.main([str(tmp_path)]) == 2
        assert "empty" in capsys.readouterr().out

    def test_json_output(self, capsys):
        assert doctor.main([str(FIXTURES / "healthy"), "--json"]) == 0
        assert json.loads(capsys.readouterr().out)["verdict"] == "healthy"

    def test_report_entry_point_dispatches_doctor(self, monkeypatch,
                                                  capsys):
        # `python -m hyperion_tpu.obs.report doctor <dir>` — main(None)
        # must resolve sys.argv BEFORE the doctor/diff dispatch
        import sys as _sys

        monkeypatch.setattr(_sys, "argv",
                            ["report", "doctor", str(FIXTURES / "healthy")])
        assert report.main() == 0
        assert "verdict: healthy" in capsys.readouterr().out

    def test_failed_publish_is_not_healthy(self, tmp_path):
        # bench.py's dead-tunnel run completes its lifecycle but
        # publishes value 0.0 with failed=true — the motivating silent
        # failure must not classify healthy
        t = Tracer(tmp_path / "telemetry.jsonl", run="bench_x", proc=0)
        t.event("bench_start", metric="matmul")
        t.event("publish", value=0.0, failed=True, error="tunnel dead")
        t.close()
        d = doctor.diagnose(tmp_path)
        assert d["verdict"] == "failed"
        assert "tunnel dead" in d["reason"]
        assert doctor.EXIT_BY_VERDICT["failed"] == 1

    def test_successful_publish_stays_healthy(self, tmp_path):
        t = Tracer(tmp_path / "telemetry.jsonl", run="bench_y", proc=0)
        t.event("bench_start", metric="matmul")
        t.event("publish", value=175.75, plausible=True, vs_baseline=1.45)
        t.close()
        assert doctor.diagnose(tmp_path)["verdict"] == "healthy"

    def test_foreign_heartbeat_is_ignored(self, tmp_path):
        # heartbeat from a DIFFERENT run id must not vouch for this one
        write_run(tmp_path / "telemetry.jsonl", "r_old", 10.0,
                  terminal=False)
        (tmp_path / "heartbeat.json").write_text(json.dumps(
            {"v": 1, "run": "r_new", "t_wall": 2_000.0, "phase": "train"}
        ))
        d = doctor.diagnose(tmp_path, run="r_old", now=5_000.0)
        assert d["heartbeat"] is None
        assert d["verdict"] == "hung"  # stream stale, no heartbeat for it


class TestInputBound:
    """`obs doctor` calls a run input-bound when the input_wait_frac
    gauge says the step loop mostly waited on the input queue — an
    orthogonal note on the liveness verdict, not a verdict itself."""

    def test_flags_input_bound_run(self, tmp_path):
        write_input_wait_run(tmp_path / "telemetry.jsonl", "r1", frac=0.8)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["verdict"] == "healthy"  # alive AND starved can coexist
        assert d["input_bound"] is True
        assert d["input_wait_frac"] == 0.8
        assert "input-bound" in d["reason"]
        assert "input wait" in doctor.render_markdown(d)
        assert "**input-bound**" in doctor.render_markdown(d)

    def test_well_fed_run_stays_quiet(self, tmp_path):
        write_input_wait_run(tmp_path / "telemetry.jsonl", "r1", frac=0.04)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["input_bound"] is False
        assert "input-bound" not in d["reason"]
        # the evidence row still renders, unflagged
        assert "input wait" in doctor.render_markdown(d)

    def test_no_gauge_means_no_claim(self, tmp_path):
        write_run(tmp_path / "telemetry.jsonl", "r1", 10.0)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["input_bound"] is False
        assert d["input_wait_frac"] is None
        assert "input wait" not in doctor.render_markdown(d)


def write_spec_serve_run(path, run: str, drafted: int, accepted: int,
                         tokens_per_tick: float = 1.4):
    """A finished serve-shaped run whose last snapshot carries the
    speculative-decoding counters/gauges (serve/metrics.py `on_spec`)."""
    clk, wall = VirtualClock(100.0), VirtualClock(1_000.0)
    t = Tracer(path, run=run, proc=0, clock=clk, wall=wall)
    t.event("serve_start")
    reg = MetricsRegistry()
    reg.counter("serve_ticks").inc(50)
    reg.counter("serve_completed").inc(4)
    reg.counter("serve_spec_drafted").inc(drafted)
    reg.counter("serve_spec_accepted").inc(accepted)
    reg.counter("serve_spec_rejected").inc(drafted - accepted)
    if drafted:
        reg.gauge("serve_spec_accept_rate").set(accepted / drafted)
    reg.gauge("serve_tokens_per_tick").set(tokens_per_tick)
    reg.gauge("queue_depth").set(0.0)
    t.snapshot(reg, step=50)
    t.event("serve_end")
    t.close()


class TestSpeculationIncident:
    """`obs doctor` on a spec-enabled serve run: the accept rate is an
    incident below SPEC_ACCEPT_FLOOR (the k+1-wide verify forward is
    then mostly wasted), with the knobs to turn named in the reason."""

    def test_low_acceptance_is_named(self, tmp_path):
        write_spec_serve_run(tmp_path / "telemetry.jsonl", "r1",
                             drafted=400, accepted=60,
                             tokens_per_tick=1.05)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["verdict"] == "healthy"
        assert d["serve"]["spec_drafted"] == 400
        assert d["spec_incidents"], "low acceptance produced no incident"
        assert ("draft mispredicting — lower --spec-k or disable "
                "--draft") in d["reason"]
        md = doctor.render_markdown(d)
        assert "serve speculation" in md
        assert "**low acceptance**" in md

    def test_healthy_acceptance_stays_quiet(self, tmp_path):
        write_spec_serve_run(tmp_path / "telemetry.jsonl", "r1",
                             drafted=400, accepted=240)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["spec_incidents"] == []
        assert "mispredicting" not in d["reason"]
        # the evidence row still renders, unflagged
        md = doctor.render_markdown(d)
        assert "serve speculation" in md
        assert "low acceptance" not in md

    def test_spec_off_run_has_no_row(self, tmp_path):
        write_spec_serve_run(tmp_path / "telemetry.jsonl", "r1",
                             drafted=0, accepted=0)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["spec_incidents"] == []
        assert "serve speculation" not in doctor.render_markdown(d)


def write_tiered_serve_run(path, run: str, *, host_cache_mb,
                           evicted=0, spilled=0, host_hits=0,
                           tier_miss=0, restores=0, saved_chains=None):
    """A finished serve-shaped run with the tiered-KV evidence trail:
    `serve_start` declares the tier budget, the snapshot carries the
    tier counters (serve/metrics.py), and `host_restore` /
    `hostcache_saved` events say the tier actually moved bytes."""
    clk, wall = VirtualClock(100.0), VirtualClock(1_000.0)
    t = Tracer(path, run=run, proc=0, clock=clk, wall=wall)
    t.event("serve_start", host_cache_mb=host_cache_mb)
    for i in range(restores):
        t.event("host_restore", request=f"q{i}", tick=i, blocks=2,
                tokens=16, bytes=4096)
    reg = MetricsRegistry()
    reg.counter("serve_ticks").inc(50)
    reg.counter("serve_completed").inc(4)
    reg.counter("serve_blocks_evicted").inc(evicted)
    reg.counter("serve_host_spilled_blocks").inc(spilled)
    reg.counter("serve_host_restored_blocks").inc(2 * restores)
    reg.counter("serve_tier_hits_host").inc(host_hits)
    reg.counter("serve_tier_hits_device").inc(1)
    reg.counter("serve_tier_miss").inc(tier_miss)
    reg.gauge("queue_depth").set(0.0)
    t.snapshot(reg, step=50)
    if saved_chains is not None:
        t.event("hostcache_saved", chains=saved_chains, mb=0.5,
                path=str(path.parent / "hostcache"))
    t.event("serve_end")
    t.close()


class TestTieredKVIncidents:
    """`obs doctor` on the host-spill tier: evictions with the tier OFF
    and spills the workload never came back for are DIFFERENT named
    incidents with different knobs — and a tier that fed re-hits is
    evidence, not a complaint."""

    def test_evictions_with_tier_disabled_are_named(self, tmp_path):
        write_tiered_serve_run(tmp_path / "telemetry.jsonl", "r1",
                               host_cache_mb=0, evicted=7, tier_miss=3)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["verdict"] == "healthy"
        assert d["tier_incidents"], "disabled tier produced no incident"
        assert "host tier DISABLED" in d["reason"]
        assert "--host-cache-mb" in d["reason"]
        md = doctor.render_markdown(d)
        assert "serve cache tiers" in md
        assert "**tier incident**" in md

    def test_spills_without_rehits_is_undersized(self, tmp_path):
        write_tiered_serve_run(tmp_path / "telemetry.jsonl", "r1",
                               host_cache_mb=4, evicted=7, spilled=7,
                               host_hits=0, tier_miss=5)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["tier_incidents"]
        assert "--host-cache-mb likely undersized" in d["reason"]
        assert d["host_tier"]["budget_mb"] == 4

    def test_tier_feeding_rehits_stays_quiet(self, tmp_path):
        write_tiered_serve_run(tmp_path / "telemetry.jsonl", "r1",
                               host_cache_mb=64, evicted=7, spilled=7,
                               host_hits=3, tier_miss=5, restores=3,
                               saved_chains=5)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["tier_incidents"] == []
        assert "cache tier" not in d["reason"]
        # the evidence row still renders, unflagged, with the
        # drain-time save cited
        assert d["host_tier"]["restore_events"] == 3
        assert d["host_tier"]["saved"] == {"chains": 5, "mb": 0.5}
        md = doctor.render_markdown(d)
        assert "serve cache tiers" in md
        assert "**tier incident**" not in md

    def test_tierless_run_has_no_row(self, tmp_path):
        write_spec_serve_run(tmp_path / "telemetry.jsonl", "r1",
                             drafted=0, accepted=0)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["tier_incidents"] == []
        assert d["host_tier"] is None
        assert "serve cache tiers" not in doctor.render_markdown(d)


class TestTenantAttributionAndRouterActions:
    """PR 14: when adversarial tenants drive the pressure, the doctor
    NAMES the offending tenant from the admit/shed event trail; and
    the acting router's telemetry (router_steer / class_brownout /
    router_scale) rolls up into one narrated line."""

    def _run(self, tmp_path, events):
        clk, wall = VirtualClock(100.0), VirtualClock(1_000.0)
        t = Tracer(tmp_path / "telemetry.jsonl", run="r1", proc=0,
                   clock=clk, wall=wall)
        t.event("serve_start")
        for name, kw in events:
            clk.advance(0.1)
            wall.advance(0.1)
            t.event(name, **kw)
        t.event("serve_end")
        t.close()
        return doctor.diagnose(tmp_path, now=1_100.0)

    def test_offending_tenant_is_named(self, tmp_path):
        d = self._run(tmp_path, [
            ("request_admitted", {"request": "a0", "tenant": "adv_burst",
                                  "sla_class": "batch"}),
            ("request_admitted", {"request": "a1", "tenant": "adv_burst",
                                  "sla_class": "batch"}),
            ("request_rejected", {"request": "a2", "tenant": "adv_burst",
                                  "sla_class": "batch", "shed": True,
                                  "reason": "shed_deadline"}),
            ("request_admitted", {"request": "u0", "tenant": "alice",
                                  "sla_class": "interactive"}),
        ])
        assert d["tenants"][0]["tenant"] == "adv_burst"
        assert d["tenants"][0]["shed"] == 1
        assert any("adv_burst" in s for s in d["tenant_incidents"])
        assert "adv_burst" in d["reason"]
        md = doctor.render_markdown(d)
        assert "`adv_burst`" in md and "**offender**" in md
        # the civilian tenant renders unflagged
        assert "`alice`" in md
        assert md.count("**offender**") == 1

    def test_untagged_run_makes_no_tenant_claim(self, tmp_path):
        d = self._run(tmp_path, [
            ("request_admitted", {"request": "a0",
                                  "sla_class": "interactive"}),
        ])
        assert d["tenants"] == [] and d["tenant_incidents"] == []
        assert "tenant" not in d["reason"]

    def test_router_actions_are_narrated(self, tmp_path):
        d = self._run(tmp_path, [
            ("router_steer", {"replica": 1, "on": True,
                              "alerts": ["ttft_p99"]}),
            ("class_brownout", {"replica": 1, "active": True,
                                "acked": True}),
            ("router_scale", {"direction": "up", "replica": 2,
                              "fleet": 3}),
            ("router_steer", {"replica": 1, "on": False}),
            ("class_brownout", {"replica": 1, "active": False,
                                "acked": True}),
            ("router_scale", {"direction": "down", "replica": 2,
                              "fleet": 2}),
        ])
        acts = d["router_actions"]
        assert len(acts) == 3
        assert any("replica(s) 1" in a and "all reversed" in a
                   for a in acts)
        assert any("brownout ordered 1x, lifted 1x" in a for a in acts)
        assert any("1 standby spawn(s), 1 retire(s)" in a for a in acts)
        assert "router actions:" in d["reason"]
        assert "router action" in doctor.render_markdown(d)

    def test_unreversed_steer_is_called_out(self, tmp_path):
        d = self._run(tmp_path, [
            ("router_steer", {"replica": 0, "on": True,
                              "alerts": ["ttft_p99"]}),
        ])
        assert any("still steered at the end" in a
                   for a in d["router_actions"])


class TestRouterWalPostMortem:
    """PR 15: a dead router life leaves its dispatch WAL next to the
    telemetry stream. Pending entries with no `router_end` event are the
    streams it still owes clients — the doctor must cite the WAL tail as
    evidence, read-only (recovery belongs to the next router life)."""

    def _tele(self, tmp_path, *, ended: bool):
        clk, wall = VirtualClock(100.0), VirtualClock(1_000.0)
        t = Tracer(tmp_path / "telemetry.jsonl", run="r1", proc=0,
                   clock=clk, wall=wall)
        t.event("router_start", replicas=2)
        t.event("route_dispatch", request="q1", replica=1)
        if ended:
            t.event("router_end")
        t.close()

    def _wal(self, tmp_path, *, settle: bool):
        from hyperion_tpu.serve.router_journal import RouterJournal

        j = RouterJournal(tmp_path / "router_journal.jsonl")
        j.dispatch("q1", line='{"id": "q1", "prompt_ids": [7]}',
                   replica=1, session="s1")
        j.hwm("q1", 3)
        if settle:
            j.done("q1", "completed")
        j.close()

    def test_orphaned_wal_becomes_the_incident(self, tmp_path):
        self._tele(tmp_path, ended=False)
        self._wal(tmp_path, settle=False)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        wal = d["router_wal"]
        assert wal["pending"] == 1
        assert "router_journal.jsonl" in wal["incident"]
        assert "in-flight" in wal["incident"]
        # the tail is the evidence: placement and high-water mark cited
        assert "q1" in wal["incident"] and "i=3" in wal["incident"]
        assert "router WAL" in d["reason"]
        md = doctor.render_markdown(d)
        assert "router WAL" in md and "owed streams" in md

    def test_clean_router_end_makes_no_claim(self, tmp_path):
        self._tele(tmp_path, ended=True)
        self._wal(tmp_path, settle=False)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["router_wal"] is not None
        assert "incident" not in d["router_wal"]
        assert "router WAL" not in d["reason"]

    def test_settled_wal_makes_no_claim(self, tmp_path):
        self._tele(tmp_path, ended=False)
        self._wal(tmp_path, settle=True)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["router_wal"]["pending"] == 0
        assert "incident" not in d["router_wal"]

    def test_no_wal_file_means_no_row(self, tmp_path):
        self._tele(tmp_path, ended=False)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["router_wal"] is None
        assert "router WAL" not in doctor.render_markdown(d)


def write_rss_run(path, run: str, series):
    """A finished serve-shaped run whose snapshots carry the host RSS
    gauge as a SERIES — the evidence `doctor` reads for the host-leak
    trend."""
    clk, wall = VirtualClock(100.0), VirtualClock(1_000.0)
    t = Tracer(path, run=run, proc=0, clock=clk, wall=wall)
    t.event("serve_start")
    for i, mb in enumerate(series):
        reg = MetricsRegistry()
        reg.counter("serve_ticks").inc(10 * (i + 1))
        reg.gauge("queue_depth").set(0.0)
        reg.gauge("host_rss_mb").set(mb)
        t.snapshot(reg, step=10 * (i + 1))
        clk.advance(1.0)
        wall.advance(1.0)
    t.event("serve_end")
    t.close()


class TestRssTrend:
    """`obs doctor` on the host-memory ledger: `ru_maxrss` is a
    high-water mark, so the leak signal is a peak STILL RISING at the
    newest snapshots after a material climb — plateaued-after-warmup
    (the normal shape) must stay quiet."""

    def test_monotonic_climb_is_warned(self, tmp_path):
        write_rss_run(tmp_path / "telemetry.jsonl", "r1",
                      [400.0, 440.0, 480.0, 520.0])
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["verdict"] == "healthy"
        assert d["rss_trend"] == {"first_mb": 400.0, "last_mb": 520.0,
                                  "samples": 4}
        assert d["rss_warning"] is not None
        assert "host RSS climbing monotonically" in d["reason"]
        md = doctor.render_markdown(d)
        assert "host RSS" in md and "**climbing**" in md

    def test_plateaued_rss_stays_quiet(self, tmp_path):
        # material climb, but the peak froze over the last snapshots:
        # warmup growth, not a leak
        write_rss_run(tmp_path / "telemetry.jsonl", "r1",
                      [400.0, 520.0, 520.0, 520.0])
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["rss_warning"] is None
        assert "climbing" not in d["reason"]
        # the evidence row still renders, unflagged
        md = doctor.render_markdown(d)
        assert "host RSS" in md and "**climbing**" not in md

    def test_short_series_makes_no_claim(self, tmp_path):
        # two points cannot distinguish warmup from leak
        write_rss_run(tmp_path / "telemetry.jsonl", "r1", [400.0, 900.0])
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["rss_trend"]["samples"] == 2
        assert d["rss_warning"] is None

    def test_no_gauge_means_no_row(self, tmp_path):
        write_run(tmp_path / "telemetry.jsonl", "r1", 10.0)
        d = doctor.diagnose(tmp_path, now=1_100.0)
        assert d["rss_trend"] is None
        assert "host RSS" not in doctor.render_markdown(d)

    def test_live_heartbeat_pulse_carries_rss(self, tmp_path):
        """Satellite contract: every beat carries the process RSS (via
        getrusage — no new deps), and the tolerant reader passes it
        through untouched."""
        from hyperion_tpu.obs.heartbeat import Heartbeat, host_rss_mb

        hb = Heartbeat(tmp_path / "heartbeat.json", run="r1", every=1)
        hb.pulse(step=1, phase="serve")
        back = read_heartbeat(tmp_path / "heartbeat.json")
        assert isinstance(back["rss_mb"], (int, float))
        assert back["rss_mb"] > 0
        assert host_rss_mb() > 0


# -------------------------------------------------- telemetry contract


class TestRecordContract:
    """Pin the wire fields the offline tools rely on. A change that
    breaks these breaks `obs doctor`/`diff`/`summarize` on every stream
    already on disk — bump trace.SCHEMA_VERSION and migrate instead."""

    RESERVED = ("v", "kind", "name", "run", "proc", "step", "t_wall",
                "t_mono")

    def records(self, name):
        out = []
        for line in (FIXTURES / name / "telemetry.jsonl").read_text() \
                .splitlines():
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # the crashed fixture's torn tail, by design
        assert out, f"fixture {name} unreadable"
        return out

    @pytest.mark.parametrize(
        "name", ALL_FIXTURES + FLEET_FIXTURES + SIM_FIXTURES)
    def test_every_record_carries_envelope(self, name):
        for r in self.records(name):
            assert r["v"] == 1
            assert r["kind"] in ("span", "event", "snapshot")
            assert isinstance(r["name"], str)
            assert isinstance(r["run"], str)
            assert isinstance(r["proc"], int)
            assert isinstance(r["t_wall"], (int, float))
            assert isinstance(r["t_mono"], (int, float))
            assert r["step"] is None or isinstance(r["step"], int)

    # the fleet ROUTER stream is events-only (relays are threads, not
    # ticks) — only its replica streams join the span contract
    @pytest.mark.parametrize("name", ALL_FIXTURES + FLEET_FIXTURES[1:])
    def test_span_records(self, name):
        spans = [r for r in self.records(name) if r["kind"] == "span"]
        assert spans
        for s in spans:
            assert isinstance(s["dur_ms"], (int, float))
            assert isinstance(s["path"], str) and s["path"].endswith(s["name"])

    def test_snapshot_record_shape(self):
        (snap,) = [r for r in self.records("healthy")
                   if r["kind"] == "snapshot"]
        m = snap["metrics"]
        assert set(m) == {"counters", "gauges", "histograms", "labels"}
        # the gauges summarize/doctor/diff read
        for g in ("tokens_per_s", "mfu", "hbm_peak_mb"):
            assert g in m["gauges"]
        assert "step_time_ms" in m["histograms"]

    def test_health_event_shape(self):
        (ev,) = [r for r in self.records("nan") if r["name"] == "health"]
        assert ev["kind"] == "event"
        assert ev["anomaly"] in ("nonfinite_loss", "nonfinite_grad",
                                 "loss_spike", "grad_explosion",
                                 "step_stall")
        assert ev["fatal"] is True
        assert ev["action"] in ("warn", "checkpoint", "abort")

    @pytest.mark.parametrize(
        "name", ALL_FIXTURES + FLEET_FIXTURES + SIM_FIXTURES)
    def test_heartbeat_contract(self, name):
        hb = read_heartbeat(FIXTURES / name / "heartbeat.json")
        assert hb is not None
        for field, typ in (("v", int), ("schema", int), ("run", str),
                           ("pid", int),
                           ("proc", int), ("step", int), ("phase", str),
                           ("t_wall", (int, float)),
                           ("t_mono", (int, float)), ("beats", int)):
            assert isinstance(hb[field], typ), (name, field)

    @pytest.mark.parametrize(
        "name", ALL_FIXTURES + FLEET_FIXTURES + SIM_FIXTURES)
    def test_heartbeat_reader_tolerates_unknown_fields(self, name, tmp_path):
        """Live-plane payload growth (alerts, occupancy, whatever comes
        next) must never break an older reader: read_heartbeat returns
        the whole dict, no field whitelist, and the age helper keeps
        working with strangers in the record."""
        import time as _time

        from hyperion_tpu.obs.heartbeat import heartbeat_age_s

        hb = read_heartbeat(FIXTURES / name / "heartbeat.json")
        grown = {**hb, "alerts": ["ttft_p99"], "from_the_future": {"x": 1}}
        p = tmp_path / "heartbeat.json"
        p.write_text(json.dumps(grown))
        back = read_heartbeat(p)
        assert back["from_the_future"] == {"x": 1}
        assert back["phase"] == hb["phase"]
        assert heartbeat_age_s(back, now=_time.time()) is not None

    @pytest.mark.parametrize("name", ALL_FIXTURES)
    def test_summarize_reads_every_fixture(self, name):
        s = report.summarize(FIXTURES / name / "telemetry.jsonl")
        assert not s.get("error")
        assert s["steps"] >= 5


# ----------------------------------------------------------------- diff


class TestDiff:
    def test_injected_step_time_regression_flagged(self, tmp_path, capsys):
        """The acceptance bar: a >=10%% injected step-time regression
        between two synthetic runs flips the exit code."""
        from hyperion_tpu.cli.main import main as cli_main

        a, b = tmp_path / "a", tmp_path / "b"
        a.mkdir(), b.mkdir()
        write_run(a / "telemetry.jsonl", "run_a", 10.0)
        write_run(b / "telemetry.jsonl", "run_b", 12.0)  # +20% step time
        rc = cli_main(["obs", "diff", str(a), str(b)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSED" in out and "step_time_p50_ms" in out

    def test_within_threshold_passes(self, tmp_path, capsys):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        write_run(a, "run_a", 10.0)
        write_run(b, "run_b", 10.5)  # +5% < default 10%
        assert obs_diff.main([str(a), str(b)]) == 0
        assert "no regressions" in capsys.readouterr().out

    def test_throughput_direction_is_inverted(self, tmp_path):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        write_run(a, "run_a", 10.0, tokens_per_s=4000.0)
        write_run(b, "run_b", 10.0, tokens_per_s=3000.0)  # -25% tok/s
        d = obs_diff.diff(obs_diff.load_summary(a),
                          obs_diff.load_summary(b))
        assert "tokens_per_s" in d["regressions"]
        # and an IMPROVEMENT the other way is not a regression
        d = obs_diff.diff(obs_diff.load_summary(b),
                          obs_diff.load_summary(a))
        assert "tokens_per_s" not in d["regressions"]

    def test_threshold_is_configurable(self, tmp_path):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        write_run(a, "run_a", 10.0)
        write_run(b, "run_b", 10.5)
        assert obs_diff.main([str(a), str(b), "--threshold", "0.01"]) == 1

    def test_normalize_bench_line(self):
        m = obs_diff.normalize({
            "metric": "matmul_bf16_8192_tflops", "value": 175.75,
            "vs_baseline": 1.452,
            "extra": {"lm_step_ms": 61.9, "lm_tokens_per_s": 66150.0},
        })
        assert m["headline_tflops"] == 175.75
        assert m["vs_baseline"] == 1.452
        assert m["lm_step_ms"] == 61.9

    def test_normalize_input_pipeline_probe(self):
        """bench.py's input_pipeline row rides the standard bench shape,
        so `obs diff --history` tracks it across BENCH_r*.json."""
        m = obs_diff.normalize({
            "metric": "matmul_bf16_8192_tflops", "value": 100.0,
            "input_pipeline": {"sync_batches_per_s": 376.6,
                               "prefetch_batches_per_s": 434.2,
                               "speedup": 1.15},
        })
        assert m["input_sync_batches_per_s"] == 376.6
        assert m["input_prefetch_batches_per_s"] == 434.2
        assert obs_diff.METRICS["input_prefetch_batches_per_s"] == "higher"

    def test_normalize_round_wrapper_and_trainer_summary(self):
        m = obs_diff.normalize({"rc": 0, "parsed": {
            "metric": "x", "value": 120.0, "vs_baseline": 1.0}})
        assert m["headline_tflops"] == 120.0
        m = obs_diff.normalize({"step_ms": 42.0, "tokens_per_s": 1000.0,
                                "peak_hbm_mb": 13580.0})
        assert m["step_time_mean_ms"] == 42.0
        assert m["hbm_peak_mb"] == 13580.0

    def test_normalize_drops_nonfinite_and_unknown(self):
        assert obs_diff.normalize({"tokens_per_s": float("nan"),
                                   "unknown_key": 3}) == {}

    def test_history_over_committed_bench_records(self, capsys):
        rc = obs_diff.main(["--history", str(REPO / "BENCH_r0*.json")])
        out = capsys.readouterr().out
        assert rc == 0
        for n in range(1, 6):
            assert f"BENCH_r0{n}.json" in out
        assert "headline_tflops" in out

    def test_history_no_match_exits_2(self, tmp_path, capsys):
        assert obs_diff.main(["--history",
                              str(tmp_path / "none_*.json")]) == 2
        assert "matched no files" in capsys.readouterr().err

    def test_unreadable_input_exits_2(self, tmp_path, capsys):
        good = tmp_path / "a.jsonl"
        write_run(good, "r", 10.0)
        assert obs_diff.main([str(good),
                              str(tmp_path / "missing.json")]) == 2


# ------------------------------------------- summarize failure satellite


class TestSummarizeEmptyStreams:
    def test_empty_file_one_line_nonzero(self, tmp_path, capsys):
        p = tmp_path / "telemetry.jsonl"
        p.write_text("")
        assert report.main(["summarize", str(p)]) == 1
        cap = capsys.readouterr()
        assert cap.out == ""
        assert len(cap.err.strip().splitlines()) == 1
        assert "no parseable records" in cap.err

    def test_garbage_only_file_nonzero(self, tmp_path, capsys):
        p = tmp_path / "telemetry.jsonl"
        p.write_text("not json\n{{{\n")
        assert report.main(["summarize", str(p)]) == 1
        assert "no parseable records" in capsys.readouterr().err

    def test_filtered_to_empty_run_nonzero(self, tmp_path, capsys):
        p = tmp_path / "telemetry.jsonl"
        write_run(p, "real_run", 10.0)
        assert report.main(["summarize", str(p), "--run", "ghost"]) == 1
        cap = capsys.readouterr()
        assert cap.out == ""  # never an all-zero report
        assert "ghost" in cap.err and "--list-runs" in cap.err

    def test_json_mode_also_errors_cleanly(self, tmp_path, capsys):
        p = tmp_path / "telemetry.jsonl"
        p.write_text("")
        assert report.main(["summarize", str(p), "--json"]) == 1
        assert capsys.readouterr().out == ""
