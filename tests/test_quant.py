"""int8 quantization (`precision/quant.py`) — numerics and tree walk.

Beyond-reference capability (the MI250X project has no quantized path —
SURVEY C21 stops at AMP), so the contract here is self-imposed: exact
scale factoring, tight error bounds, lossless tree round-trip shape."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.precision.quant import (
    dequantize,
    dequantize_tree,
    int8_matmul,
    quantize_int8,
    quantize_tree,
    quantized_dense,
)


class TestQuantizeInt8:
    def test_round_trip_error_bound(self):
        x = jax.random.normal(jax.random.key(0), (64, 128), jnp.float32)
        q, s = quantize_int8(x, axis=-1)
        assert q.dtype == jnp.int8 and s.shape == (64, 1)
        err = np.abs(dequantize(q, s) - np.asarray(x))
        # max error per row is half a quantization step = scale/2
        assert np.all(err <= np.asarray(s) / 2 + 1e-7)

    def test_axis0_scale_shape(self):
        w = jax.random.normal(jax.random.key(1), (128, 256), jnp.float32)
        q, s = quantize_int8(w, axis=0)
        assert s.shape == (1, 256)

    def test_values_clip_to_127(self):
        x = jnp.array([[1e9, -1e9, 0.0]], jnp.float32)
        q, _ = quantize_int8(x)
        assert int(q.max()) == 127 and int(q.min()) == -127

    def test_zero_tensor_safe(self):
        q, s = quantize_int8(jnp.zeros((4, 4)))
        assert np.all(np.asarray(q) == 0) and np.all(np.isfinite(np.asarray(s)))


class TestInt8Matmul:
    def test_matches_float_matmul(self):
        kx, kw = jax.random.split(jax.random.key(2))
        x = jax.random.normal(kx, (32, 128), jnp.float32)
        w = jax.random.normal(kw, (128, 64), jnp.float32)
        xq, sx = quantize_int8(x, axis=-1)
        wq, sw = quantize_int8(w, axis=0)
        out = int8_matmul(xq, wq, sx, sw, out_dtype=jnp.float32)
        ref = x @ w
        # int8 x int8 with exact int32 accumulation: error comes only
        # from input rounding — ~0.5% relative for unit-variance data
        rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
        assert rel < 0.015, f"relative error {rel:.4f}"

    def test_batched_lhs(self):
        x = jax.random.normal(jax.random.key(3), (4, 8, 32), jnp.float32)
        w = jax.random.normal(jax.random.key(4), (32, 16), jnp.float32)
        xq, sx = quantize_int8(x, axis=-1)
        wq, sw = quantize_int8(w, axis=0)
        out = int8_matmul(xq, wq, sx, sw, out_dtype=jnp.float32)
        assert out.shape == (4, 8, 16)
        rel = np.linalg.norm(out - x @ w) / np.linalg.norm(np.asarray(x @ w))
        assert rel < 0.02

    def test_quantized_dense_drop_in(self):
        kx, kw = jax.random.split(jax.random.key(5))
        x = jax.random.normal(kx, (16, 64), jnp.bfloat16)
        w = jax.random.normal(kw, (64, 32), jnp.float32)
        wq, sw = quantize_int8(w, axis=0)
        out = quantized_dense(x, wq, sw)
        assert out.dtype == jnp.bfloat16 and out.shape == (16, 32)
        ref = x.astype(jnp.float32) @ w
        rel = np.linalg.norm(out.astype(jnp.float32) - ref) / np.linalg.norm(ref)
        assert rel < 0.03  # bf16 activations add their own rounding

    def test_jit_and_grad_free(self):
        # the quantized path is inference-only: jit must compile it and
        # produce the same values as eager
        kx, kw = jax.random.split(jax.random.key(6))
        x = jax.random.normal(kx, (8, 32), jnp.float32)
        w = jax.random.normal(kw, (32, 8), jnp.float32)
        wq, sw = quantize_int8(w, axis=0)
        eager = quantized_dense(x, wq, sw, out_dtype=jnp.float32)
        jitted = jax.jit(
            lambda x: quantized_dense(x, wq, sw, out_dtype=jnp.float32)
        )(x)
        np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted),
                                   rtol=1e-6)


class TestQuantizeTree:
    def _params(self):
        k = jax.random.key(7)
        return {
            "dense": {"kernel": jax.random.normal(k, (32, 16)),
                      "bias": jnp.zeros((16,))},
            "emb": {"embedding": jax.random.normal(k, (50, 8))},
            "norm": {"scale": jnp.ones((32,))},
        }

    def test_only_2d_kernels_quantized(self):
        qt = quantize_tree(self._params())
        assert set(qt["dense"]["kernel"]) == {"q", "scale"}
        assert qt["dense"]["kernel"]["q"].dtype == jnp.int8
        assert qt["dense"]["bias"].dtype == jnp.float32
        assert qt["emb"]["embedding"].dtype == jnp.float32

    def test_round_trip(self):
        params = self._params()
        back = dequantize_tree(quantize_tree(params), dtype=jnp.float32)
        ref = params["dense"]["kernel"]
        rel = np.linalg.norm(back["dense"]["kernel"] - ref) / np.linalg.norm(
            np.asarray(ref))
        assert rel < 0.01
        np.testing.assert_array_equal(
            np.asarray(back["norm"]["scale"]), np.asarray(params["norm"]["scale"]))

    def test_memory_halves_vs_bf16(self):
        # weight-only int8's point: kernel bytes drop 2x vs bf16 (4x vs
        # fp32), scales are negligible
        params = {"dense": {"kernel": jnp.zeros((256, 256), jnp.float32)}}
        qt = quantize_tree(params)
        q_bytes = qt["dense"]["kernel"]["q"].nbytes
        s_bytes = qt["dense"]["kernel"]["scale"].nbytes
        assert q_bytes == 256 * 256  # 1 byte/elem
        assert s_bytes <= 4 * 256
