"""int8 quantization (`precision/quant.py`) — numerics and tree walk.

Beyond-reference capability (the MI250X project has no quantized path —
SURVEY C21 stops at AMP), so the contract here is self-imposed: exact
scale factoring, tight error bounds, lossless tree round-trip shape."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.precision.quant import (
    dequantize,
    dequantize_params,
    int8_matmul,
    quantize_int8,
    quantize_llama,
    quantized_dense,
)


class TestQuantizeInt8:
    def test_round_trip_error_bound(self):
        x = jax.random.normal(jax.random.key(0), (64, 128), jnp.float32)
        q, s = quantize_int8(x, axis=-1)
        assert q.dtype == jnp.int8 and s.shape == (64, 1)
        err = np.abs(dequantize(q, s) - np.asarray(x))
        # max error per row is half a quantization step = scale/2
        assert np.all(err <= np.asarray(s) / 2 + 1e-7)

    def test_axis0_scale_shape(self):
        w = jax.random.normal(jax.random.key(1), (128, 256), jnp.float32)
        q, s = quantize_int8(w, axis=0)
        assert s.shape == (1, 256)

    def test_values_clip_to_127(self):
        x = jnp.array([[1e9, -1e9, 0.0]], jnp.float32)
        q, _ = quantize_int8(x)
        assert int(q.max()) == 127 and int(q.min()) == -127

    def test_zero_tensor_safe(self):
        q, s = quantize_int8(jnp.zeros((4, 4)))
        assert np.all(np.asarray(q) == 0) and np.all(np.isfinite(np.asarray(s)))


class TestInt8Matmul:
    def test_matches_float_matmul(self):
        kx, kw = jax.random.split(jax.random.key(2))
        x = jax.random.normal(kx, (32, 128), jnp.float32)
        w = jax.random.normal(kw, (128, 64), jnp.float32)
        xq, sx = quantize_int8(x, axis=-1)
        wq, sw = quantize_int8(w, axis=0)
        out = int8_matmul(xq, wq, sx, sw, out_dtype=jnp.float32)
        ref = x @ w
        # int8 x int8 with exact int32 accumulation: error comes only
        # from input rounding — ~0.5% relative for unit-variance data
        rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
        assert rel < 0.015, f"relative error {rel:.4f}"

    def test_batched_lhs(self):
        x = jax.random.normal(jax.random.key(3), (4, 8, 32), jnp.float32)
        w = jax.random.normal(jax.random.key(4), (32, 16), jnp.float32)
        xq, sx = quantize_int8(x, axis=-1)
        wq, sw = quantize_int8(w, axis=0)
        out = int8_matmul(xq, wq, sx, sw, out_dtype=jnp.float32)
        assert out.shape == (4, 8, 16)
        rel = np.linalg.norm(out - x @ w) / np.linalg.norm(np.asarray(x @ w))
        assert rel < 0.02

    def test_quantized_dense_drop_in(self):
        kx, kw = jax.random.split(jax.random.key(5))
        x = jax.random.normal(kx, (16, 64), jnp.bfloat16)
        w = jax.random.normal(kw, (64, 32), jnp.float32)
        wq, sw = quantize_int8(w, axis=0)
        out = quantized_dense(x, wq, sw)
        assert out.dtype == jnp.bfloat16 and out.shape == (16, 32)
        ref = x.astype(jnp.float32) @ w
        rel = np.linalg.norm(out.astype(jnp.float32) - ref) / np.linalg.norm(ref)
        assert rel < 0.03  # bf16 activations add their own rounding

    def test_jit_and_grad_free(self):
        # the quantized path is inference-only: jit must compile it and
        # produce the same values as eager
        kx, kw = jax.random.split(jax.random.key(6))
        x = jax.random.normal(kx, (8, 32), jnp.float32)
        w = jax.random.normal(kw, (32, 8), jnp.float32)
        wq, sw = quantize_int8(w, axis=0)
        eager = quantized_dense(x, wq, sw, out_dtype=jnp.float32)
        jitted = jax.jit(
            lambda x: quantized_dense(x, wq, sw, out_dtype=jnp.float32)
        )(x)
        np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted),
                                   rtol=1e-6)


class TestQuantLlama:
    """Weight-only int8 through the real model (`LlamaConfig.quant`)."""

    def _setup(self):
        from hyperion_tpu.models.llama import Llama, llama_tiny_config

        cfg = llama_tiny_config()
        model = Llama(cfg)
        params = model.init_params(jax.random.key(0), batch=2, seq=16)
        qmodel, qparams = quantize_llama(params, cfg)
        return cfg, model, params, qmodel, qparams

    def test_forward_parity(self):
        cfg, model, params, qmodel, qparams = self._setup()
        ids = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                 cfg.vocab_size, jnp.int32)
        ref = model.apply({"params": params}, ids)
        out = qmodel.apply({"params": qparams}, ids)
        rel = np.linalg.norm(out - ref) / np.linalg.norm(np.asarray(ref))
        assert rel < 0.03, f"quantized forward off by {rel:.4f}"

    def test_param_structure_matches_init(self):
        # the converted tree must be loadable wherever the quant model's
        # own init is — same leaf paths, shapes, dtypes
        _, _, _, qmodel, qparams = self._setup()
        init_q = qmodel.init_params(jax.random.key(0), batch=2, seq=16)
        s1 = jax.tree.map(lambda a: (a.shape, str(a.dtype)), init_q)
        s2 = jax.tree.map(lambda a: (a.shape, str(a.dtype)), qparams)
        assert s1 == s2

    def test_kv_cache_decode(self):
        from hyperion_tpu.infer.generate import generate

        cfg, _, _, qmodel, qparams = self._setup()
        prompt = jax.random.randint(jax.random.key(2), (2, 8), 0,
                                    cfg.vocab_size, jnp.int32)
        out = generate(qmodel, {"params": qparams}, prompt, max_new_tokens=4)
        assert out.shape == (2, 4) and out.dtype == jnp.int32
        again = generate(qmodel, {"params": qparams}, prompt, max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(again))

    def test_int8_weight_bytes(self):
        _, _, params, _, qparams = self._setup()
        def nbytes(t):
            return sum(x.nbytes for x in jax.tree.leaves(t))
        # fp32 tiny model: quantized tree should be ~4x smaller on the
        # dense kernels; overall well under half (embeddings stay float)
        assert nbytes(qparams) < 0.6 * nbytes(params)


class TestQuantTransformerLM:
    """Weight-only int8 through the GPT-2-family LM (biased denses,
    recompute generation path)."""

    def _setup(self):
        from hyperion_tpu.models.transformer_lm import (
            TransformerLM, simple_lm_config,
        )
        from hyperion_tpu.precision.quant import quantize_lm

        cfg = simple_lm_config(
            vocab_size=128, d_model=32, n_heads=4, n_layers=2, ff_dim=64,
            max_len=16, dropout=0.0,
        )
        model = TransformerLM(cfg)
        params = model.init_params(jax.random.key(0))
        # init-time biases are all zeros, which would make every bias
        # assertion vacuous — perturb them so the bias path is
        # load-bearing in the parity checks below
        keys = iter(jax.random.split(jax.random.key(99), 64))

        def bump_biases(node):
            if isinstance(node, dict):
                return {
                    k: (0.1 * jax.random.normal(next(keys), v.shape, v.dtype)
                        if k == "bias" else bump_biases(v))
                    for k, v in node.items()
                }
            return node

        params = bump_biases(params)
        qmodel, qparams = quantize_lm(params, cfg)
        return cfg, model, params, qmodel, qparams

    def test_forward_parity_with_biases(self):
        cfg, model, params, qmodel, qparams = self._setup()
        ids = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                 cfg.vocab_size, jnp.int32)
        ref = model.apply({"params": params}, ids)
        out = qmodel.apply({"params": qparams}, ids)
        rel = np.linalg.norm(out - ref) / np.linalg.norm(np.asarray(ref))
        assert rel < 0.03, f"quantized forward off by {rel:.4f}"

    def test_bias_stays_float_and_loads(self):
        _, _, params, qmodel, qparams = self._setup()
        blk = qparams["block_0"]
        assert blk["fc1"]["kernel_q"].dtype == jnp.int8
        np.testing.assert_array_equal(
            np.asarray(blk["fc1"]["bias"]),
            np.asarray(params["block_0"]["fc1"]["bias"]),
        )
        init_q = qmodel.init_params(jax.random.key(0))
        s1 = jax.tree.map(lambda a: (a.shape, str(a.dtype)), init_q)
        s2 = jax.tree.map(lambda a: (a.shape, str(a.dtype)), qparams)
        assert s1 == s2

    def test_float_param_structure_unchanged(self):
        # routing every dense through one ctor must not move or rename
        # any float param (checkpoint + TP-rule compatibility)
        _, _, params, _, _ = self._setup()
        blk = params["block_0"]
        assert set(blk["fc1"]) == {"kernel", "bias"}
        assert blk["fc1"]["kernel"].shape == (32, 64)
        assert set(blk["attn"]["q_proj"]) == {"kernel", "bias"}
        assert blk["attn"]["q_proj"]["kernel"].shape == (32, 4, 8)
        assert blk["attn"]["o_proj"]["kernel"].shape == (4, 8, 32)
        assert params["lm_head"]["kernel"].shape == (32, 128)

    def test_recompute_generation(self):
        from hyperion_tpu.infer.generate import generate_recompute

        cfg, _, _, qmodel, qparams = self._setup()
        prompt = jax.random.randint(jax.random.key(2), (2, 4), 0,
                                    cfg.vocab_size, jnp.int32)
        out = generate_recompute(qmodel, {"params": qparams}, prompt,
                                 max_new_tokens=4)
        assert out.shape == (2, 4) and out.dtype == jnp.int32


class TestParamsRoundTrip:
    def test_weight_only_selective(self):
        # the converted tree quantizes dense kernels only: norms and
        # embeddings stay float (the weight-only recipe)
        _, _, params, _, qparams = TestQuantLlama()._setup()
        layer = qparams["layer_0"]
        assert layer["attn"]["q_proj"]["kernel_q"].dtype == jnp.int8
        assert layer["attn"]["o_proj"]["kernel_q"].dtype == jnp.int8
        assert layer["input_norm"]["weight"].dtype == jnp.float32
        assert qparams["embed_tokens"]["embedding"].dtype == params[
            "embed_tokens"]["embedding"].dtype

    def test_dequantize_params_restores_kernels(self):
        _, _, params, _, qparams = TestQuantLlama()._setup()
        back = dequantize_params(qparams, dtype=jnp.float32)
        ref = params["layer_0"]["mlp"]["gate_proj"]["kernel"]
        got = back["layer_0"]["mlp"]["gate_proj"]["kernel"]
        assert got.shape == ref.shape
        rel = np.linalg.norm(got - ref) / np.linalg.norm(np.asarray(ref))
        assert rel < 0.01
        # o_proj's 3-D kernel (contraction over two axes) round-trips too
        ref = params["layer_0"]["attn"]["o_proj"]["kernel"]
        got = back["layer_0"]["attn"]["o_proj"]["kernel"]
        assert got.shape == ref.shape
        rel = np.linalg.norm(got - ref) / np.linalg.norm(np.asarray(ref))
        assert rel < 0.01
