"""Scaling-report + bench-suite plumbing tests (pure/fast paths).

The reference's report pipeline was only ever validated by running it on
a 4-GPU box (SURVEY §4); here the parsing, warmup-discard, and
speedup/efficiency math get golden tests on synthetic CSVs.
"""

import csv
from pathlib import Path

import pytest

from hyperion_tpu.bench.compile_bench import summarize
from hyperion_tpu.metrics.csv_logger import run_id
from hyperion_tpu.metrics.scaling_report import (
    create_scaling_report,
    parse_run_name,
)


def write_metrics(dir: Path, job: str, n: int, durations, ts="20260729_120000"):
    dir.mkdir(parents=True, exist_ok=True)
    path = dir / f"{job}_{n}gpus_{ts}_metrics.csv"
    with path.open("w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["epoch", "loss", "duration_s", "gpus"])
        for i, d in enumerate(durations):
            w.writerow([i + 1, 5.0, d, n])
    return path


class TestParseRunName:
    def test_roundtrip_with_logger_format(self):
        rid = run_id("language_ddp", 4)
        assert parse_run_name(f"{rid}_metrics.csv") == ("language_ddp", 4)

    def test_job_names_with_underscores(self):
        assert parse_run_name("cifar_ddp_8gpus_20260101_000000_metrics.csv") == \
            ("cifar_ddp", 8)

    def test_rejects_foreign_files(self):
        assert parse_run_name("scaling_analysis.csv") is None


class TestScalingReport:
    def test_speedup_and_efficiency(self, tmp_path):
        # 3 epochs; first third (1 epoch) discarded as warmup
        write_metrics(tmp_path, "language_ddp", 1, [100.0, 12.0, 12.0])
        write_metrics(tmp_path, "language_ddp", 4, [50.0, 4.0, 4.0])
        rows = create_scaling_report(tmp_path)
        by_n = {r["gpus"]: r for r in rows}
        assert by_n[1]["epoch_time_s"] == 12.0  # warmup epoch dropped
        assert by_n[4]["speedup"] == 3.0
        assert by_n[4]["efficiency_pct"] == 75.0
        assert (tmp_path / "scaling_analysis.csv").exists()

    def test_multiple_runs_same_count_average(self, tmp_path):
        write_metrics(tmp_path, "cifar_ddp", 1, [10.0, 10.0],
                      ts="20260729_110000")
        write_metrics(tmp_path, "cifar_ddp", 1, [20.0, 20.0],
                      ts="20260729_120000")
        rows = create_scaling_report(tmp_path)
        assert rows[0]["epoch_time_s"] == 15.0

    def test_no_baseline_reports_absolute_only(self, tmp_path):
        write_metrics(tmp_path, "llama", 4, [30.0, 30.0])
        rows = create_scaling_report(tmp_path)
        assert rows[0]["speedup"] == ""

    def test_empty_dir_is_empty_not_fabricated(self, tmp_path):
        # the reference fabricates sample data here; we must not
        assert create_scaling_report(tmp_path) == []
        content = (tmp_path / "scaling_analysis.csv").read_text()
        assert content.strip().splitlines()[1:] == []


class TestCompileBenchSummary:
    def test_speedups_vs_jit(self):
        rows = [
            {"model": "m", "variant": "op_by_op", "median_ms": 100.0, "note": ""},
            {"model": "m", "variant": "jit", "median_ms": 10.0, "note": ""},
            {"model": "m", "variant": "jit_pallas", "median_ms": 5.0, "note": ""},
        ]
        text = summarize(rows)
        assert "0.10x" in text
        assert "2.00x" in text

    def test_failed_variant(self):
        rows = [
            {"model": "m", "variant": "jit", "median_ms": 10.0, "note": ""},
            {"model": "m", "variant": "jit_pallas", "median_ms": float("nan"),
             "note": "failed: x"},
            {"model": "m", "variant": "op_by_op", "median_ms": 20.0, "note": ""},
        ]
        assert "failed" in summarize(rows)


class TestCliParser:
    def test_defaults_per_job(self):
        from hyperion_tpu.cli.main import build_parser, make_config

        args = build_parser().parse_args(["--model", "cifar"])
        cfg = make_config(args, "cifar")
        assert cfg.train.batch_size == 64
        assert cfg.train.learning_rate == 1e-3

    def test_fsdp_jobs_get_fsdp_mesh_and_clip(self):
        from hyperion_tpu.cli.main import build_parser, make_config

        args = build_parser().parse_args(["--model", "language_fsdp"])
        cfg = make_config(args, "language_fsdp")
        assert cfg.distributed.fsdp == -1
        assert cfg.optimization.grad_clip_norm == 1.0

    def test_mesh_override(self):
        from hyperion_tpu.cli.main import build_parser, make_config

        args = build_parser().parse_args(
            ["--model", "language_ddp", "--mesh", "2,2,2,1"])
        cfg = make_config(args, "language_ddp")
        assert (cfg.distributed.data, cfg.distributed.fsdp,
                cfg.distributed.model, cfg.distributed.seq) == (2, 2, 2, 1)


class TestDecodeBench:
    @pytest.mark.slow
    def test_tiny_decode_row(self, tmp_path):
        from hyperion_tpu.bench.decode_bench import benchmark_decode

        row = benchmark_decode("tiny", batch=2, prompt_len=16, decode_len=8)
        assert row["decode_tokens_per_s"] > 0
        assert row["prefill_ms"] > 0
        assert row["params_m"] > 0


class TestCompareToReference:
    """The round-end comparison tool (scripts/compare_to_reference.py)
    must render whatever subset of capture artifacts exists."""

    def _run(self, tmp_path, capsys):
        import importlib.util
        import sys

        spec = importlib.util.spec_from_file_location(
            "compare_to_reference",
            Path(__file__).parent.parent / "scripts" / "compare_to_reference.py",
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        argv = sys.argv
        sys.argv = ["x", "--root", str(tmp_path / "benchmarks"),
                    "--runs", str(tmp_path / "runs")]
        try:
            mod.main()
        finally:
            sys.argv = argv
        return capsys.readouterr().out

    def test_empty_capture_renders_placeholders(self, tmp_path, capsys):
        out = self._run(tmp_path, capsys)
        assert "not captured yet" in out
        assert "## Model baselines" in out

    def test_populated_tables(self, tmp_path, capsys):
        bdir = tmp_path / "benchmarks" / "baseline"
        bdir.mkdir(parents=True)
        with (bdir / "model_benchmarks.csv").open("w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=[
                "model", "batch_size", "dtype", "total_ms", "samples_per_s"])
            w.writeheader()
            w.writerow({"model": "resnet50", "batch_size": 32,
                        "dtype": "bfloat16", "total_ms": 28.0,
                        "samples_per_s": 1142.9})
        (tmp_path / "benchmarks" / "bench_live.json").write_text(
            '{"value": 175.75, "unit": "TFLOPS", "vs_baseline": 1.452}\n')
        out = self._run(tmp_path, capsys)
        assert "175.75" in out
        assert "resnet50" in out and "2.01x" in out  # 1142.9/568.22


class TestValidateHeadline:
    """Headline promotion (scripts/validate_headline.py) is monotonic:
    a degraded tunnel window must not overwrite the committed record
    (2026-07-31: the time-shared chip measured 81.7 TFLOPS on the same
    chain that recorded 175.75 the day before)."""

    SCRIPT = Path(__file__).parent.parent / "scripts" / "validate_headline.py"

    def _run(self, tmp_path, latest=None, good=None):
        import subprocess
        import sys

        out = tmp_path / "results" / "benchmarks"
        out.mkdir(parents=True, exist_ok=True)
        if latest is not None:
            (out / "bench_live_latest.json").write_text(latest)
        if good is not None:
            (out / "bench_live.json").write_text(good)
        proc = subprocess.run(
            [sys.executable, str(self.SCRIPT)], cwd=tmp_path,
            capture_output=True, text=True, timeout=60,
        )
        good_path = out / "bench_live.json"
        return proc.returncode, (
            good_path.read_text() if good_path.exists() else None
        )

    def test_first_capture_promotes(self, tmp_path):
        rc, good = self._run(tmp_path, latest='{"value": 100.0}\n')
        assert rc == 0 and '"value": 100.0' in good

    def test_better_value_promotes(self, tmp_path):
        rc, good = self._run(
            tmp_path, latest='{"value": 180.0}\n', good='{"value": 175.75}\n')
        assert rc == 0 and '"value": 180.0' in good

    def test_degraded_window_keeps_record_and_fails_stage(self, tmp_path):
        rc, good = self._run(
            tmp_path, latest='{"value": 81.69}\n', good='{"value": 175.75}\n')
        assert rc == 1 and '"value": 175.75' in good

    def test_zero_headline_fails_stage(self, tmp_path):
        rc, good = self._run(
            tmp_path, latest='{"value": 0.0, "error": "x"}\n',
            good='{"value": 175.75}\n')
        assert rc == 1 and '"value": 175.75' in good

    def test_missing_latest_fails_stage(self, tmp_path):
        rc, _ = self._run(tmp_path, latest=None, good='{"value": 175.75}\n')
        assert rc == 1

    def test_within_noise_window_stamps_without_ratchet(self, tmp_path):
        rc, good = self._run(
            tmp_path, latest='{"value": 175.0}\n', good='{"value": 175.75}\n')
        assert rc == 0 and '"value": 175.75' in good  # record untouched


class TestAttentionBench:
    """Long-seq attention scaling bench (bench/attention_bench.py):
    row shape, CSV union-fieldnames, and error rows must not kill the
    sweep (an OOM row is the finding, not a crash)."""

    def test_ok_row_and_csv(self, tmp_path, capsys):
        from hyperion_tpu.bench import attention_bench

        attention_bench.main([
            "--seqs", "128", "--impls", "xla", "--modes", "fwd",
            "--geometries", "gpt2",
            "--dtype", "float32", "--out", str(tmp_path)])
        rows = list(csv.DictReader(
            (tmp_path / "attention_scaling.csv").open()))
        assert len(rows) == 1 and rows[0]["status"] == "ok"
        assert rows[0]["geometry"] == "gpt2"
        assert float(rows[0]["per_iter_ms"]) > 0
        assert float(rows[0]["achieved_tflops"]) > 0

    def test_error_row_records_note(self, tmp_path):
        from hyperion_tpu.bench.attention_bench import benchmark_attention
        from hyperion_tpu.bench.util import write_csv as _write_csv

        ok = benchmark_attention(128, "xla", "fwd", "float32")
        bad = benchmark_attention(128, "definitely-not-an-impl", "fwd")
        assert bad["status"] == "error" and "impl" in bad["note"]
        # union fieldnames: ok row lacks "note", error row adds it
        _write_csv(tmp_path / "mixed.csv", [ok, bad])
        rows = list(csv.DictReader((tmp_path / "mixed.csv").open()))
        assert rows[0]["note"] == "" and rows[1]["status"] == "error"

    def test_attention_table_renders(self, tmp_path, capsys):
        # uses the report-runner helper from TestCompareToReference (the
        # table lives in the same compare_to_reference.py report)
        adir = tmp_path / "benchmarks" / "attention"
        adir.mkdir(parents=True)
        with (adir / "attention_scaling.csv").open("w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=[
                "seq", "impl", "mode", "status", "per_iter_ms",
                "temp_memory_gb"])
            w.writeheader()
            w.writerow({"seq": 8192, "impl": "xla", "mode": "train",
                        "status": "oom", "per_iter_ms": "nan",
                        "temp_memory_gb": "nan"})
            w.writerow({"seq": 8192, "impl": "pallas", "mode": "train",
                        "status": "ok", "per_iter_ms": 12.5,
                        "temp_memory_gb": 0.21})
        out = TestCompareToReference()._run(tmp_path, capsys)
        assert "Long-seq attention" in out
        assert "oom" in out and "12.5" in out  # xla OOM row renders as such
        assert "nanx" not in out  # no speedup computed from a nan row


class TestTier1DurationGuard:
    """scripts/check_tier1_duration.py — the tier-1 wall-time budget
    (a suite one slow test away from the 900s timeout is already a
    regression; the guard fails it at 880s with headroom to spare)."""

    def _guard(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_tier1_duration",
            Path(__file__).parent.parent / "scripts"
            / "check_tier1_duration.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_under_budget_passes(self, tmp_path):
        mod = self._guard()
        log = tmp_path / "t1.log"
        log.write_text("...\n== 1014 passed, 3 skipped in 782.41s "
                       "(0:13:02) ==\n")
        assert mod.main([str(log)]) == 0

    def test_over_budget_fails(self, tmp_path):
        mod = self._guard()
        log = tmp_path / "t1.log"
        log.write_text("== 1014 passed in 891.02s (0:14:51) ==\n")
        assert mod.main([str(log)]) == 1
        # and a custom budget is respected
        assert mod.main([str(log), "920"]) == 0

    def test_missing_summary_is_a_failure(self, tmp_path):
        # a log with no summary line means pytest never finished —
        # exactly the timeout scenario the guard exists to preempt
        mod = self._guard()
        log = tmp_path / "t1.log"
        log.write_text("tests/test_serve.py ......\n")
        assert mod.main([str(log)]) == 1
        assert mod.main([str(tmp_path / "missing.log")]) == 1

    def test_elapsed_fallback_when_quiet_log_has_no_summary(self, tmp_path):
        # the real tier-1 command runs at -qq (pyproject -q + command
        # -q), which suppresses the summary line entirely: the guard
        # must then judge the shell-measured elapsed time instead
        mod = self._guard()
        log = tmp_path / "t1.log"
        log.write_text(".......... [100%]\n")
        assert mod.main([str(log), "--elapsed", "790"]) == 0
        assert mod.main([str(log), "--elapsed", "893"]) == 1
        # a parsed summary line wins over the measurement (the shell
        # clock includes collection + teardown slop)
        log.write_text("== 1014 passed in 700.00s (0:11:40) ==\n")
        assert mod.main([str(log), "--elapsed", "9999"]) == 0

    def test_top_durations_sums_phases_per_test(self, tmp_path, capsys):
        # the --durations table charges setup/call/teardown separately;
        # the guard's share line must charge a slow fixture to the test
        # that paid for it, then rank
        mod = self._guard()
        table = (
            "============ slowest 15 durations ============\n"
            "30.00s call     tests/test_router.py::test_drill\n"
            "12.00s setup    tests/test_router.py::test_drill\n"
            "25.00s call     tests/test_serve.py::test_smoke\n"
            "20.00s call     tests/test_bench.py::test_scale\n"
            "1.50s teardown  tests/test_serve.py::test_smoke\n"
            "9.00s call     tests/test_obs.py::test_minor\n"
        )
        top = mod.top_durations(table)
        assert top == [
            (42.0, "tests/test_router.py::test_drill"),
            (26.5, "tests/test_serve.py::test_smoke"),
            (20.0, "tests/test_bench.py::test_scale"),
        ]
        # and main() narrates the share on every run, not just failures
        log = tmp_path / "t1.log"
        log.write_text(table + "== 100 passed in 200.00s ==\n")
        assert mod.main([str(log)]) == 0
        out = capsys.readouterr().out
        assert "top-3 tests carry 44% of the suite" in out
        assert "test_drill 42s" in out
