"""Native C++ component tests: recordio storage + host coordination.

These compile the extensions with g++ on first use (cached by source
hash), then exercise them for real — including multi-process barriers
and peer-death detection, the failure-handling capability the reference
only had as env-var timeouts (SURVEY §5.3).
"""

import multiprocessing as mp
import os
import socket
import time

import numpy as np
import pytest

from hyperion_tpu.data.recordio import RecordFile, write_records
from hyperion_tpu.runtime.native_coord import CoordError, HostCoordinator


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestRecordIO:
    def test_roundtrip(self, tmp_path):
        rows = np.arange(5 * 8, dtype=np.int32).reshape(5, 8)
        path = tmp_path / "data.rec"
        write_records(path, rows)
        with RecordFile(path) as rf:
            assert len(rf) == 5
            np.testing.assert_array_equal(rf.read_all(), rows)

    def test_gather_shuffled(self, tmp_path):
        rows = np.random.default_rng(0).normal(size=(100, 4, 3)).astype(np.float32)
        path = tmp_path / "data.rec"
        write_records(path, rows)
        with RecordFile(path) as rf:
            idx = np.asarray([7, 0, 99, 42], np.uint64)
            np.testing.assert_array_equal(rf.gather(idx), rows[[7, 0, 99, 42]])

    def test_out_of_range_raises(self, tmp_path):
        write_records(tmp_path / "d.rec", np.zeros((3, 2), np.int8))
        with RecordFile(tmp_path / "d.rec") as rf:
            with pytest.raises(IndexError):
                rf.gather(np.asarray([5], np.uint64))

    def test_corrupt_file_rejected(self, tmp_path):
        p = tmp_path / "bad.rec"
        p.write_bytes(b"not a record file at all, padded" * 4)
        (tmp_path / "bad.rec.json").write_text(
            '{"dtype": "int8", "row_shape": [2]}')
        with pytest.raises(OSError):
            RecordFile(p)

    def test_sidecar_mismatch_rejected(self, tmp_path):
        write_records(tmp_path / "d.rec", np.zeros((3, 2), np.int8))
        (tmp_path / "d.rec.json").write_text(
            '{"dtype": "int32", "row_shape": [2]}')
        with pytest.raises(OSError, match="record"):
            RecordFile(tmp_path / "d.rec")


def _worker_ok(port, rank, barriers):
    c = HostCoordinator(rank, 3, port=port, timeout_s=20)
    for _ in range(barriers):
        c.barrier(timeout_s=20)
    c.close()


def _worker_dies_after_join(port, rank):
    c = HostCoordinator(rank, 3, port=port, timeout_s=20)
    del c  # close() → coordinator must detect the dead peer
    os._exit(0)


class TestHostCoordinator:
    def test_three_process_barriers(self):
        port = free_port()
        ctx = mp.get_context("spawn")
        workers = [
            ctx.Process(target=_worker_ok, args=(port, r, 3)) for r in (1, 2)
        ]
        for w in workers:
            w.start()
        coord = HostCoordinator(0, 3, port=port, timeout_s=20)
        assert coord.alive_count() == 3
        for _ in range(3):
            coord.barrier(timeout_s=20)
        for w in workers:
            w.join(timeout=30)
            assert w.exitcode == 0
        coord.close()

    def test_rendezvous_timeout(self):
        port = free_port()
        t0 = time.monotonic()
        with pytest.raises(CoordError, match="rendezvous"):
            HostCoordinator(0, 3, port=port, timeout_s=1.5)
        assert time.monotonic() - t0 < 10

    def test_dead_peer_fails_barrier_fast(self):
        port = free_port()
        ctx = mp.get_context("spawn")
        w1 = ctx.Process(target=_worker_ok, args=(port, 1, 1))
        w2 = ctx.Process(target=_worker_dies_after_join, args=(port, 2))
        w1.start()
        w2.start()
        coord = HostCoordinator(0, 3, port=port, timeout_s=20)
        w2.join(timeout=10)  # rank 2 exits right after joining
        with pytest.raises(CoordError, match="died|timeout"):
            coord.barrier(timeout_s=8)
        w1.terminate()  # rank 1 is stuck in its barrier; clean up
        w1.join(timeout=5)
        coord.close()
