"""obs trace --fleet: the cross-process join (PR 16).

Driven entirely by the golden fleet fixture
(tests/data/telemetry/fleet/ — regenerable via gen_fixtures.py): a
router stream plus two replica streams carrying one clean journey, one
mid-stream failover, and one client resume under a suffixed wire id.
Pins the joins, the Perfetto flow-arrow validity of the merged Chrome
export, the exact-sum fleet attribution, and the partial-evidence
degradation contract (deleted replica dir -> named evidence gaps,
never a crash). Host-only: JSONL parsing, zero jit.
"""

from __future__ import annotations

import json
import re
import shutil
from pathlib import Path

import pytest

from hyperion_tpu.obs import fleet_trace, timeline

FLEET = Path(__file__).parent / "data" / "telemetry" / "fleet"


@pytest.fixture(scope="module")
def asm():
    a = fleet_trace.assemble(FLEET)
    assert a is not None
    return a


def by_id(asm):
    return {r["id"]: r for r in asm["requests"]}


# ------------------------------------------------- resume-id grammar


class TestBaseRequestId:
    """Satellite: the `{rid}~rN` suffix grammar `submit_resume` mints
    must fold back to one id, or every resumed request double-counts
    in attribution and worst-k tables."""

    @pytest.mark.parametrize("rid", ["abc", "w1", "f2", "load-7",
                                     "9f1c", "a~b"])
    @pytest.mark.parametrize("n", [1, 2, 17])
    def test_round_trip(self, rid, n):
        assert timeline.base_request_id(f"{rid}~r{n}") == rid

    def test_identity_for_unsuffixed(self):
        for rid in ("abc", "r1", "x~r", "x~ry", "~r"):
            assert timeline.base_request_id(rid) == rid

    def test_only_the_tail_suffix_strips(self):
        # one resume of a resume suffixes again — strip one layer at a
        # time, exactly like the wire ids nest
        assert timeline.base_request_id("a~r1~r2") == "a~r1"
        assert timeline.base_request_id(
            timeline.base_request_id("a~r1~r2")) == "a"

    def test_mid_string_marker_untouched(self):
        assert timeline.base_request_id("a~r2b") == "a~r2b"

    def test_grammar_matches_minting(self):
        # the producer's format string, pinned: server.py mints
        # f"{rid}~r{seq}" with seq >= 1
        assert re.fullmatch(r".*~r\d+", "x~r1")
        assert timeline.base_request_id("x" + "~r" + "1") == "x"


# ------------------------------------------------------------- joins


class TestFleetJoin:
    def test_discovers_router_and_replicas(self, asm):
        assert asm["router_runs"] == ["route_fix"]
        assert sorted(asm["replicas"]) == [0, 1]
        assert asm["replicas"][0]["runs"] == ["serve_r0_100"]

    def test_three_journeys_joined(self, asm):
        reqs = by_id(asm)
        assert sorted(reqs) == ["f0", "f1", "f2"]
        assert all(r["status"] == "done" for r in reqs.values())

    def test_clean_journey_shape(self, asm):
        f0 = by_id(asm)["f0"]
        assert f0["n_dispatches"] == 1
        assert f0["n_failovers"] == 0 and f0["n_resumes"] == 0
        # single relay: the value IS the router's measured e2e_s
        assert f0["e2e_s"] == pytest.approx(0.132, abs=1e-6)

    def test_failover_journey(self, asm):
        f1 = by_id(asm)["f1"]
        assert f1["n_dispatches"] == 2
        assert f1["n_failovers"] == 1
        c = f1["components_s"]
        # redispatch -> replacement admit: 2 ms re-placement + 300 ms
        # restart/connect (the fixture's pinned gap)
        assert c["failover_gap"] == pytest.approx(0.302, abs=1e-6)
        # replica phases come from the COMPLETING leg (replica 0)
        assert c["queue_wait"] == pytest.approx(0.03, abs=1e-6)

    def test_resume_wire_id_folds(self, asm):
        f2 = by_id(asm)["f2"]
        assert f2["n_resumes"] == 1
        # the resumed leg admitted as `f2~r1` — it must NOT appear as
        # its own journey, and must contribute the resume_gap
        assert "f2~r1" not in by_id(asm)
        assert f2["components_s"]["resume_gap"] == pytest.approx(
            0.007, abs=1e-6)

    def test_no_evidence_gaps_on_the_golden_fixture(self, asm):
        assert asm["evidence_gaps"] == []


# ------------------------------------------------- exact-sum property


class TestAttribution:
    def test_components_sum_exactly_to_measured_value(self, asm):
        """THE tier-1 pin: every fleet attribution row's components +
        other equal the client-observed value — nothing invented,
        nothing dropped between processes."""
        att = fleet_trace.attribution(asm)
        assert att["completed"] == 3
        assert att["rows"], "fixture must yield attribution rows"
        for row in att["rows"]:
            total = sum(row["components_ms"].values()) + row["other_ms"]
            assert total == pytest.approx(row["value_ms"], abs=0.005), \
                (row["metric"], row["q"])

    def test_e2e_vocabulary_is_the_fleet_superset(self, asm):
        (row,) = [r for r in fleet_trace.attribution(asm)["rows"]
                  if r["metric"] == "e2e" and r["q"] == 99]
        assert set(row["components_ms"]) == set(fleet_trace.FLEET_PHASES)

    def test_p99_e2e_dominated_by_failover_gap(self, asm):
        (row,) = [r for r in fleet_trace.attribution(asm)["rows"]
                  if r["metric"] == "e2e" and r["q"] == 99]
        assert row["dominant"] == "failover_gap"
        assert row["dominant_frac"] >= fleet_trace.TAIL_DOMINANT_FRAC

    def test_incident_names_the_slow_restart(self, asm):
        rows = fleet_trace.attribution(asm)["rows"]
        incidents = fleet_trace.tail_incidents(rows)
        assert any("failover_gap" in m and "replica restarts too slow"
                   in m for m in incidents)

    def test_ttft_decomposes_with_cross_process_components(self, asm):
        f0 = by_id(asm)["f0"]
        tc = f0["ttft_components_s"]
        assert tc["router_overhead"] == pytest.approx(0.002, abs=1e-6)
        assert tc["dispatch_gap"] == pytest.approx(0.004, abs=1e-6)
        # ttft value closes exactly over its components
        assert f0["ttft_s"] == pytest.approx(
            sum(tc.values()), abs=1e-6)


# ------------------------------------------------------ Chrome export


class TestChromeExport:
    @pytest.fixture(scope="class")
    def trace(self):
        return fleet_trace.chrome_fleet_trace(fleet_trace.assemble(FLEET))

    def test_one_trace_spans_three_processes(self, trace):
        ev = trace["traceEvents"]
        assert {e["pid"] for e in ev} == {0, 1, 2}
        names = {(e["pid"], e["args"]["name"]) for e in ev
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert (0, "hyperion route") in names
        assert (1, "hyperion serve replica_0") in names
        assert (2, "hyperion serve replica_1") in names

    def test_flow_arrows_pair_and_cross_processes(self, trace):
        """Perfetto renders an arrow only for a well-formed s/f pair:
        same id + cat, the finish side bound to the enclosing slice
        ("bp": "e"). Every dispatch/failover/resume edge must produce
        one, and it must actually cross a process boundary."""
        ev = trace["traceEvents"]
        starts = {e["id"]: e for e in ev if e["ph"] == "s"}
        finishes = {e["id"]: e for e in ev if e["ph"] == "f"}
        assert sorted(starts) == sorted(finishes)
        assert len(starts) == 5  # f0: 1 dispatch; f1: 2; f2: 2
        kinds = []
        for fid, s in starts.items():
            f = finishes[fid]
            assert s["cat"] == f["cat"] == "fleet"
            assert s["name"] == f["name"]
            assert f["bp"] == "e"
            assert s["pid"] == 0 and f["pid"] != 0   # router -> replica
            assert f["ts"] >= s["ts"]                # time flows forward
            kinds.append(s["name"])
        assert sorted(set(kinds)) == ["dispatch", "failover", "resume"]

    def test_replica_segments_share_the_wall_axis(self, trace):
        ev = trace["traceEvents"]
        assert all(e["ts"] >= 0 for e in ev if "ts" in e)
        # the failover's replacement prefill happens AFTER the original
        # dispatch on the merged axis — mono bases differ per process,
        # so only a correct wall conversion orders them
        x = [e for e in ev if e["ph"] == "X"]
        assert any(e["pid"] in (1, 2) for e in x)
        assert any(e["pid"] == 0 and e["name"] == "relay" for e in x)


# ------------------------------------------------------- degradation


class TestPartialEvidence:
    def test_deleted_replica_dir_degrades_with_named_gaps(self, tmp_path):
        base = tmp_path / "fleet"
        shutil.copytree(FLEET, base)
        shutil.rmtree(base / "replica_0")
        asm = fleet_trace.assemble(base)
        assert asm is not None
        # all journeys still render from router-side evidence
        assert sorted(by_id(asm)) == ["f0", "f1", "f2"]
        gaps = "\n".join(asm["evidence_gaps"])
        assert "no matching request_admitted" in gaps
        assert "replica 0" in gaps
        # and the whole pipeline stays alive on the partial evidence
        att = fleet_trace.attribution(asm)
        trace = fleet_trace.chrome_fleet_trace(asm)
        assert att["rows"] and trace["traceEvents"]

    def test_missing_replica_stream_named(self, tmp_path):
        base = tmp_path / "fleet"
        shutil.copytree(FLEET, base)
        (base / "replica_1" / "telemetry.jsonl").unlink()
        asm = fleet_trace.assemble(base)
        assert any("replica_1" in g and "no telemetry.jsonl" in g
                   for g in asm["evidence_gaps"])

    def test_foreign_run_heartbeat_named(self, tmp_path):
        base = tmp_path / "fleet"
        shutil.copytree(FLEET, base)
        hb = base / "replica_0" / "heartbeat.json"
        doc = json.loads(hb.read_text())
        doc["run"] = "serve_r0_SOMEONE_ELSE"
        hb.write_text(json.dumps(doc))
        asm = fleet_trace.assemble(base)
        assert any("foreign run" in g and "replica_0" in g
                   for g in asm["evidence_gaps"])

    def test_torn_router_tail_survives(self, tmp_path):
        base = tmp_path / "fleet"
        shutil.copytree(FLEET, base)
        with (base / "telemetry.jsonl").open("a") as f:
            f.write('{"v":1,"kind":"event","name":"route_disp')
        asm = fleet_trace.assemble(base)
        assert sorted(by_id(asm)) == ["f0", "f1", "f2"]

    def test_no_router_stream_exits_2(self, tmp_path, capsys):
        rc = timeline.main([str(tmp_path), "--fleet", "--export",
                            "none"])
        assert rc == 2
        assert "no router telemetry" in capsys.readouterr().err


# --------------------------------------------------------------- CLI


class TestCli:
    def test_obs_trace_fleet_end_to_end(self, tmp_path, capsys):
        out = tmp_path / "fleet.json"
        rc = timeline.main([str(FLEET), "--fleet", "--export",
                            str(out)])
        assert rc == 0
        text = capsys.readouterr().out
        assert "Fleet trace" in text
        assert "failover_gap" in text
        assert "incident" in text
        t = json.loads(out.read_text())
        assert {e["pid"] for e in t["traceEvents"]} == {0, 1, 2}

    def test_json_mode_carries_the_join(self, tmp_path, capsys):
        rc = timeline.main([str(FLEET), "--fleet", "--json",
                            "--export", "none"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["export"] is None
        assert len(doc["fleet"]["requests"]) == 3
        assert doc["incidents"]
        for row in doc["attribution"]["rows"]:
            total = sum(row["components_ms"].values()) + row["other_ms"]
            assert total == pytest.approx(row["value_ms"], abs=0.005)

    def test_cli_main_dispatches_fleet_flag(self, tmp_path, capsys):
        from hyperion_tpu.cli.main import main as cli_main

        out = tmp_path / "t.json"
        rc = cli_main(["obs", "trace", str(FLEET), "--fleet",
                       "--export", str(out)])
        assert rc == 0
        assert out.exists()


# ----------------------------------------------- doctor integration


class TestDoctorFleetTrace:
    def test_doctor_names_the_cross_process_incident(self):
        from hyperion_tpu.obs import doctor

        d = doctor.diagnose(FLEET)
        assert d["verdict"] == "healthy"
        assert any("failover_gap" in m and "replica restarts" in m
                   for m in d["fleet_trace_incidents"])
        assert "fleet trace:" in d["reason"]
        assert any(r["q"] == 99 for r in d["fleet_trace"])

    def test_doctor_survives_partial_fleet(self, tmp_path):
        from hyperion_tpu.obs import doctor

        base = tmp_path / "fleet"
        shutil.copytree(FLEET, base)
        shutil.rmtree(base / "replica_0")
        d = doctor.diagnose(base)  # must not raise
        assert d["verdict"] in ("healthy", "running", "crashed",
                                "stalled", "hung", "failed")


class TestFixtureRegeneration:
    """The golden fleet fixture is byte-stable: rerunning the generator
    (fake clocks, pinned pid/rss) reproduces the committed files
    exactly, so fixture edits are always deliberate diffs."""

    def test_fleet_fixture_regenerates_byte_identical(self, tmp_path,
                                                      monkeypatch):
        import importlib.util
        from unittest import mock

        gen_path = FLEET.parent / "gen_fixtures.py"
        spec = importlib.util.spec_from_file_location("gen_fix", gen_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        monkeypatch.setattr(mod, "_OUT", tmp_path)
        with mock.patch("os.getpid", return_value=4242), \
                mock.patch("hyperion_tpu.obs.heartbeat.host_rss_mb",
                           return_value=20.5):
            mod.fleet()

        for rel in ("telemetry.jsonl", "heartbeat.json",
                    "replica_0/telemetry.jsonl", "replica_0/heartbeat.json",
                    "replica_1/telemetry.jsonl", "replica_1/heartbeat.json"):
            fresh = (tmp_path / "fleet" / rel).read_bytes()
            committed = (FLEET / rel).read_bytes()
            assert fresh == committed, f"fleet/{rel} drifted from generator"
