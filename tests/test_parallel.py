"""Sharding-layout tests on the simulated 8-device mesh.

Covers what the reference could only check by eyeballing CSVs from a real
4-GPU run (SURVEY §4): that FSDP actually shards memory, that TP specs
divide cleanly, and that a sharded forward/backward agrees numerically
with the replicated one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hyperion_tpu.models.transformer_lm import TransformerLM, simple_lm_config
from hyperion_tpu.parallel import (
    TRANSFORMER_TP_RULES,
    named_shardings,
    partition_specs,
    shard_params,
    shardings_like,
)
from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def lm_params():
    cfg = simple_lm_config(vocab_size=512, d_model=64, n_heads=4, n_layers=2,
                           ff_dim=128, max_len=32)
    model = TransformerLM(cfg)
    return model.init_params(jax.random.key(0))


def _leaf_bytes(tree):
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def _addressable_bytes(tree):
    total = 0
    for leaf in jax.tree.leaves(tree):
        for s in leaf.addressable_shards:
            total += s.data.size * s.data.dtype.itemsize
    return total


class TestFsdpSpecs:
    def test_large_params_shard_small_replicate(self, lm_params, mesh8):
        specs = partition_specs(lm_params, mesh8, fsdp_min_size=2**10)
        flat = jax.tree_util.tree_leaves_with_path(lm_params)
        flat_specs = {jax.tree_util.keystr(k): v for k, v in
                      jax.tree_util.tree_leaves_with_path(specs, is_leaf=lambda x: isinstance(x, P))}
        for key, leaf in flat:
            spec = flat_specs[jax.tree_util.keystr(key)]
            if leaf.size >= 2**10:
                assert "fsdp" in spec, f"{key} {leaf.shape} should be fsdp-sharded"
            else:
                assert spec == P(), f"{key} {leaf.shape} should stay replicated"

    def test_sharding_cuts_per_device_memory(self, lm_params, mesh8):
        shardings = named_shardings(lm_params, mesh8, fsdp_min_size=2**10)
        sharded = shard_params(lm_params, shardings)
        full = _leaf_bytes(lm_params) * 8  # replicated over 8 devices
        actual = _addressable_bytes(sharded)
        # fsdp=4 → params stored ~2x (data axis replicates), not 8x
        assert actual < full / 3

    def test_fsdp_disabled_replicates(self, lm_params, mesh8):
        specs = partition_specs(lm_params, mesh8, fsdp=False)
        assert all(s == P() for s in jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)))


class TestTpSpecs:
    def test_tp_rules_claim_model_axis(self, lm_params):
        mesh = make_mesh(MeshSpec(data=2, model=4))
        specs = partition_specs(lm_params, mesh, tp_rules=TRANSFORMER_TP_RULES,
                                fsdp=False)
        flat = {jax.tree_util.keystr(k): v for k, v in
                jax.tree_util.tree_leaves_with_path(specs, is_leaf=lambda x: isinstance(x, P))}
        qk = next(v for k, v in flat.items() if "q_proj" in k and "kernel" in k)
        assert qk == P(None, "model")  # trailing Nones trimmed
        ok = next(v for k, v in flat.items() if "o_proj" in k and "kernel" in k)
        assert ok == P("model")
        # root-level params (no leading path segment) must match too —
        # embedding + lm_head are ~70% of the params at GPT-2 vocab
        emb = next(v for k, v in flat.items() if "tok_emb" in k)
        assert emb == P(None, "model")
        head = next(v for k, v in flat.items() if "lm_head" in k and "kernel" in k)
        assert head == P(None, "model")

    def test_indivisible_tp_raises(self):
        mesh = make_mesh(MeshSpec(data=1, model=8))
        params = {"x": {"q_proj": {"kernel": np.zeros((4, 6, 2))}}}  # 6 % 8 != 0
        with pytest.raises(ValueError, match="not divisible"):
            partition_specs(params, mesh, tp_rules=TRANSFORMER_TP_RULES, fsdp=False)


class TestNumericalEquivalence:
    def test_sharded_forward_matches_replicated(self, lm_params, mesh8):
        cfg = simple_lm_config(vocab_size=512, d_model=64, n_heads=4, n_layers=2,
                               ff_dim=128, max_len=32)
        model = TransformerLM(cfg)
        ids = jax.random.randint(jax.random.key(1), (8, 32), 0, 512)

        ref = model.apply({"params": lm_params}, ids)

        shardings = named_shardings(lm_params, mesh8, fsdp_min_size=2**10)
        sharded = shard_params(lm_params, shardings)
        batch_sh = NamedSharding(mesh8, P(("data", "fsdp")))
        ids_sharded = jax.device_put(ids, batch_sh)
        out = jax.jit(lambda p, i: model.apply({"params": p}, i))(sharded, ids_sharded)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_tp_forward_matches_replicated(self, lm_params):
        cfg = simple_lm_config(vocab_size=512, d_model=64, n_heads=4, n_layers=2,
                               ff_dim=128, max_len=32)
        model = TransformerLM(cfg)
        ids = jax.random.randint(jax.random.key(1), (4, 32), 0, 512)
        ref = model.apply({"params": lm_params}, ids)

        mesh = make_mesh(MeshSpec(data=2, model=4))
        shardings = named_shardings(lm_params, mesh, tp_rules=TRANSFORMER_TP_RULES)
        sharded = shard_params(lm_params, shardings)
        out = jax.jit(lambda p, i: model.apply({"params": p}, i))(sharded, ids)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestShardingsLike:
    def test_optimizer_state_inherits_param_sharding(self, lm_params, mesh8):
        import optax

        shardings = named_shardings(lm_params, mesh8, fsdp_min_size=2**10)
        opt = optax.adamw(1e-3)
        state_shapes = jax.eval_shape(opt.init, lm_params)
        st_sh = shardings_like(state_shapes, lm_params, shardings, mesh8)
        # mu/nu leaves must not all be replicated
        specs = {s.spec for s in jax.tree.leaves(
            st_sh, is_leaf=lambda x: isinstance(x, NamedSharding))}
        assert any("fsdp" in spec for spec in specs if spec)
        # and scalar count is replicated
        flat = jax.tree.leaves(st_sh, is_leaf=lambda x: isinstance(x, NamedSharding))
        shapes = jax.tree.leaves(state_shapes)
        for sh, shape in zip(flat, shapes):
            if np.prod(shape.shape, dtype=int) == 1:
                assert sh.spec == P()
