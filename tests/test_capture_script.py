"""Capture-script ↔ CLI contract tests.

The round-5 capture stages run unattended in scarce tunnel windows; a
flag typo costs a full stage attempt (and its retry) before anyone
notices. These tests extract every `python -m hyperion_tpu...`
invocation from scripts/capture_round5.sh and drive the REAL argument
parsers over them, so flag drift fails in CI instead of on the chip.
"""

from __future__ import annotations

import re
import shlex
from pathlib import Path

import pytest

SCRIPT = Path(__file__).resolve().parents[1] / "scripts" / "capture_round5.sh"


def _invocations() -> list[list[str]]:
    """['-m', 'module', args...] for each python -m line (continuations
    joined)."""
    text = SCRIPT.read_text()
    text = re.sub(r"\\\n\s*", " ", text)  # join line continuations
    out = []
    for line in text.splitlines():
        line = line.strip()
        m = re.search(r"python -m (hyperion_tpu[\w.]*)\s+(.*)", line)
        if not m:
            continue
        module, rest = m.group(1), m.group(2)
        # drop shell artifacts after the command proper
        rest = rest.split("|")[0].split(">")[0]
        toks = [t for t in shlex.split(rest) if t != ";"]
        out.append([module, *toks])
    return out


def _sub_vars(toks: list[str]) -> list[str]:
    # the script's $OUT/$RUNS expand to plain paths; any $VAR is a path
    return [re.sub(r"\$\{?\w+\}?", "results/x", t) for t in toks]


class TestCaptureInvocations:
    def test_script_exists_and_has_stages(self):
        text = SCRIPT.read_text()
        assert text.count("stage ") >= 10
        # ADVICE r4: re-tuned stages must carry fresh stamp labels
        for label in ("llama7b_proof_r5", "attention_bench_r5",
                      "compile_bench_r5", "wikitext_real_ddp_r5"):
            assert label in text, f"missing stage {label}"

    def test_cli_invocations_parse(self):
        from hyperion_tpu.cli.main import build_parser

        invocations = [
            i for i in _invocations() if i[0] == "hyperion_tpu.cli.main"
        ]
        assert len(invocations) >= 4  # 7B proof + 2 real-data + tiny lora
        parser = build_parser()
        for inv in invocations:
            args = parser.parse_args(_sub_vars(inv[1:]))  # SystemExit = fail
            assert args.model in ("llama", "language_ddp", "language_fsdp",
                                  "cifar", "all", "scaling")

    def test_real_data_stages_use_committed_arrows(self):
        from hyperion_tpu.cli.main import build_parser

        parser = build_parser()
        real = []
        for inv in _invocations():
            if inv[0] != "hyperion_tpu.cli.main":
                continue
            args = parser.parse_args(_sub_vars(inv[1:]))
            if args.train_split == "test":
                real.append(args)
        assert len(real) >= 3  # 7B proof, ddp, fsdp (+ tiny lora)
        for args in real:
            assert args.data_dir == "data", (
                "real-data stages must load from the committed arrows"
            )
            # and the committed arrow must actually exist
            arrow = (SCRIPT.parents[1] / args.data_dir /
                     "wikitext2_tokenized" / "test")
            assert list(arrow.glob("data-*.arrow"))

    @pytest.mark.parametrize("module", [
        "hyperion_tpu.bench.decode_bench",
        "hyperion_tpu.bench.baseline",
        "hyperion_tpu.bench.attention_bench",
        "hyperion_tpu.bench.compile_bench",
        "hyperion_tpu.bench.hw_explore",
    ])
    def test_bench_invocations_parse(self, module):
        """Drive the REAL bench parsers (build_parser) over the script's
        argv — argparse choices/types catch bad values, not just
        unknown flags."""
        import importlib

        invocations = [i for i in _invocations() if i[0] == module]
        if not invocations:
            pytest.skip(f"{module} not invoked by capture_round5.sh")
        mod = importlib.import_module(module)
        if not hasattr(mod, "build_parser"):
            # modules without the split still get flag-name validation
            src = Path(mod.__file__).read_text()
            for inv in invocations:
                for tok in inv[1:]:
                    if tok.startswith("--"):
                        assert f'"{tok}"' in src, (
                            f"{module}: unknown flag {tok}"
                        )
            return
        parser = mod.build_parser()
        for inv in invocations:
            parser.parse_args(_sub_vars(inv[1:]))  # SystemExit = failure
