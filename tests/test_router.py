"""Replica-tier router: dispatch policy as pure host logic, the
ejection/readmission state machine, failover dedup, client reconnect,
the socket load driver, fleet doctor/diff integration — and the
subprocess acceptance drill (2 supervised replicas, one SIGKILLed
mid-stream, client output bit-identical to a single engine).

Everything except the acceptance class runs with ZERO jit compiles:
the router runtime itself is jax-free, so its tests drive it over
fake replicas that speak the wire protocol (tokens derived
deterministically from prompt+seed, exactly like the real engine's
guarantee) — the dispatch/failover/affinity machinery is exercised end
to end in a few seconds.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from hyperion_tpu.serve.replica import (
    EJECTED,
    READY,
    STARTING,
    ReplicaHandle,
)
from hyperion_tpu.serve.router import (
    Router,
    RouterPolicy,
    StreamDedup,
    build_parser,
)

REPO = Path(__file__).resolve().parents[1]


def beat(t, phase="serve", active=0, queue=0, pid=1):
    return {"v": 1, "run": "x", "pid": pid, "phase": phase,
            "t_wall": t, "t_mono": t, "beats": 1,
            "active": active, "queue": queue}


def mkreps(tmp_path, n):
    return [ReplicaHandle.under(tmp_path, i) for i in range(n)]


# --------------------------------------------------- state machine


class TestReplicaStateMachine:
    def test_only_serve_phase_beats_admit(self, tmp_path):
        rep = mkreps(tmp_path, 1)[0]
        assert rep.state == STARTING
        assert rep.observe_beat(beat(10.0, phase="load"), 10.0) is None
        assert rep.observe_beat(beat(11.0, phase="warmup"), 11.0) is None
        assert rep.state == STARTING
        assert rep.observe_beat(beat(12.0, phase="serve"), 12.0) == "ready"
        assert rep.state == READY

    def test_stale_ejects_and_only_newer_beat_readmits(self, tmp_path):
        rep = mkreps(tmp_path, 1)[0]
        rep.observe_beat(beat(10.0), 10.0)
        assert rep.check_stale(15.0, stale_s=10.0) is None
        reason = rep.check_stale(25.0, stale_s=10.0)
        assert reason and "stale" in reason
        assert rep.state == EJECTED and rep.ejected_at == 25.0
        # the crashed child's old heartbeat file is still on disk: a
        # re-read of the SAME beat must not readmit
        assert rep.observe_beat(beat(10.0), 26.0) is None
        assert rep.state == EJECTED
        # a beat newer than the file's last but OLDER than the ejection
        # must not readmit either
        assert rep.observe_beat(beat(20.0), 27.0) is None
        assert rep.state == EJECTED
        # only a genuinely fresh serve beat readmits
        assert rep.observe_beat(beat(28.0), 28.0) == "ready"
        assert rep.state == READY and rep.ejected_at is None

    def test_draining_replica_is_ejected_not_dispatched(self, tmp_path):
        """A replica that is still BEATING but has left the serve
        phases (graceful drain, done) must stop receiving dispatches —
        its queue rejects everything, and forwarding those rejections
        while healthy peers idle would be self-inflicted downtime."""
        rep = mkreps(tmp_path, 1)[0]
        assert rep.observe_beat(beat(1.0), 1.0) == "ready"
        assert rep.observe_beat(beat(2.0, phase="drain"), 2.0) == "ejected"
        assert rep.state == EJECTED
        assert "serve phase" in rep.eject_reason
        # a done beat while already ejected: no transition
        assert rep.observe_beat(beat(3.0, phase="done"), 3.0) is None
        # ... but a fresh serve beat (a restarted child) readmits
        assert rep.observe_beat(beat(4.0), 4.0) == "ready"

    def test_first_eject_reason_sticks(self, tmp_path):
        rep = mkreps(tmp_path, 1)[0]
        rep.observe_beat(beat(1.0), 1.0)
        assert rep.eject(2.0, "connection error") == "connection error"
        assert rep.eject(3.0, "child exit 70") == "connection error"
        assert rep.ejected_at == 2.0

    def test_load_score_adds_unseen_dispatches(self, tmp_path):
        rep = mkreps(tmp_path, 1)[0]
        rep.observe_beat(beat(1.0, active=2, queue=3), 1.0)
        assert rep.load_score() == 5
        rep.dispatched_since_beat += 4
        assert rep.load_score() == 9
        # a fresh beat folds them into its own active/queue
        rep.observe_beat(beat(2.0, active=4, queue=1), 2.0)
        assert rep.load_score() == 5


# ----------------------------------------------------- dispatch policy


def _ready_policy(tmp_path, n=3, **kw):
    pol = RouterPolicy(mkreps(tmp_path, n), **kw)
    pol.observe_beats(lambda p: beat(1.0), now=1.0)
    return pol


class TestRouterPolicy:
    def test_least_loaded_with_index_tiebreak(self, tmp_path):
        pol = _ready_policy(tmp_path)
        pol.replicas[0].hb_active = 2
        pol.replicas[1].hb_queue = 1
        rep, _ = pol.choose({"prompt_ids": [1, 2]})
        assert rep.index == 2
        # tie between 1 (score 1+1 dispatch... ) — reset and check tie
        pol2 = _ready_policy(tmp_path)
        rep, _ = pol2.choose({"prompt_ids": [1, 2]})
        assert rep.index == 0  # all zero: lowest index wins

    def test_choose_accounts_dispatches(self, tmp_path):
        pol = _ready_policy(tmp_path, n=2)
        seen = [pol.choose({"prompt_ids": [i]})[0].index
                for i in range(4)]
        # with no affinity key (short prompts), dispatch alternates by
        # the since-beat counter
        assert seen == [0, 1, 0, 1]

    def test_session_affinity_sticks(self, tmp_path):
        pol = _ready_policy(tmp_path)
        doc = {"session_id": "alice", "prompt_ids": [1]}
        first, m1 = pol.choose(doc)
        second, m2 = pol.choose(doc)
        assert first.index == second.index
        assert not m1["affinity_hit"] and m2["affinity_hit"]

    def test_prefix_affinity_needs_long_prefix(self, tmp_path):
        pol = _ready_policy(tmp_path, prefix_tokens=8)
        short = {"prompt_ids": list(range(4))}
        assert pol.affinity_key(short) is None
        long_a = {"prompt_ids": list(range(8)) + [99]}
        long_b = {"prompt_ids": list(range(8)) + [42]}
        assert pol.affinity_key(long_a) == pol.affinity_key(long_b)

    def test_affinity_yields_under_load_slack(self, tmp_path):
        pol = _ready_policy(tmp_path, n=2, affinity_slack=2)
        doc = {"session_id": "hot", "prompt_ids": [1]}
        target, _ = pol.choose(doc)
        # pile load onto the sticky target beyond the slack
        target.hb_active = 10
        other, meta = pol.choose(doc)
        assert other.index != target.index
        assert not meta["affinity_hit"]
        # ... and the key is REMAPPED to the new replica
        again, meta2 = pol.choose(doc)
        assert again.index == other.index and meta2["affinity_hit"]

    def test_affinity_skips_ejected_target(self, tmp_path):
        pol = _ready_policy(tmp_path, n=2)
        doc = {"session_id": "s", "prompt_ids": [1]}
        target, _ = pol.choose(doc)
        pol.eject(target, "crashed", now=2.0)
        rep, meta = pol.choose(doc)
        assert rep.index != target.index and not meta["affinity_hit"]

    def test_affinity_map_is_lru_bounded(self, tmp_path):
        pol = _ready_policy(tmp_path, affinity_cap=4)
        for i in range(10):
            pol.choose({"session_id": f"s{i}", "prompt_ids": [1]})
        assert len(pol._affinity) == 4

    def test_exclude_and_exhaustion(self, tmp_path):
        pol = _ready_policy(tmp_path, n=2)
        rep, _ = pol.choose({"prompt_ids": [1]}, exclude={0})
        assert rep.index == 1
        none, _ = pol.choose({"prompt_ids": [1]}, exclude={0, 1})
        assert none is None

    def test_observe_beats_full_cycle(self, tmp_path):
        pol = RouterPolicy(mkreps(tmp_path, 2))
        trs = pol.observe_beats(lambda p: beat(1.0), now=1.0)
        assert [t[0] for t in trs] == ["ready", "ready"]
        trs = pol.observe_beats(lambda p: beat(1.0), now=50.0,
                                stale_s=10.0)
        assert [t[0] for t in trs] == ["ejected", "ejected"]
        assert pol.ready_count == 0
        trs = pol.observe_beats(lambda p: beat(60.0), now=60.0)
        assert [t[0] for t in trs] == ["readmitted", "readmitted"]
        assert pol.ready_count == 2


# ------------------------------------------- cache-aware steering


def _advertise(pol, index, digest, t=2.0):
    """Deliver a fresh heartbeat carrying a hot-prefix advertisement
    (`prefix_roots`) to one replica, exactly as the engine's beat
    extra_fn publishes it."""
    b = beat(t)
    b["prefix_roots"] = [digest]
    pol.replicas[index].observe_beat(b, t)


class TestCacheAwareSteering:
    """The tiered-KV fleet half (serve/hostcache.py): replicas
    advertise hot prefix roots on heartbeats and the dispatch policy
    steers matching no-session requests there — pure host logic over
    fabricated beats, zero jit compiles."""

    # short prompt: BELOW prefix_tokens (32), so affinity_key() is None
    # — only the cache-aware term can see the shared prefix
    IDS = [7, 8, 9, 7]

    def test_heartbeat_advertises_and_clears_roots(self, tmp_path):
        from hyperion_tpu.serve.hostcache import prefix_root_digest

        rep = mkreps(tmp_path, 1)[0]
        d = prefix_root_digest(self.IDS)
        b = beat(1.0)
        b["prefix_roots"] = [d]
        assert rep.observe_beat(b, 1.0) == "ready"
        assert rep.hb_prefix_roots == (d,)
        # a later beat WITHOUT the key clears the advertisement — a
        # restarted (cold) engine must not keep attracting traffic on
        # its dead predecessor's word
        rep.observe_beat(beat(2.0), 2.0)
        assert rep.hb_prefix_roots == ()

    def test_no_session_burst_lands_on_advertiser(self, tmp_path):
        from hyperion_tpu.serve.hostcache import prefix_root_digest

        pol = _ready_policy(tmp_path)
        _advertise(pol, 2, prefix_root_digest(self.IDS))
        rep, meta = pol.choose({"prompt_ids": list(self.IDS)})
        assert rep.index == 2  # NOT the least-loaded tiebreak (0)
        assert meta["cache_hit"] and not meta["affinity_hit"]
        assert not meta["had_key"]  # steered purely by advertisement

    def test_degrades_to_least_loaded_past_slack(self, tmp_path):
        from hyperion_tpu.serve.hostcache import prefix_root_digest

        pol = _ready_policy(tmp_path, affinity_slack=2)
        _advertise(pol, 1, prefix_root_digest(self.IDS))
        pol.replicas[1].hb_active = 10  # advertiser is overloaded
        rep, meta = pol.choose({"prompt_ids": list(self.IDS)})
        assert rep.index == 0 and not meta["cache_hit"]

    def test_no_advertiser_degrades_to_least_loaded(self, tmp_path):
        pol = _ready_policy(tmp_path)
        rep, meta = pol.choose({"prompt_ids": list(self.IDS)})
        assert rep.index == 0 and not meta["cache_hit"]

    def test_steer_seeds_affinity_for_the_burst(self, tmp_path):
        from hyperion_tpu.serve.hostcache import prefix_root_digest

        pol = _ready_policy(tmp_path)
        _advertise(pol, 1, prefix_root_digest(self.IDS))
        doc = {"session_id": "burst", "prompt_ids": list(self.IDS)}
        first, m1 = pol.choose(doc)
        assert first.index == 1 and m1["cache_hit"]
        # the advertisement goes stale (next beat omits it) — the rest
        # of the burst STICKS via the affinity map the steer seeded
        pol.replicas[1].observe_beat(beat(3.0), 3.0)
        second, m2 = pol.choose(doc)
        assert second.index == 1
        assert m2["affinity_hit"] and not m2["cache_hit"]

    def test_affinity_hit_pre_empts_cache_term(self, tmp_path):
        from hyperion_tpu.serve.hostcache import prefix_root_digest

        pol = _ready_policy(tmp_path)
        doc = {"session_id": "s", "prompt_ids": list(self.IDS)}
        target, _ = pol.choose(doc)
        # a DIFFERENT replica starts advertising the same root: the
        # established session must not bounce off its sticky target
        _advertise(pol, (target.index + 1) % 3,
                   prefix_root_digest(self.IDS))
        rep, meta = pol.choose(doc)
        assert rep.index == target.index
        assert meta["affinity_hit"] and not meta["cache_hit"]

    def test_cache_aware_off_disables_the_term(self, tmp_path):
        from hyperion_tpu.serve.hostcache import prefix_root_digest

        pol = _ready_policy(tmp_path, cache_aware=False)
        _advertise(pol, 2, prefix_root_digest(self.IDS))
        rep, meta = pol.choose({"prompt_ids": list(self.IDS)})
        assert rep.index == 0 and not meta["cache_hit"]

    def test_metrics_count_cache_steers(self):
        from hyperion_tpu.serve.metrics import RouterMetrics

        m = RouterMetrics()
        m.on_dispatch(0, affinity_hit=False, had_key=False)
        m.on_dispatch(1, affinity_hit=False, had_key=False,
                      cache_hit=True)
        assert m.summary()["cache_steered"] == 1


class TestReplicaArgvDrift:
    """The child command `replica_argv` builds from the ROUTE parser's
    namespace must parse against the SERVE parser it targets — a flag
    present on one surface but not the other fails here in tier-1, not
    at replica spawn time inside a live fleet."""

    def test_child_argv_parses_against_serve_surface(self, tmp_path):
        from hyperion_tpu.serve.router import replica_argv
        from hyperion_tpu.serve.server import build_parser as serve_parser

        args = build_parser().parse_args(
            ["--ckpt", "m.npz", "--replicas", "2",
             "--base-dir", str(tmp_path), "--host-cache-mb", "8"])
        rep = mkreps(tmp_path, 1)[0]
        argv = replica_argv(args, rep)
        assert argv[:4] == [sys.executable, "-m",
                            "hyperion_tpu.cli.main", "serve"]
        a = serve_parser().parse_args(argv[4:])
        assert a.slots == args.slots
        assert a.queue_capacity == args.queue_capacity
        assert a.host_cache_mb == 8

    def test_tier_off_route_spawns_tier_off_replicas(self, tmp_path):
        from hyperion_tpu.serve.router import replica_argv
        from hyperion_tpu.serve.server import build_parser as serve_parser

        args = build_parser().parse_args(
            ["--ckpt", "m.npz", "--base-dir", str(tmp_path)])
        a = serve_parser().parse_args(
            replica_argv(args, mkreps(tmp_path, 1)[0])[4:])
        assert a.host_cache_mb == 0


# ------------------------------------------------------------- dedup


class TestStreamDedup:
    def test_exactly_once_across_redispatch(self):
        d = StreamDedup()
        # first stream delivers 0..2 then dies
        for i in range(3):
            assert d.admit({"event": "token", "token": i, "i": i})
        # failover stream recomputes from 0: dups dropped, rest pass
        admitted = [i for i in range(6)
                    if d.admit({"event": "token", "token": i, "i": i})]
        assert admitted == [3, 4, 5]
        assert d.delivered == 6

    def test_terminals_always_pass(self):
        d = StreamDedup()
        assert d.admit({"event": "done"})
        assert d.admit({"event": "rejected", "reason": "x"})

    def test_missing_index_falls_back_to_counting(self):
        d = StreamDedup()
        assert d.admit({"event": "token", "token": 7})
        assert d.admit({"event": "token", "token": 8})
        assert d.delivered == 2


# ---------------------------------------------------- client reconnect


class TestClientReconnect:
    def test_connect_rides_through_late_bind(self, tmp_path):
        """The satellite: a server whose socket comes up LATE (a
        supervised restart) must be reconnectable, not fatal."""
        from hyperion_tpu.serve.client import ServeClient

        path = str(tmp_path / "late.sock")

        def bind_late():
            time.sleep(0.5)
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(path)
            srv.listen(1)
            conn, _ = srv.accept()
            conn.close()
            srv.close()

        t = threading.Thread(target=bind_late, daemon=True)
        t0 = time.monotonic()
        t.start()
        c = ServeClient(path, timeout_s=5.0).connect()
        assert time.monotonic() - t0 >= 0.4  # it actually waited
        c.close()
        t.join(timeout=5)

    def test_no_retry_fails_immediately(self, tmp_path):
        from hyperion_tpu.serve.client import ServeClient

        with pytest.raises(FileNotFoundError):
            ServeClient(str(tmp_path / "absent.sock"),
                        retry=None).connect()

    def test_retry_is_bounded(self, tmp_path):
        from hyperion_tpu.serve.client import ServeClient
        from hyperion_tpu.utils.retry import RetryPolicy

        t0 = time.monotonic()
        with pytest.raises(FileNotFoundError):
            ServeClient(str(tmp_path / "absent.sock"),
                        retry=RetryPolicy(tries=3, base_delay_s=0.01,
                                          max_delay_s=0.02,
                                          deadline_s=1.0)).connect()
        assert time.monotonic() - t0 < 2.0

    @staticmethod
    def _cutting_server(path, cut_at=2, n=6):
        """A serve-wire server whose FIRST connection dies after
        `cut_at` tokens; a reconnect speaking the resume verb gets the
        suffix. Returns the thread (daemon, serves two connections)."""

        def serve():
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(path)
            srv.listen(2)
            for life in range(2):
                conn, _ = srv.accept()
                f = conn.makefile("rb")
                doc = json.loads(f.readline())
                if doc.get("kind") == "resume":
                    rid = doc["request_id"]
                    start = int(doc["next_index"])
                else:
                    rid, start = doc["id"], 0
                stop = cut_at if life == 0 else n
                for i in range(start, stop):
                    conn.sendall((json.dumps(
                        {"id": rid, "event": "token", "token": 100 + i,
                         "i": i}) + "\n").encode())
                if life == 1:
                    conn.sendall((json.dumps(
                        {"id": rid, "event": "done",
                         "n_tokens": n}) + "\n").encode())
                conn.close()
            srv.close()

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        return t

    def test_mid_stream_cut_raises_stream_interrupted(self, tmp_path):
        """The satellite bugfix: a wire death mid-stream must never
        read as a short-but-clean stream — without resume the client
        raises StreamInterrupted carrying the next index owed."""
        from hyperion_tpu.serve.client import ServeClient, StreamInterrupted

        path = str(tmp_path / "cut.sock")
        self._cutting_server(path, cut_at=2)
        got = []
        with pytest.raises(StreamInterrupted) as ei:
            with ServeClient(path, timeout_s=5.0) as c:
                for rec in c.stream(id="r1", prompt_ids=[1],
                                    max_new_tokens=6):
                    got.append(rec)
        assert [r["token"] for r in got] == [100, 101]
        assert ei.value.request_id == "r1"
        assert ei.value.next_index == 2
        assert isinstance(ei.value, ConnectionError)  # failover classifiable

    def test_resume_reconnects_and_dedups_to_one_stream(self, tmp_path):
        """resume=True: the same cut turns into reconnect + resume verb
        + index dedup — the caller sees one gapless stream and a real
        terminal event."""
        from hyperion_tpu.serve.client import ServeClient

        path = str(tmp_path / "res.sock")
        self._cutting_server(path, cut_at=2, n=6)
        with ServeClient(path, timeout_s=5.0, resume=True) as c:
            recs = list(c.stream(id="r2", prompt_ids=[1],
                                 max_new_tokens=6))
        toks = [r for r in recs if r.get("event") == "token"]
        assert [r["i"] for r in toks] == list(range(6))
        assert [r["token"] for r in toks] == [100 + i for i in range(6)]
        assert recs[-1]["event"] == "done"


# ------------------------------------------------- fake-replica fleet

# A wire-protocol replica with NO jax: tokens derive deterministically
# from (prompt, seed, index) — the same any-replica-same-stream
# guarantee the real engine gets from seeded sampling — so failover
# dedup is testable at full speed. Writes real heartbeat files.
FAKE_REPLICA = r'''
import json, os, socket, socketserver, sys, threading, time

sock_path, hb_path = sys.argv[1], sys.argv[2]
die_after = int(sys.argv[3]) if len(sys.argv) > 3 else -1
attempt = int(os.environ.get("HYPERION_ATTEMPT", "0") or 0)
# FAKE_ALERT=1: report a firing SLO alert on every beat, the way a
# real engine's obs/slo.py monitor would — exercises the router's
# fleet-alert tally without a real overload
alerts = ["ttft_p99"] if os.environ.get("FAKE_ALERT") else []

def beats():
    n = 0
    while True:
        n += 1
        tmp = hb_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"v": 1, "schema": 1, "run": "fake",
                       "pid": os.getpid(),
                       "phase": "serve", "t_wall": time.time(),
                       "t_mono": time.monotonic(), "beats": n,
                       "active": 0, "queue": 0, "alerts": alerts}, f)
        os.replace(tmp, hb_path)
        time.sleep(0.1)

threading.Thread(target=beats, daemon=True).start()

# inline exposition socket speaking the obs/export.py one-line wire
# protocol (the fake stays import-free): obs.sock next to the
# heartbeat, one JSON snapshot per connection — `obs top` reads the
# fleet through these
def expo():
    obs_path = os.path.join(os.path.dirname(hb_path), "obs.sock")
    class E(socketserver.StreamRequestHandler):
        def handle(self):
            self.wfile.write((json.dumps({
                "v": 1, "kind": "exposition", "pid": os.getpid(),
                "t_wall": time.time(), "role": "engine",
                "phase": "serve", "tick": 7, "active": 1, "slots": 2,
                "occupancy": 0.5, "queue": 0, "draining": False,
                "brownout": False, "blocks_in_use": 3,
                "alerts": alerts,
                "metrics": {"gauges": {"tokens_per_s": 42.0}},
                "windows": {"window_s": 60.0,
                            "histograms": {"ttft_ms": {"count": 5,
                                                       "p99": 12.5}},
                            "counters": {"tokens": {"delta": 60,
                                                    "per_s": 1.0}}},
            }) + "\n").encode())
    class ES(socketserver.ThreadingMixIn, socketserver.UnixStreamServer):
        daemon_threads = True
    if os.path.exists(obs_path):
        os.unlink(obs_path)
    ES(obs_path, E).serve_forever()

threading.Thread(target=expo, daemon=True).start()

def tok(psum, seed, i):
    return (psum * 31 + seed * 7 + i * 13) % 1000

class H(socketserver.StreamRequestHandler):
    def handle(self):
        for raw in self.rfile:
            doc = json.loads(raw)
            start = 0
            if doc.get("kind") == "resume":
                # the wire protocol's resume verb: recompute the SAME
                # deterministic stream, emit only the suffix the client
                # is owed (the real server drops i < next_index the
                # same way)
                req = doc.get("request") or {}
                rid = doc.get("request_id") or doc.get("id")
                start = int(doc.get("next_index", 0))
                doc = dict(req, id=rid)
            rid = doc["id"]; n = int(doc.get("max_new_tokens", 4))
            psum = sum(doc.get("prompt_ids", [])); seed = int(doc.get("seed", 0))
            for i in range(start, n):
                if die_after >= 0 and attempt == 0 \
                        and rid.startswith("kill") and i == die_after:
                    os._exit(1)
                self.wfile.write((json.dumps(
                    {"id": rid, "event": "token",
                     "token": tok(psum, seed, i), "i": i}) + "\n").encode())
                self.wfile.flush()
                time.sleep(0.02)
            self.wfile.write((json.dumps(
                {"id": rid, "event": "done", "n_tokens": n}) + "\n").encode())
            self.wfile.flush()

class S(socketserver.ThreadingMixIn, socketserver.UnixStreamServer):
    daemon_threads = True

if os.path.exists(sock_path):
    os.unlink(sock_path)
S(sock_path, H).serve_forever()
'''


@pytest.fixture()
def fake_replica_script(tmp_path):
    p = tmp_path / "fake_replica.py"
    p.write_text(FAKE_REPLICA)
    return p


class _Recorder:
    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def write(self, rec):
        with self._lock:
            self.records.extend(rec if isinstance(rec, list) else [rec])


def _mk_router(tmp_path, script, n=2, die_after=-1, **over):
    from hyperion_tpu.obs.heartbeat import null_heartbeat
    from hyperion_tpu.obs.trace import null_tracer

    argv = ["--ckpt", "unused.npz", "--replicas", str(n),
            "--base-dir", str(tmp_path / "fleet"), "--no-tokenizer",
            "--dispatch-timeout", "20", "--stream-timeout", "30",
            "--stale-s", "2.0", "--hang-timeout", "0",
            "--drain-timeout", "5"]
    for k, v in over.items():
        argv += [f"--{k.replace('_', '-')}", str(v)]
    args = build_parser().parse_args(argv)

    def child_argv(a, rep):
        cmd = [sys.executable, str(script), rep.socket_path,
               rep.heartbeat_path]
        if rep.index == 0 and die_after >= 0:
            cmd.append(str(die_after))
        return cmd

    return Router(args, null_tracer(), null_heartbeat(),
                  child_argv_fn=child_argv)


def _by_request(records):
    toks, dones = {}, {}
    for r in records:
        if r.get("event") == "token":
            toks.setdefault(r["id"], []).append((r["i"], r["token"]))
        elif r.get("event") == "done":
            dones[r["id"]] = dones.get(r["id"], 0) + 1
    return toks, dones


class TestRouterRuntime:
    """The full router runtime — supervision, monitor, dispatch, relay,
    failover — over jax-free fake replicas. Zero jit compiles."""

    def test_dispatch_completes_and_spreads(self, tmp_path,
                                            fake_replica_script):
        router = _mk_router(tmp_path, fake_replica_script, n=2)
        try:
            router.start()
            assert router.wait_ready(2, timeout_s=20)
            out = _Recorder()
            threads = [router.submit_line(json.dumps(
                {"id": f"q{i}", "prompt_ids": [i, i + 1],
                 "max_new_tokens": 3, "seed": i}), out)
                for i in range(4)]
            for t in threads:
                t.join(timeout=20)
            toks, dones = _by_request(out.records)
            assert set(dones) == {f"q{i}" for i in range(4)}
            assert all(v == 1 for v in dones.values())
            share = router.metrics.summary()["per_replica_dispatched"]
            assert set(share) == {"0", "1"}  # both replicas served
        finally:
            router._hard_stop.set()
            router.shutdown()

    def test_failover_is_exactly_once_and_identical(self, tmp_path,
                                                    fake_replica_script):
        """Replica 0 dies after 3 tokens of the victim stream; the
        relay fails over to replica 1, which recomputes the SAME
        deterministic stream — the client sees indices 0..n-1 exactly
        once, matching an undisturbed request's values."""
        router = _mk_router(tmp_path, fake_replica_script, n=2,
                            die_after=3)
        try:
            router.start()
            assert router.wait_ready(2, timeout_s=20)
            out = _Recorder()
            # pin the victim to replica 0 via session affinity, then a
            # control request with the same payload on replica 1
            t1 = router.submit_line(json.dumps(
                {"id": "kill_1", "session_id": "a",
                 "prompt_ids": [5, 6], "max_new_tokens": 8,
                 "seed": 3}), out)
            t1.join(timeout=30)
            toks, dones = _by_request(out.records)
            assert dones.get("kill_1") == 1
            idx = [i for i, _ in toks["kill_1"]]
            assert idx == list(range(8)), idx  # no dup, no gap
            # deterministic contract: values match the fake's formula
            psum, seed = 5 + 6, 3
            assert [t for _, t in toks["kill_1"]] == [
                (psum * 31 + seed * 7 + i * 13) % 1000 for i in range(8)]
            s = router.metrics.summary()
            assert s["redispatched"] >= 1 and s["ejections"] >= 1
        finally:
            router._hard_stop.set()
            router.shutdown()

    def test_draining_router_rejects_new_work(self, tmp_path,
                                              fake_replica_script):
        router = _mk_router(tmp_path, fake_replica_script, n=1)
        try:
            router.start()
            assert router.wait_ready(1, timeout_s=20)
            router.begin_drain()
            out = _Recorder()
            assert router.submit_line(json.dumps(
                {"id": "late", "prompt_ids": [1],
                 "max_new_tokens": 2}), out) is None
            assert out.records[0]["event"] == "rejected"
            assert out.records[0]["reason"] == "draining"
        finally:
            router._hard_stop.set()
            router.shutdown()

    def test_malformed_line_rejected_not_fatal(self, tmp_path,
                                               fake_replica_script):
        router = _mk_router(tmp_path, fake_replica_script, n=1)
        try:
            router.start()
            out = _Recorder()
            assert router.submit_line("{not json", out) is None
            assert out.records[0]["event"] == "error"
            assert router.metrics.summary()["rejected"] == 1
        finally:
            router._hard_stop.set()
            router.shutdown()


# ---------------------------------------------- router WAL + resume


class TestRouterWal:
    """The dispatch WAL and the resume verb over the jax-free runtime:
    what a router life journals, what the next life recovers, and how a
    client's resume replays exactly the suffix owed."""

    def test_dispatch_hwm_done_journaled_and_clean_close(self, tmp_path,
                                                         fake_replica_script):
        from hyperion_tpu.serve.router_journal import RouterJournal

        router = _mk_router(tmp_path, fake_replica_script, n=1)
        jpath = tmp_path / "fleet" / "router_journal.jsonl"
        try:
            router.start()
            assert router.wait_ready(1, timeout_s=20)
            out = _Recorder()
            t = router.submit_line(json.dumps(
                {"id": "w1", "prompt_ids": [2, 3], "max_new_tokens": 3,
                 "seed": 1}), out)
            t.join(timeout=20)
            recs = [json.loads(line) for line in
                    jpath.read_text().splitlines()]
            kinds = [(r["k"], r.get("id")) for r in recs]
            assert ("dispatch", "w1") in kinds
            assert ("done", "w1") in kinds
            hwms = [r["i"] for r in recs
                    if r["k"] == "hwm" and r["id"] == "w1"]
            assert hwms and hwms[-1] == 3  # every forwarded token marked
            disp = next(r for r in recs if r["k"] == "dispatch")
            assert json.loads(disp["line"])["id"] == "w1"  # wire line rides
        finally:
            router._hard_stop.set()
            router.shutdown()
        # the idle drain close-cleans: nothing for a next life to recover
        orphans, clean = RouterJournal(jpath).recover()
        assert clean and orphans == []

    def test_resume_verb_replays_suffix_exactly_once(self, tmp_path,
                                                     fake_replica_script):
        """A client that received 4 tokens resumes {request_id,
        next_index=4}: the router re-dispatches through the resume verb
        with the dedup floored there — the writer sees ONLY the suffix,
        bit-identical to the deterministic stream."""
        router = _mk_router(tmp_path, fake_replica_script, n=2)
        try:
            router.start()
            assert router.wait_ready(2, timeout_s=20)
            out = _Recorder()
            t = router.submit_line(json.dumps(
                {"id": "v1", "prompt_ids": [5, 6], "max_new_tokens": 8,
                 "seed": 3}), out)
            t.join(timeout=20)
            res = _Recorder()
            t = router.submit_line(json.dumps(
                {"kind": "resume", "request_id": "v1",
                 "next_index": 4}), res)
            assert t is not None
            t.join(timeout=20)
            toks, dones = _by_request(res.records)
            assert dones.get("v1") == 1
            psum, seed = 5 + 6, 3
            assert toks["v1"] == [
                (i, (psum * 31 + seed * 7 + i * 13) % 1000)
                for i in range(4, 8)]
            assert router.metrics.summary()["resumes"] == 1
        finally:
            router._hard_stop.set()
            router.shutdown()

    def test_resume_of_unknown_request_rejected(self, tmp_path,
                                                fake_replica_script):
        router = _mk_router(tmp_path, fake_replica_script, n=1)
        try:
            router.start()
            out = _Recorder()
            assert router.submit_line(json.dumps(
                {"kind": "resume", "request_id": "ghost",
                 "next_index": 2}), out) is None
            assert out.records[0]["event"] == "rejected"
            assert out.records[0]["reason"] == "unknown_request"
        finally:
            router._hard_stop.set()
            router.shutdown()

    def test_resume_falls_back_to_client_carried_request(self, tmp_path,
                                                         fake_replica_script):
        """A router life that never saw the request (fresh process, no
        WAL record) still answers a resume that carries the original
        request body — the client's copy is the source of last resort."""
        router = _mk_router(tmp_path, fake_replica_script, n=1)
        try:
            router.start()
            assert router.wait_ready(1, timeout_s=20)
            out = _Recorder()
            t = router.submit_line(json.dumps(
                {"kind": "resume", "request_id": "c1", "next_index": 2,
                 "request": {"prompt_ids": [7, 8], "max_new_tokens": 5,
                             "seed": 2}}), out)
            assert t is not None
            t.join(timeout=20)
            toks, dones = _by_request(out.records)
            assert dones.get("c1") == 1
            psum, seed = 7 + 8, 2
            assert toks["c1"] == [
                (i, (psum * 31 + seed * 7 + i * 13) % 1000)
                for i in range(2, 5)]
        finally:
            router._hard_stop.set()
            router.shutdown()

    def test_next_life_recovers_orphans_from_wal(self, tmp_path,
                                                 fake_replica_script):
        """A WAL a dead router life left behind (dispatch, hwm 3, no
        terminal) re-dispatches in jsonl mode floored at the journaled
        hwm — the union across lives is gapless and duplicate-free."""
        from hyperion_tpu.serve.router_journal import RouterJournal

        jpath = tmp_path / "fleet" / "router_journal.jsonl"
        jpath.parent.mkdir(parents=True)
        dead = RouterJournal(jpath)
        line = json.dumps({"id": "o1", "prompt_ids": [5, 6],
                           "max_new_tokens": 8, "seed": 3})
        dead.dispatch("o1", line=line, replica=0, session=None)
        dead.hwm("o1", 3)
        dead.close()  # handle closed, NO clean marker — the crash shape
        router = _mk_router(tmp_path, fake_replica_script, n=1)
        try:
            router.start()
            assert router.wait_ready(1, timeout_s=20)
            out = _Recorder()
            assert router.recover_journal(out) == 1
            deadline = time.monotonic() + 20
            while not any(r.get("event") == "done"
                          for r in out.records):
                assert time.monotonic() < deadline
                time.sleep(0.05)
            toks, dones = _by_request(out.records)
            assert dones.get("o1") == 1
            psum, seed = 5 + 6, 3
            assert toks["o1"] == [
                (i, (psum * 31 + seed * 7 + i * 13) % 1000)
                for i in range(3, 8)]
            s = router.metrics.summary()
            assert s["orphans_recovered"] == 1
        finally:
            router._hard_stop.set()
            router.shutdown()

    def test_socket_mode_parks_orphans_for_client_resume(self, tmp_path,
                                                         fake_replica_script):
        """Socket-mode recovery must NOT pre-emptively re-dispatch (it
        would race the reconnecting client): orphans park until the
        client's resume verb names them, and the client's own index —
        not the journaled hwm — floors the replay."""
        from hyperion_tpu.serve.router_journal import RouterJournal

        jpath = tmp_path / "fleet" / "router_journal.jsonl"
        jpath.parent.mkdir(parents=True)
        dead = RouterJournal(jpath)
        line = json.dumps({"id": "p1", "prompt_ids": [4, 4],
                           "max_new_tokens": 6, "seed": 1})
        dead.dispatch("p1", line=line, replica=0, session=None)
        dead.hwm("p1", 4)  # hwm may run one AHEAD of the client
        dead.close()
        router = _mk_router(tmp_path, fake_replica_script, n=1)
        try:
            router.start()
            assert router.wait_ready(1, timeout_s=20)
            assert router.recover_journal(None) == 1  # socket mode: park
            out = _Recorder()
            t = router.submit_line(json.dumps(
                {"kind": "resume", "request_id": "p1",
                 "next_index": 3}), out)  # client is BEHIND the hwm
            assert t is not None
            t.join(timeout=20)
            toks, dones = _by_request(out.records)
            assert dones.get("p1") == 1
            assert [i for i, _ in toks["p1"]] == [3, 4, 5]
        finally:
            router._hard_stop.set()
            router.shutdown()


# ------------------------------------------------- socket load driver


class TestLoadgenSocket:
    def test_workload_is_shared_with_inprocess_driver(self):
        from hyperion_tpu.serve.loadgen import LoadSpec, build_workload

        spec = LoadSpec(n_requests=6, seed=4, shared_prefix_tokens=8)
        a_arr, a_reqs = build_workload(spec)
        b_arr, b_reqs = build_workload(spec)
        assert list(a_arr) == list(b_arr)
        for x, y in zip(a_reqs, b_reqs):
            assert x.id == y.id and x.seed == y.seed
            assert x.max_new_tokens == y.max_new_tokens
            assert x.prompt_ids.tolist() == y.prompt_ids.tolist()
        # shared prefix really is shared
        p0 = a_reqs[0].prompt_ids[:8].tolist()
        assert all(r.prompt_ids[:8].tolist() == p0 for r in a_reqs)

    def test_socket_mode_drives_a_live_wire(self, tmp_path,
                                            fake_replica_script):
        """The satellite: loadgen's socket-target mode against a real
        unix-socket server (the fake replica speaks the exact serve
        wire protocol)."""
        from hyperion_tpu.serve.loadgen import LoadSpec, run_load_socket

        sock = str(tmp_path / "lg.sock")
        hb = str(tmp_path / "lg_hb.json")
        proc = subprocess.Popen(
            [sys.executable, str(fake_replica_script), sock, hb])
        try:
            t0 = time.monotonic()
            while not os.path.exists(sock):
                assert proc.poll() is None
                assert time.monotonic() - t0 < 10
                time.sleep(0.05)
            spec = LoadSpec(n_requests=5, rate_hz=50.0,
                            prompt_lens=(2, 3), max_new=(2, 3), seed=1)
            rep = run_load_socket(sock, spec, request_timeout_s=30)
            assert rep["mode"] == "socket"
            assert rep["completed"] == 5 and rep["rejected"] == 0
            assert rep["tokens"] > 0 and rep["tokens_per_s"] > 0
            assert rep["ttft_p50_ms"] is not None
        finally:
            proc.terminate()
            proc.wait(timeout=10)


# ----------------------------------------------- obs integration


class TestObsIntegration:
    def _fleet_dir(self, tmp_path, stale_age=400.0):
        base = tmp_path / "fleet"
        now = time.time()
        (base / "replica_0").mkdir(parents=True)
        (base / "replica_1").mkdir(parents=True)
        (base / "replica_0" / "heartbeat.json").write_text(json.dumps(
            {"v": 1, "run": "serve_r0_1", "pid": 11, "phase": "serve",
             "t_wall": now - stale_age, "t_mono": 0.0, "beats": 5,
             "active": 2, "queue": 1, "attempt": 0, "replica": 0}))
        (base / "replica_1" / "heartbeat.json").write_text(json.dumps(
            {"v": 1, "run": "serve_r1_1", "pid": 12, "phase": "done",
             "t_wall": now - 1.0, "t_mono": 0.0, "beats": 9,
             "active": 0, "queue": 0, "attempt": 0, "replica": 1}))
        recs = [
            {"kind": "event", "name": "router_start", "run": "route_1",
             "t_wall": now - 500.0, "t_mono": 0.0, "replicas": 2},
            {"kind": "event", "name": "replica_ejected", "run": "route_1",
             "t_wall": now - stale_age, "t_mono": 1.0, "replica": 0,
             "reason": "heartbeat stale"},
            {"kind": "event", "name": "router_end", "run": "route_1",
             "t_wall": now - 0.5, "t_mono": 2.0, "dispatched": 7,
             "completed": 7},
        ]
        (base / "telemetry.jsonl").write_text(
            "".join(json.dumps(r) + "\n" for r in recs))
        return base

    def test_doctor_renders_fleet_and_names_dead_replica(self, tmp_path):
        from hyperion_tpu.obs.doctor import diagnose, render_markdown

        base = self._fleet_dir(tmp_path)
        d = diagnose(base)
        assert d["verdict"] == "healthy"  # the ROUTER drained cleanly
        states = {r["replica"]: r["state"] for r in d["fleet"]}
        assert states == {"0": "dead", "1": "done"}
        assert d["fleet_incidents"] and "replica 0" in d["fleet_incidents"][0]
        assert "fleet: replica 0 DEAD" in d["reason"]
        row0 = next(r for r in d["fleet"] if r["replica"] == "0")
        assert row0["active"] == 2 and row0["queue"] == 1
        assert row0["ejections"] == 1
        md = render_markdown(d)
        assert "| replica 0 |" in md and "**dead**" in md
        assert "| replica 1 |" in md

    def test_doctor_quiet_when_fleet_healthy(self, tmp_path):
        from hyperion_tpu.obs.doctor import diagnose

        base = self._fleet_dir(tmp_path, stale_age=1.0)
        d = diagnose(base)
        assert not d["fleet_incidents"]
        assert all(r["state"] in ("beating", "done") for r in d["fleet"])

    def test_diff_gates_serving_scale_keys(self, tmp_path):
        from hyperion_tpu.obs import diff as obs_diff

        def line(tps, scaleup, fair, aff):
            return {"metric": "matmul_bf16_8192_tflops", "value": 100.0,
                    "serving_scale": {"tokens_per_s": tps,
                                      "scaleup": scaleup,
                                      "fairness": fair,
                                      "affinity_hit_rate": aff}}

        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(line(700.0, 1.8, 1.0, 0.8)))
        b.write_text(json.dumps(line(400.0, 1.1, 0.4, 0.2)))
        d = obs_diff.diff(obs_diff.load_summary(a),
                          obs_diff.load_summary(b))
        assert {"serve_scale_tokens_per_s", "serve_scale_scaleup",
                "serve_scale_fairness",
                "serve_affinity_hit_rate"} <= set(d["regressions"])

    def test_timeline_tags_replica_runs(self):
        from hyperion_tpu.obs.timeline import replica_of_run

        assert replica_of_run("serve_r3_1754000000") == 3
        assert replica_of_run("serve_1754000000") is None
        assert replica_of_run("route_1754000000") is None

    def test_smoke_script_route_invocation_parses(self):
        """Flag-drift guard (the capture-script pattern): the smoke
        script's `hyperion route` invocation must parse against the
        real router arg surface."""
        import re
        import shlex

        script = (REPO / "scripts" / "serve_smoke.sh").read_text()
        script = re.sub(r"\\\n\s*", " ", script)
        calls = re.findall(
            r"python -m hyperion_tpu\.cli\.main route\s+(.*)", script)
        assert len(calls) >= 2, (
            "serve_smoke.sh lost a router invocation (expected the "
            "crash drill AND the live obs top fleet)")
        parsed = []
        for call in calls:
            # strip shell artifacts: stderr redirects (` 2> file`),
            # stdout redirects, pipes, backgrounding
            call = re.split(r"\s2>", call)[0].split(">")[0]
            toks = [t for t in shlex.split(call) if t not in ("|", "&")]
            args = build_parser().parse_args(
                [re.sub(r"\$\{?\w+\}?", "x", t) for t in toks])
            assert args.replicas >= 2
            parsed.append(args)
        # the crash drill still carries its chaos plan, and the live
        # fleet probe carries an SLO target for the alert plane
        assert any(a.replica_chaos for a in parsed)
        assert any(a.slo_ttft_p99_ms > 0 for a in parsed)


# ------------------------------------------------- acceptance drill


class TestRouteAcceptance:
    @pytest.mark.slow
    def test_route_kill_one_replica_bit_identical(self, tmp_path):
        """The PR-9 acceptance subprocess test: `hyperion route` over 2
        supervised replicas under seeded load, replica 0 hard-crashed
        (os._exit via chaos crash@tick) mid-stream. Every admitted
        request completes with temp-0 output bit-identical to an
        uninterrupted single-engine run, no client stream carries a
        duplicate token, and the dead replica's restart shows journal
        replay on its telemetry.

        Marked slow: the supervised-ROUTER drill below kills a layer
        ABOVE this one and exercises the same replica failover + journal
        machinery on its way; this drill stays for `-m slow` depth."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from hyperion_tpu.checkpoint.io import export_gathered
        from hyperion_tpu.infer.generate import generate
        from hyperion_tpu.models.llama import Llama, llama_tiny_config

        model = Llama(llama_tiny_config(max_len=64))
        variables = {"params": model.init_params(jax.random.key(0),
                                                 seq=8)}
        ckpt = tmp_path / "llama.npz"
        export_gathered(ckpt, variables["params"])
        prompts = [np.asarray([3 + i, 4, 5, 6, 7, 8], np.int32)
                   for i in range(6)]
        budget = 10
        lines = "".join(
            json.dumps({"id": f"a{i}", "prompt_ids": p.tolist(),
                        "max_new_tokens": budget}) + "\n"
            for i, p in enumerate(prompts))
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        env.pop("HYPERION_TELEMETRY", None)
        base = tmp_path / "fleet"
        # --min-ready 2: dispatch must spread over BOTH replicas before
        # the drill fires, so replica 0 always holds streams when it
        # dies; the short stdin tail keeps EOF from racing the crash
        r = subprocess.run(
            ["bash", "-c",
             f"(cat; sleep 2) | {sys.executable} -m "
             "hyperion_tpu.cli.main route --replicas 2 --min-ready 2 "
             f"--ckpt {ckpt} --no-tokenizer --base-dir {base} "
             "--max-len 64 --slots 2 --warmup-lens 8 "
             "--replica-heartbeat-every 1 "
             "--replica-chaos 0:crash@tick=2"],
            input=lines, env=env, capture_output=True, text=True,
            timeout=360, cwd=str(REPO),
        )
        assert r.returncode == 0, r.stderr[-3000:]

        toks: dict[str, list] = {}
        dones: dict[str, int] = {}
        for line in r.stdout.splitlines():
            rec = json.loads(line)  # router stdout carries ONLY wire
            if rec.get("event") == "token":
                toks.setdefault(rec["id"], []).append(
                    (rec["i"], rec["token"]))
            elif rec.get("event") == "done":
                dones[rec["id"]] = dones.get(rec["id"], 0) + 1
        # every admitted request: exactly one done, gapless dup-free
        # indices, tokens bit-identical to the single-engine oracle
        assert set(dones) == {f"a{i}" for i in range(6)}
        assert all(v == 1 for v in dones.values())
        for i, p in enumerate(prompts):
            got = toks[f"a{i}"]
            assert [ix for ix, _ in got] == list(range(budget)), got
            ref = np.asarray(generate(
                model, variables, jnp.asarray(p)[None],
                budget))[0].tolist()
            assert [t for _, t in got] == ref, f"a{i} diverged"
        # the crash really happened, and failover is on the router's
        # own stream
        assert "crash@tick" in r.stderr
        route = (base / "telemetry.jsonl").read_text()
        assert '"route_redispatch"' in route
        # the dead replica's journal still owes its in-flight requests
        # (failover delivered them, but ITS WAL cannot know): drain it
        # exactly as a supervised restart would — deterministic replay
        # evidence on the replica's own telemetry stream, independent
        # of how the in-run restart raced the router's drain window
        env2 = dict(env,
                    HYPERION_TELEMETRY=str(
                        base / "replica_0" / "telemetry.jsonl"))
        r2 = subprocess.run(
            [sys.executable, "-m", "hyperion_tpu.cli.main", "serve",
             "--ckpt", str(ckpt), "--no-tokenizer",
             "--max-len", "64", "--slots", "2", "--warmup-lens", "8",
             "--journal", str(base / "replica_0" / "journal.jsonl")],
            stdin=subprocess.DEVNULL, env=env2, capture_output=True,
            text=True, timeout=240, cwd=str(REPO))
        assert r2.returncode == 0, r2.stderr[-2000:]
        r0 = (base / "replica_0" / "telemetry.jsonl").read_text()
        recs = [json.loads(line) for line in r0.splitlines()
                if line.strip()]
        assert any(rec.get("name") == "journal_replayed"
                   and rec.get("resumed", 0) >= 1 for rec in recs)
        assert any(rec.get("name") == "serve_prefill"
                   and rec.get("resumed") for rec in recs)
        # ... and the drained journal owes nothing for a third life
        from hyperion_tpu.serve.journal import RequestJournal

        assert RequestJournal(
            base / "replica_0" / "journal.jsonl").pending_count() == 0

    def test_route_supervised_router_crash_resume(self, tmp_path):
        """THE acceptance drill for the router-SPOF tentpole:
        `hyperion route --supervise` over 2 REAL replicas, the router
        itself hard-exited mid-stream by chaos `crash@dispatch=3` while
        4 auto-resuming clients hold streams. The supervisor restarts
        the router; the new life re-adopts the still-live replicas
        (no respawn, no recompile), recovers the dispatch WAL, and
        answers the clients' resume verbs — every stream completes
        temp-0 bit-identical to the lone-engine `generate` oracle with
        gapless, duplicate-free indices across both router lives."""
        import signal as signal_mod

        import jax
        import jax.numpy as jnp
        import numpy as np

        from hyperion_tpu.checkpoint.io import export_gathered
        from hyperion_tpu.infer.generate import generate
        from hyperion_tpu.models.llama import Llama, llama_tiny_config
        from hyperion_tpu.serve.client import ServeClient

        model = Llama(llama_tiny_config(max_len=64))
        variables = {"params": model.init_params(jax.random.key(0),
                                                 seq=8)}
        ckpt = tmp_path / "llama.npz"
        export_gathered(ckpt, variables["params"])
        prompts = [np.asarray([3 + i, 4, 5, 6, 7, 8], np.int32)
                   for i in range(4)]
        budget = 10
        oracle = {
            f"s{i}": np.asarray(generate(
                model, variables, jnp.asarray(p)[None],
                budget))[0].tolist()
            for i, p in enumerate(prompts)}

        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        env.pop("HYPERION_TELEMETRY", None)
        base = tmp_path / "fleet"
        sock = str(tmp_path / "route.sock")
        out_log = open(tmp_path / "route.out", "wb")
        err_log = open(tmp_path / "route.err", "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "hyperion_tpu.cli.main", "route",
             "--supervise", "--replicas", "2", "--min-ready", "2",
             "--ckpt", str(ckpt), "--no-tokenizer",
             "--base-dir", str(base), "--max-len", "64", "--slots", "2",
             "--warmup-lens", "8", "--replica-heartbeat-every", "1",
             "--socket", sock, "--chaos", "crash@dispatch=3"],
            env=env, cwd=str(REPO), stdout=out_log, stderr=err_log,
            start_new_session=True)
        try:
            t0 = time.monotonic()
            while True:
                probe = socket.socket(socket.AF_UNIX,
                                      socket.SOCK_STREAM)
                probe.settimeout(1.0)
                try:
                    probe.connect(sock)
                    probe.close()
                    break
                except OSError:
                    probe.close()
                    assert proc.poll() is None, "supervisor died early"
                    assert time.monotonic() - t0 < 240, \
                        "router socket never came up"
                    time.sleep(0.2)

            results: dict[str, dict] = {}
            errors: list[str] = []

            def drive(i):
                try:
                    with ServeClient(sock, timeout_s=120.0,
                                     resume=True) as c:
                        results[f"s{i}"] = c.generate(
                            id=f"s{i}",
                            prompt_ids=prompts[i].tolist(),
                            max_new_tokens=budget)
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(f"s{i}: {e!r}")

            threads = [threading.Thread(target=drive, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=240)
            assert not errors, f"streams failed: {errors}"
            assert not any(t.is_alive() for t in threads), \
                "a resuming client hung"
            for rid, ref in oracle.items():
                res = results[rid]
                assert res["final"]["event"] == "done", (rid, res)
                assert res["tokens"] == ref, (
                    f"{rid} diverged across router lives")

            # the drill really happened: chaos fired (router stdout),
            # the supervisor restarted the router (its stderr), and the
            # new life ADOPTED the surviving replicas and answered
            # resumes (control-plane telemetry)
            deadline = time.monotonic() + 30
            while True:
                out_txt = (tmp_path / "route.out").read_text(
                    errors="replace")
                err_txt = (tmp_path / "route.err").read_text(
                    errors="replace")
                if "crash@dispatch=3" in out_txt \
                        and "route-supervisor] router exit" in err_txt:
                    break
                assert time.monotonic() < deadline, (
                    f"no crash/restart evidence:\n{err_txt[-2000:]}")
                time.sleep(0.5)
            names = []
            for line in (base / "telemetry.jsonl").read_text() \
                    .splitlines():
                try:
                    names.append(json.loads(line).get("name"))
                except json.JSONDecodeError:
                    pass
            assert names.count("replica_adopted") >= 2, (
                "restarted router respawned instead of adopting: "
                f"{names.count('replica_adopted')}")
            assert names.count("route_resume") >= 1, names
            assert "route_orphan_recovered" in names, names

            # graceful drain: TERM the router CHILD (heartbeat pid);
            # exit 0 stops the supervisor loop
            hb = json.loads((base / "heartbeat.json").read_text())
            os.kill(int(hb["pid"]), signal_mod.SIGTERM)
            assert proc.wait(timeout=60) == 0
        finally:
            out_log.close()
            err_log.close()
            try:
                os.killpg(proc.pid, signal_mod.SIGKILL)
            except (OSError, ProcessLookupError):
                pass


# ------------------------------------------- live fleet observability


class TestLiveFleetObservability:
    """`obs top` + fleet alert surfacing over the REAL router runtime
    (fake replicas speaking the exposition wire protocol) — zero jit
    compiles, like the rest of the runtime tests."""

    def test_obs_top_reads_running_fleet_sockets(self, tmp_path,
                                                 fake_replica_script):
        from hyperion_tpu.obs.top import sample_all

        router = _mk_router(tmp_path, fake_replica_script, n=2)
        try:
            router.start()
            assert router.wait_ready(2, timeout_s=20)
            deadline = time.monotonic() + 20
            while True:
                rows = sample_all(tmp_path / "fleet")
                live = [r for r in rows if r["state"] == "live"]
                if len(live) == 2:
                    break
                assert time.monotonic() < deadline, rows
                time.sleep(0.2)
            for r in live:
                # the live columns come off the exposition socket, not
                # the heartbeat file
                assert r["source"] == "socket"
                assert r["occupancy"] == 0.5
                assert r["ttft_p99_ms"] == 12.5
                assert r["tokens_per_s"] == 1.0
                assert r["blocks_in_use"] == 3
                assert r["alerts"] == []
        finally:
            router._hard_stop.set()
            router.shutdown()
        # fleet stopped: the sockets stop answering and the SAME
        # sampler degrades every row to its heartbeat file
        rows = sample_all(tmp_path / "fleet", stale_s=3600.0)
        assert rows and all(r["source"] != "socket" for r in rows)

    def test_router_tallies_fleet_alerts(self, tmp_path,
                                         fake_replica_script,
                                         monkeypatch):
        monkeypatch.setenv("FAKE_ALERT", "1")
        router = _mk_router(tmp_path, fake_replica_script, n=2)
        try:
            router.start()
            assert router.wait_ready(2, timeout_s=20)
            deadline = time.monotonic() + 10
            while router.metrics.summary()["fleet_alerts_raised"] < 2:
                assert time.monotonic() < deadline, \
                    router.metrics.summary()
                time.sleep(0.1)
            s = router.metrics.summary()
            assert s["fleet_alerts_raised"] == 2   # one raise per replica
            assert s["fleet_alerts_active"] == 2
            exp = router.exposition()
            assert exp["role"] == "router"
            assert sorted(exp["alerts"]) == ["r0:ttft_p99",
                                             "r1:ttft_p99"]
            assert all(r["alerts"] == ["ttft_p99"]
                       for r in exp["replicas"])
            # a PERSISTING alert never re-counts on later beats — the
            # tally is raises, not beat-observations
            time.sleep(0.5)
            assert router.metrics.summary()["fleet_alerts_raised"] == 2
        finally:
            router._hard_stop.set()
            router.shutdown()

    def test_dead_replica_alert_stops_counting(self, tmp_path):
        """A ghost must not page: an ejected/dead replica's
        last-reported alert leaves the live fleet tally (the dead
        replica itself is the incident), and a readmitted replica
        still alerting counts as a NEW raise."""
        router = _mk_router(tmp_path, tmp_path / "unused.py", n=2)
        r0, r1 = router.replicas
        r0.state = READY
        r0.hb_alerts = ("ttft_p99",)
        r1.state = READY
        assert router._sweep_fleet_alerts() == ["r0:ttft_p99"]
        assert router.metrics.summary()["fleet_alerts_raised"] == 1
        assert router.exposition()["alerts"] == ["r0:ttft_p99"]
        # the replica dies: its stale alarm stops counting fleet-wide
        r0.state = EJECTED
        assert router._sweep_fleet_alerts() == []
        assert router.exposition()["alerts"] == []
        # ...but the per-replica evidence row keeps the last word
        row0 = router.exposition()["replicas"][0]
        assert row0["state"] == EJECTED
        assert row0["alerts"] == ["ttft_p99"]
        # readmitted and still alerting: a new observation epoch —
        # honestly re-raised, not deduped against the old life
        r0.state = READY
        assert router._sweep_fleet_alerts() == ["r0:ttft_p99"]
        assert router.metrics.summary()["fleet_alerts_raised"] == 2

    def test_route_slo_monitor_fires_on_fleet_rejects(self, tmp_path):
        """The router-level burn-rate monitor (route_ prefix) over its
        own windowed relay outcomes — pure host logic, no children."""
        from hyperion_tpu.obs.registry import MetricsRegistry
        from hyperion_tpu.obs.slo import SLOMonitor, SLOTarget
        from hyperion_tpu.serve.router import _route_window_value

        reg = MetricsRegistry()
        mon = SLOMonitor(
            (SLOTarget("route_reject_rate", "reject_rate", 0.1),),
            reg, fast_s=10.0, slow_s=30.0, eval_every_s=0.0,
            value_fn=_route_window_value)
        for _ in range(8):
            reg.counter("route_completed").inc()
        assert mon.evaluate() == []          # 0% rejects: quiet
        for _ in range(4):
            reg.counter("route_rejected").inc()
        (tr,) = mon.evaluate()
        assert tr["kind"] == "raised" and tr["alert"] == "route_reject_rate"
        assert tr["fast"] == pytest.approx(1 / 3)

# ------------------------------------------------- acting on alerts


class TestActingRouter:
    """PR 14: the router ACTS on the alerts it tallies — steers
    interactive traffic off TTFT-burning replicas, orders batch-class
    brownouts, and scales standbys — all as pure host logic over
    fabricated heartbeats. Zero jit compiles, zero child processes."""

    def test_steered_replica_skipped_for_interactive_only(self, tmp_path):
        pol = _ready_policy(tmp_path, n=2)
        pol.set_steered(pol.replicas[0], True)
        rep, meta = pol.choose({"prompt_ids": [1]})
        assert rep.index == 1 and meta["steered_away"]
        # batch traffic still flows to the steered replica (it is the
        # least-loaded one — interactive was just moved off it)
        rep_b, meta_b = pol.choose({"class": "batch",
                                    "prompt_ids": [1]})
        assert rep_b.index == 0 and not meta_b["steered_away"]
        # every replica steered: interactive falls back to the full
        # ready set rather than refusing service
        pol.set_steered(pol.replicas[1], True)
        rep2, meta2 = pol.choose({"prompt_ids": [2]})
        assert rep2 is not None and not meta2["steered_away"]

    def test_sweep_steers_on_ttft_alert_with_hysteresis(self, tmp_path):
        router = _mk_router(tmp_path, tmp_path / "unused.py", n=2)
        r0, r1 = router.replicas
        r0.state = READY
        r1.state = READY
        r0.hb_alerts = ("ttft_p99",)
        assert router._sweep_actions() == 1
        assert r0.steered and not r1.steered
        s = router.metrics.summary()
        assert s["steers"] == 1 and s["steered_now"] == 1
        assert s["class_brownouts"] == 1  # ordered (no ack — no child)
        assert router.exposition()["act"]["steered"] == [0]
        # still burning: steering is idempotent, no double count
        assert router._sweep_actions() == 1
        assert router.metrics.summary()["steers"] == 1
        # alert clears: unsteer only after N CONSECUTIVE clean sweeps
        r0.hb_alerts = ()
        router._sweep_actions()
        router._sweep_actions()
        assert r0.steered  # 2 of 3
        r0.hb_alerts = ("ttft_p99",)  # relapse resets the count
        router._sweep_actions()
        r0.hb_alerts = ()
        router._sweep_actions()
        router._sweep_actions()
        assert r0.steered
        router._sweep_actions()  # third consecutive clean sweep
        assert not r0.steered
        s = router.metrics.summary()
        assert s["unsteers"] == 1 and s["steered_now"] == 0

    def test_ejected_silence_is_not_recovery(self, tmp_path):
        router = _mk_router(tmp_path, tmp_path / "unused.py", n=2,
                            steer_clear_sweeps=1)
        r0, _ = router.replicas
        r0.state = READY
        r0.hb_alerts = ("ttft_p99",)
        router._sweep_actions()
        assert r0.steered
        # the replica dies with the alert latched: its silence must
        # not count toward unsteering
        r0.state = EJECTED
        r0.hb_alerts = ()
        for _ in range(3):
            router._sweep_actions()
        assert r0.steered
        r0.state = READY  # readmitted and clean: NOW it unsteers
        router._sweep_actions()
        assert not r0.steered

    def test_scale_governor_spawns_and_retires_standby(self, tmp_path):
        router = _mk_router(tmp_path, tmp_path / "unused.py", n=2,
                            max_replicas=3)
        router._supervise_one = lambda rep: None  # no real children
        r0, r1 = router.replicas
        r0.state = READY
        r1.state = READY
        assert router._scale_gov is not None
        r0.hb_alerts = ("ttft_p99",)
        router._sweep_actions()  # burning=1: governor enters, scale up
        assert len(router.replicas) == 3
        standby = router.replicas[2]
        assert standby.standby and not standby.retiring
        s = router.metrics.summary()
        assert s["scale_up"] == 1 and s["scale_down"] == 0
        assert router.exposition()["act"]["fleet"] == 3
        # burn persists: no second spawn (governor already entered)
        router._sweep_actions()
        assert len(router.replicas) == 3
        assert router.metrics.summary()["scale_up"] == 1
        # burn clears: governor exits, the standby retires
        r0.hb_alerts = ()
        router._sweep_actions()
        assert standby.retiring
        assert standby.state == EJECTED
        s = router.metrics.summary()
        assert s["scale_down"] == 1

    def test_no_act_flag_disables_the_acting_half(self, tmp_path):
        router = _mk_router(tmp_path, tmp_path / "unused.py", n=2)
        router._act = False
        r0, _ = router.replicas
        r0.state = READY
        r0.hb_alerts = ("ttft_p99",)
        assert router._sweep_actions() == 0
        assert not r0.steered
        assert router.metrics.summary()["steers"] == 0
