"""Generation: KV-cache decode correctness vs full forward, sampling,
eos handling, GQA, and the recompute fallback for cache-less models."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.infer import generate, generate_recompute, sample_token
from hyperion_tpu.models.llama import Llama, init_cache, llama_tiny_config
from hyperion_tpu.models.transformer_lm import TransformerLM, simple_lm_config

B, P = 2, 6


@pytest.fixture(scope="module")
def llama():
    model = Llama(llama_tiny_config(max_len=32))
    params = model.init_params(jax.random.key(0), seq=8)
    return model, {"params": params}


@pytest.fixture(scope="module")
def prompt():
    return jnp.asarray(
        np.random.default_rng(0).integers(1, 250, (B, P)), jnp.int32
    )


class TestKVCache:
    def test_prefill_logits_match_full_forward(self, llama, prompt):
        model, variables = llama
        full = model.apply(variables, prompt)
        cache = init_cache(model.cfg, B)
        pre, cache = model.apply(variables, prompt, cache=cache, cache_index=0)
        np.testing.assert_allclose(
            np.asarray(pre), np.asarray(full), atol=2e-5, rtol=2e-4
        )

    @pytest.mark.slow
    def test_stepwise_decode_matches_full_forward(self, llama, prompt):
        """Teacher-forced: feeding gold tokens one at a time through the
        cache must reproduce the full forward's logits per position."""
        model, variables = llama
        full = model.apply(variables, prompt)
        cache = init_cache(model.cfg, B)
        logits0, cache = model.apply(
            variables, prompt[:, :1], cache=cache, cache_index=0
        )
        np.testing.assert_allclose(
            np.asarray(logits0[:, 0]), np.asarray(full[:, 0]),
            atol=2e-5, rtol=2e-4,
        )
        for t in range(1, P):
            lt, cache = model.apply(
                variables, prompt[:, t:t + 1], cache=cache,
                cache_index=jnp.int32(t),
            )
            np.testing.assert_allclose(
                np.asarray(lt[:, 0]), np.asarray(full[:, t]),
                atol=3e-5, rtol=3e-4,
            )

    def test_gqa_decode(self):
        cfg = llama_tiny_config(n_heads=4, n_kv_heads=2, max_len=16)
        model = Llama(cfg)
        params = model.init_params(jax.random.key(1), seq=8)
        ids = jnp.asarray(
            np.random.default_rng(1).integers(1, 250, (1, 5)), jnp.int32
        )
        full = model.apply({"params": params}, ids)
        cache = init_cache(cfg, 1)
        pre, _ = model.apply(
            {"params": params}, ids, cache=cache, cache_index=0
        )
        np.testing.assert_allclose(
            np.asarray(pre), np.asarray(full), atol=2e-5, rtol=2e-4
        )


class TestGenerate:
    def test_greedy_cache_equals_recompute(self, llama, prompt):
        """The two decoding strategies must emit identical greedy
        continuations — the strongest cross-check of the cache path."""
        model, variables = llama
        out_c = generate(model, variables, prompt, 8)
        out_r = generate_recompute(model, variables, prompt, 8)
        np.testing.assert_array_equal(np.asarray(out_c), np.asarray(out_r))

    def test_eos_stops_row(self, llama, prompt):
        model, variables = llama
        ref = generate(model, variables, prompt, 8)
        eos = int(ref[0, 2])  # force eos at the 3rd emitted token of row 0
        out = generate(model, variables, prompt, 8, eos_id=eos, pad_id=0)
        row = np.asarray(out[0])
        hit = int(np.argmax(row == eos))
        assert (row[hit + 1:] == 0).all()

    def test_temperature_sampling_in_vocab(self, llama, prompt):
        model, variables = llama
        out = generate(
            model, variables, prompt, 6, temperature=0.8, top_k=12,
            rng=jax.random.key(7),
        )
        a = np.asarray(out)
        assert a.shape == (B, 6)
        assert (0 <= a).all() and (a < model.cfg.vocab_size).all()

    def test_length_guard(self, llama, prompt):
        model, variables = llama
        with pytest.raises(ValueError, match="max_len"):
            generate(model, variables, prompt, 1000)

    def test_recompute_works_for_transformer_lm(self):
        cfg = simple_lm_config(
            vocab_size=128, d_model=32, n_heads=4, n_layers=2, ff_dim=64,
            max_len=24, dropout=0.0,
        )
        model = TransformerLM(cfg)
        params = model.init_params(jax.random.key(0))
        ids = jnp.asarray(
            np.random.default_rng(2).integers(1, 120, (2, 5)), jnp.int32
        )
        out = generate_recompute(model, {"params": params}, ids, 6)
        a = np.asarray(out)
        assert a.shape == (2, 6)
        assert (0 <= a).all() and (a < 128).all()
        # greedy is deterministic
        out2 = generate_recompute(model, {"params": params}, ids, 6)
        np.testing.assert_array_equal(a, np.asarray(out2))


class TestSampleToken:
    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.1, 3.0, -1.0], [2.0, 0.0, 2.5]])
        out = sample_token(logits, None)
        np.testing.assert_array_equal(np.asarray(out), [1, 2])

    def test_top_k_restricts_support(self):
        logits = jnp.asarray([[10.0, 5.0, -100.0, -100.0]])
        for seed in range(8):
            t = sample_token(
                logits, jax.random.key(seed), temperature=1.0, top_k=2
            )
            assert int(t[0]) in (0, 1)

    def test_top_p_restricts_support(self):
        # token 0 holds ~93% of the mass (softmax([5,2,1,0])): any
        # top_p <= 0.93 keeps only it
        logits = jnp.asarray([[5.0, 2.0, 1.0, 0.0]])
        for seed in range(8):
            t = sample_token(
                logits, jax.random.key(seed), temperature=1.0, top_p=0.5
            )
            assert int(t[0]) == 0
        # p=1.0 is a no-op: every token stays reachable
        seen = {
            int(sample_token(jnp.zeros((1, 4)), jax.random.key(s),
                             temperature=1.0, top_p=1.0)[0])
            for s in range(32)
        }
        assert seen == {0, 1, 2, 3}

    def test_top_p_first_token_always_survives(self):
        # a peaked distribution with tiny top_p must not mask everything
        logits = jnp.asarray([[0.0, 0.0, 0.0, 0.0]])
        t = sample_token(logits, jax.random.key(0), temperature=1.0,
                         top_p=1e-6)
        assert 0 <= int(t[0]) < 4


class TestGenerationCLI:
    @pytest.mark.slow
    def test_main_end_to_end(self, tmp_path):
        """Tokenizer training -> LM export -> CLI generation round trip."""
        from hyperion_tpu.checkpoint.io import export_gathered
        from hyperion_tpu.data.bpe import train_bpe
        from hyperion_tpu.infer.generate import main

        tok = train_bpe(["the quick brown fox"] * 4, vocab_size=300,
                        verbose=False)
        tok.save(tmp_path / "tok")
        cfg = simple_lm_config(
            vocab_size=tok.vocab_size, d_model=32, n_heads=4, n_layers=2,
            ff_dim=64, max_len=32, dropout=0.0,
        )
        model = TransformerLM(cfg)
        params = model.init_params(jax.random.key(0))
        export_gathered(tmp_path / "lm.npz", params)
        rc = main([
            "--prompt", "the quick", "--ckpt", str(tmp_path / "lm.npz"),
            "--tokenizer-dir", str(tmp_path / "tok"),
            "--max-new-tokens", "4",
        ])
        assert rc == 0

    @pytest.mark.slow
    def test_main_moe_export(self, tmp_path):
        """MoELM export -> CLI generation (recompute path, architecture
        rebuilt from the expert-bank shapes + block pattern)."""
        from hyperion_tpu.checkpoint.io import export_gathered
        from hyperion_tpu.data.bpe import train_bpe
        from hyperion_tpu.infer.generate import main, model_from_npz
        from hyperion_tpu.models.moe_lm import MoELM, MoELMConfig
        from hyperion_tpu.ops.moe import MoEConfig

        tok = train_bpe(["the quick brown fox jumps over the lazy dog"] * 4,
                        vocab_size=256, verbose=False)
        tok.save(tmp_path / "tok")
        base = simple_lm_config(
            vocab_size=tok.vocab_size, d_model=32, n_heads=4, n_layers=2,
            ff_dim=64, max_len=32, dropout=0.0,
        )
        moe = MoEConfig(n_experts=4, top_k=2, d_model=32, ff_dim=64)
        cfg = MoELMConfig(base=base, moe=moe, moe_every=2)
        params = MoELM(cfg).init_params(jax.random.key(0))
        export_gathered(tmp_path / "moe.npz", params)
        # the reconstructor recovers the architecture exactly
        from hyperion_tpu.checkpoint.io import load_gathered

        model, cached = model_from_npz(load_gathered(tmp_path / "moe.npz"))
        assert not cached
        assert model.cfg.moe.n_experts == 4
        assert model.cfg.moe_every == 2
        assert model.cfg.base.n_layers == 2
        rc = main([
            "--prompt", "the quick", "--ckpt", str(tmp_path / "moe.npz"),
            "--tokenizer-dir", str(tmp_path / "tok"),
            "--max-new-tokens", "4",
        ])
        assert rc == 0

    @pytest.mark.slow
    def test_main_speculative(self, tmp_path):
        """Target + draft Llama exports -> --draft-ckpt CLI decode."""
        from hyperion_tpu.checkpoint.io import export_gathered
        from hyperion_tpu.data.bpe import train_bpe
        from hyperion_tpu.infer.generate import main
        from hyperion_tpu.models.llama import Llama, llama_tiny_config

        tok = train_bpe(["the quick brown fox jumps over the lazy dog"] * 4,
                        vocab_size=256, verbose=False)
        tok.save(tmp_path / "tok")
        cfg = llama_tiny_config(vocab_size=tok.vocab_size, max_len=64)
        export_gathered(tmp_path / "target.npz",
                        Llama(cfg).init_params(jax.random.key(0), seq=8))
        dcfg = llama_tiny_config(vocab_size=tok.vocab_size, max_len=64,
                                 n_layers=1)
        export_gathered(tmp_path / "draft.npz",
                        Llama(dcfg).init_params(jax.random.key(1), seq=8))
        rc = main([
            "--prompt", "the quick brown fox jumps",
            "--ckpt", str(tmp_path / "target.npz"),
            "--draft-ckpt", str(tmp_path / "draft.npz"), "--draft-k", "3",
            "--tokenizer-dir", str(tmp_path / "tok"),
            "--max-new-tokens", "6", "--max-len", "64",
        ])
        assert rc == 0

    @pytest.mark.slow
    def test_main_quant_int8_llama(self, tmp_path):
        """Llama export -> --quant int8 weight-only decode via the CLI."""
        from hyperion_tpu.checkpoint.io import export_gathered
        from hyperion_tpu.data.bpe import train_bpe
        from hyperion_tpu.infer.generate import main
        from hyperion_tpu.models.llama import Llama, llama_tiny_config

        tok = train_bpe(["the quick brown fox"] * 4, vocab_size=300,
                        verbose=False)
        tok.save(tmp_path / "tok")
        cfg = llama_tiny_config(vocab_size=tok.vocab_size, max_len=32)
        params = Llama(cfg).init_params(jax.random.key(0), seq=8)
        export_gathered(tmp_path / "llama.npz", params)
        rc = main([
            "--prompt", "the quick", "--ckpt", str(tmp_path / "llama.npz"),
            "--tokenizer-dir", str(tmp_path / "tok"),
            "--max-new-tokens", "4", "--max-len", "32", "--quant", "int8",
        ])
        assert rc == 0
