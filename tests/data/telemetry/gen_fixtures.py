#!/usr/bin/env python
"""Regenerate the golden telemetry fixtures (run from the repo root):

    python tests/data/telemetry/gen_fixtures.py

Four run directories, one per failure mode `obs doctor` must classify
(tests/test_obs_doctor.py asserts the verdicts; the schema contract
test asserts the record fields stay stable):

    healthy/  — full run, terminal `train_end`, heartbeat phase "done"
    nan/      — loss goes NaN mid-run; real HealthMonitor under the
                `abort` policy emits the `health` event + abort trail
    stalled/  — tail steps ~50x slower than the run's own p50; no
                terminal event. Classified "stalled" only from a fresh
                vantage (`--now` near its heartbeat — the loop is alive
                and degrading); against real time the same stream is
                "hung", staleness outranking the stall pattern
    crashed/  — stream ends mid-record (the killed-process signature);
                heartbeat frozen in phase "train"
    serve/    — a drained serve run whose p99 TTFT is dominated by
                queue wait: full request lifecycles (admitted →
                scheduled → prefill span → first token → finished with
                per-phase totals), one preempt-replay, one reject and
                one timeout with `queued_s` — the golden stream
                `obs trace` reconstructs and `obs doctor` must raise a
                named queue-wait incident on (tests/test_timeline.py)
    slo/      — an overload serve run driven through the REAL burn-rate
                monitor (obs/slo.py, fake clocks): windowed TTFT p99
                breaches its target in both windows → exactly ONE
                `alert_raised`; load then drops and the windows drain →
                exactly ONE `alert_cleared`. `obs doctor` must name the
                resolved alert; the schema test pins the event payloads
                (tests/test_obs_live.py)
    sim/      — a small pinned flight-simulator failover run
                (serve/simulate.py): the real policy code on a virtual
                clock, half the fleet killed and readmitted. Pins the
                simulator's telemetry contract (`sim_scenario`,
                `sim_report` + the standard router vocabulary) so
                doctor/diff keep consuming simulator output unchanged
                (tests/test_obs_doctor.py, tests/test_simulate.py)

Everything is driven by fake clocks pinned to _WALL0 so the files are
byte-stable across regenerations (no real time leaks in). The committed
wall timestamps are intentionally in the past: doctor's staleness rules
must hold against real `time.time()` too, which is exactly how the
tier-1 smoke test runs it.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[3]))

from hyperion_tpu.obs.health import HealthConfig, HealthMonitor  # noqa: E402
from hyperion_tpu.obs.heartbeat import Heartbeat  # noqa: E402
from hyperion_tpu.obs.registry import MetricsRegistry  # noqa: E402
from hyperion_tpu.obs.trace import Tracer  # noqa: E402
from hyperion_tpu.utils.clock import VirtualClock  # noqa: E402

_WALL0 = 1754000000.0  # 2026-07-31T21:33:20Z — fixed so fixtures are stable
_OUT = Path(__file__).resolve().parent


def _setup(name: str, run: str):
    d = _OUT / name
    d.mkdir(parents=True, exist_ok=True)
    (d / "telemetry.jsonl").unlink(missing_ok=True)
    # one VirtualClock carries both accumulators: clk() is the
    # monotonic read, clk.wall the wall read — the same object the
    # simulator and the fake-clock tests inject
    clk = VirtualClock(100.0, wall0=_WALL0)
    t = Tracer(d / "telemetry.jsonl", run=run, proc=0, clock=clk,
               wall=clk.wall)
    hb = Heartbeat(d / "heartbeat.json", run=run, proc=0, every=1,
                   clock=clk, wall=clk.wall)
    return d, t, hb, clk


def _snapshot(t: Tracer, step: int, tokens_per_s: float = 4096.0):
    reg = MetricsRegistry()
    reg.counter("steps").inc(step)
    reg.gauge("tokens_per_s").set(tokens_per_s)
    reg.gauge("step_time_ema_ms").set(10.0)
    reg.gauge("mfu").set(0.31)
    reg.gauge("hbm_peak_mb").set(900.0)
    reg.histogram("step_time_ms").observe(10.0)
    reg.set_label("mfu_peak_source", "nominal")
    t.snapshot(reg, step=step, epoch=1)


def _steps(t: Tracer, hb: Heartbeat, clk, durs_ms, start=0):
    for i, ms in enumerate(durs_ms, start):
        with t.span("train_step", step=i):
            clk.advance(ms / 1e3)
        hb.beat(step=i, phase="train", epoch=1)


def healthy():
    d, t, hb, clk = _setup("healthy", "fix_healthy")
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    with t.span("epoch", step=0) as ep:
        _steps(t, hb, clk, [10.0] * 8)
        ep.set(epoch=1, steps=8)
    _snapshot(t, 8)
    with t.span("checkpoint", epoch=1):
        clk.advance(0.2)
    hb.pulse(step=8, phase="checkpoint", epoch=1)
    t.event("train_end", preempted=False, epochs_run=1)
    hb.close(phase="done")
    t.close()


def nan():
    d, t, hb, clk = _setup("nan", "fix_nan")
    mon = HealthMonitor(HealthConfig(policy="abort"), tracer=t)
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    losses = [4.0, 3.8, 3.7, 3.6, 3.9, float("nan")]
    aborted_at = None
    with t.span("epoch", step=0) as ep:
        for i, loss in enumerate(losses):
            with t.span("train_step", step=i):
                clk.advance(0.010)
            hb.beat(step=i, phase="train", epoch=1)
            action = mon.observe_step(i, loss=loss, grad_norm=1.0,
                                      step_time_s=0.010)
            if action == "abort":
                aborted_at = i
                break
        ep.set(epoch=1, steps=aborted_at + 1)
    assert aborted_at is not None, "fixture must abort on the NaN"
    t.event("health_abort", epoch=1, steps_done=aborted_at + 1,
            **mon.summary())
    t.event("train_end", preempted="health_abort", epochs_run=0)
    hb.close(phase="aborted")
    t.close()


def stalled():
    d, t, hb, clk = _setup("stalled", "fix_stalled")
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    # the epoch span never closes: the run was still inside it
    t._stack.append("epoch")
    _steps(t, hb, clk, [10.0] * 8 + [500.0, 520.0, 540.0])
    t.flush()
    t.close()


def hung():
    d, t, hb, clk = _setup("hung", "fix_hung")
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    t._stack.append("epoch")
    _steps(t, hb, clk, [10.0] * 6)
    t.flush()
    t.close()
    # the heartbeat froze in phase "train" — wall-clock staleness (vs a
    # real `now`) is the only evidence, which is the point of the file


def crashed():
    d, t, hb, clk = _setup("crashed", "fix_crashed")
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    t._stack.append("epoch")
    _steps(t, hb, clk, [10.0] * 5)
    t.flush()
    t.close()
    # SIGKILL mid-record: the stream's last line is a fragment a reader
    # must survive AND a doctor must recognize as the crash signature
    with (d / "telemetry.jsonl").open("a", encoding="utf-8") as f:
        f.write('{"v":1,"kind":"span","name":"train_step","run":"fix_crash')


def serve():
    """Queue-wait-dominated serve run. Phase numbers are constructed so
    every `request_finished` decomposes exactly (components + other ==
    e2e) and queue wait owns ~80% of the p99 TTFT — the named-incident
    threshold case for `obs doctor`."""
    d, t, hb, clk = _setup("serve", "fix_serve")

    adv = clk.advance

    t.event("serve_start", slots=2, max_len=64, block_size=8,
            num_blocks=17, prefix_cache=True)
    hb.pulse(phase="serve", step=0, active=0, queue=0)
    # engine row: a few ticks so doctor sees step spans too
    for i in range(6):
        with t.span("serve_tick", step=i) as sp:
            adv(0.010)
            sp.set(active=2)
    # six completed requests, FIFO waits 300..400 ms >> 20 ms prefill
    queue_waits = [0.30, 0.32, 0.34, 0.35, 0.38, 0.40]
    prefill_s, decode_s, cw_s = 0.020, 0.050, 0.002
    for i, qw in enumerate(queue_waits):
        rid = f"r{i}"
        preempted = i == 3
        t.event("request_admitted", request=rid, prompt_len=16,
                max_new_tokens=8, deadline_s=None)
        adv(qw)
        t.event("request_scheduled", request=rid, tick=6 + i,
                resumed=False, queue_wait_s=qw, gate_wait_s=0.0,
                replay_wait_s=0.0)
        with t.span("serve_prefill", step=6 + i) as sp:
            adv(prefill_s)
            sp.set(request=rid, slot=i % 2, prompt_len=16,
                   cached_tokens=0, bucket=16, resumed=False)
        t.event("request_first_token", request=rid, tick=6 + i,
                ttft_s=qw + prefill_s, queue_wait_s=qw,
                gate_wait_s=0.0, prefill_s=prefill_s)
        replay_s = 0.0
        if preempted:
            adv(decode_s / 2)
            t.event("request_preempted", request=rid, generated=4,
                    tick=7 + i)
            adv(0.060)  # replay queue wait
            t.event("request_scheduled", request=rid, tick=8 + i,
                    resumed=True, queue_wait_s=0.0, gate_wait_s=0.0,
                    replay_wait_s=0.060)
            with t.span("serve_prefill", step=8 + i) as sp:
                adv(0.020)  # replay re-prefill
                sp.set(request=rid, slot=i % 2, prompt_len=20,
                       cached_tokens=16, bucket=4, resumed=True)
            replay_s = 0.080
            adv(decode_s / 2)
        else:
            adv(decode_s)
        adv(cw_s + 0.001)  # sink writes + unattributed remainder
        t.event(
            "request_finished", request=rid, tick=9 + i, reason="budget",
            prompt_len=16, n_tokens=8, preempts=1 if preempted else 0,
            e2e_s=round(qw + prefill_s + decode_s + replay_s + cw_s
                        + 0.001, 6),
            ttft_s=round(qw + prefill_s, 6),
            queue_wait_s=qw, gate_wait_s=0.0, prefill_s=prefill_s,
            decode_s=decode_s, preempt_replay_s=replay_s,
            client_write_s=cw_s)
        hb.beat(step=10 + i, phase="serve", active=2, queue=4 - i)
    # the requests that died at the door / in the queue stay visible
    t.event("request_rejected", request="r6", reason="queue_full",
            prompt_len=16, queued_s=0.0)
    t.event("request_admitted", request="r7", prompt_len=16,
            max_new_tokens=8, deadline_s=0.5)
    adv(0.600)
    t.event("request_timeout", request="r7", waited_s=0.6, queued_s=0.6)
    reg = MetricsRegistry()
    reg.counter("serve_ticks").inc(12)
    reg.counter("serve_completed").inc(6)
    reg.counter("serve_rejected").inc(1)
    reg.counter("serve_timed_out").inc(1)
    reg.counter("serve_preempted").inc(1)
    reg.counter("serve_prefix_lookups").inc(6)
    reg.counter("serve_prefix_hits").inc(0)
    reg.gauge("queue_depth").set(0.0)
    reg.gauge("slot_occupancy").set(0.0)
    reg.gauge("tokens_per_s").set(18.0)
    for qw in queue_waits:
        reg.histogram("ttft_ms").observe((qw + prefill_s) * 1e3)
        reg.histogram("queue_wait_ms").observe(qw * 1e3)
    t.snapshot(reg, step=12)
    t.event("serve_end", ticks=12, completed=6, rejected=1, timed_out=1,
            tokens=48, prefix_hits=0, preempted=1)
    hb.close(phase="done", tokens=48, active=0, queue=0)
    t.close()


def slo():
    """Overload run for the live plane: the REAL SLOMonitor (fake
    clock, test-scaled windows — fast 2s / slow 8s) watches a windowed
    TTFT p99 target of 100 ms while the run observes 400 ms TTFTs.
    Both windows breach → one `alert_raised`; load stops, the rings
    drain → one `alert_cleared`. The hysteresis (clear at 90% of
    target in BOTH windows) is exercised by the same math production
    runs — the fixture just pins its wire records."""
    from hyperion_tpu.obs import slo as slo_mod

    d, t, hb, clk = _setup("slo", "fix_slo")

    adv = clk.advance

    reg = MetricsRegistry(clock=clk)
    # min_count scaled down with the windows: the 2s fast window at
    # one request/s holds 2 samples — the production floor (5) is for
    # production windows
    mon = slo_mod.SLOMonitor(
        slo_mod.standard_targets(ttft_p99_ms=100.0, min_count=2), reg,
        fast_s=2.0, slow_s=8.0, eval_every_s=0.5, clock=clk)
    t.event("serve_start", slots=2, max_len=64, block_size=8,
            num_blocks=17, prefix_cache=True)
    hb.pulse(phase="serve", step=0, active=2, queue=3, alerts=[])
    raised = cleared = 0

    def pump(step: int, phase: str, active: int, queue: int) -> None:
        nonlocal raised, cleared
        for tr in mon.evaluate():
            slo_mod.publish([tr], t, reg, step=step,
                            active=len(mon.active))
            raised += tr["kind"] == "raised"
            cleared += tr["kind"] == "cleared"
            hb.pulse(step=step, phase=phase, active=active, queue=queue,
                     alerts=mon.active_names())

    # overload: ten 400 ms TTFTs, one per second — 4x the target's
    # budget in both windows almost immediately
    for i in range(10):
        with t.span("serve_tick", step=i) as sp:
            adv(0.010)
            sp.set(active=2)
        reg.counter("serve_ticks").inc()
        reg.counter("serve_accepted").inc()
        reg.counter("serve_completed").inc()
        reg.histogram("ttft_ms").observe(400.0)
        reg.gauge("queue_depth").set(3.0)
        reg.gauge("slot_occupancy").set(1.0)
        reg.gauge("tokens_per_s").set(8.0)
        adv(0.990)
        pump(i, "serve", 2, 3)
        hb.beat(step=i, phase="serve", active=2, queue=3,
                alerts=mon.active_names())
    # load drops: the loop idles, the windows drain, the alert clears
    # once BOTH windows are back under the clear ratio
    for i in range(10, 24):
        adv(1.0)
        pump(i, "serve_idle", 0, 0)
        hb.beat(step=i, phase="serve_idle", active=0, queue=0,
                alerts=mon.active_names())
    assert raised == 1 and cleared == 1, (raised, cleared)
    assert not mon.active
    t.snapshot(reg, step=24)
    t.event("serve_end", ticks=24, completed=10, rejected=0,
            timed_out=0, tokens=40, prefix_hits=0, preempted=0,
            alerts_raised=1)
    hb.close(phase="done", active=0, queue=0, alerts=[])
    t.close()


def fleet():
    """Golden fleet: a router stream plus two `replica_*/` serve
    streams joined by the wire hop context (`trace={"id","hop",
    "attempt","router_life"}` — the fields serve/router.py stamps on
    every dispatch). Three journeys:

        f0 — clean single dispatch to replica 0
        f1 — mid-stream failover: replica 1 dies after the first
             token, the router redispatches to replica 0 (the
             failover_gap component)
        f2 — client disconnect + resume: the resumed relay admits
             under the suffixed wire id `f2~r1` (the resume_gap
             component; the id must fold back to f2)

    All processes share ONE wall clock (same host) but run distinct
    monotonic bases — exactly the skew `obs trace --fleet` must
    reconcile. Every request_finished decomposes exactly, so the
    fleet attribution's sum-to-measured pin has a ground truth."""
    base = _OUT / "fleet"
    for sub in ("", "replica_0", "replica_1"):
        d = base / sub
        d.mkdir(parents=True, exist_ok=True)
        (d / "telemetry.jsonl").unlink(missing_ok=True)

    # the shared host wall clock is a VirtualClock CALLED directly (its
    # monotonic accumulator plays the wall role); the per-process
    # monotonic clocks start from distinct bases to create the skew
    wall = VirtualClock(_WALL0)   # the host clock every process shares
    rclk, c0, c1 = (VirtualClock(100.0), VirtualClock(50.0),
                    VirtualClock(60.0))

    def adv(s: float) -> None:
        wall.advance(s)
        for c in (rclk, c0, c1):
            c.advance(s)

    rt = Tracer(base / "telemetry.jsonl", run="route_fix", proc=0,
                clock=rclk, wall=wall)
    rhb = Heartbeat(base / "heartbeat.json", run="route_fix", proc=0,
                    every=1, clock=rclk, wall=wall)
    t0 = Tracer(base / "replica_0" / "telemetry.jsonl",
                run="serve_r0_100", proc=0, clock=c0, wall=wall)
    h0 = Heartbeat(base / "replica_0" / "heartbeat.json",
                   run="serve_r0_100", proc=0, every=1, clock=c0,
                   wall=wall)
    t1 = Tracer(base / "replica_1" / "telemetry.jsonl",
                run="serve_r1_100", proc=0, clock=c1, wall=wall)
    h1 = Heartbeat(base / "replica_1" / "heartbeat.json",
                   run="serve_r1_100", proc=0, every=1, clock=c1,
                   wall=wall)

    rhb.pulse(phase="route", ready=2, dispatched=0)
    for t, h, idx in ((t0, h0, 0), (t1, h1, 1)):
        t.event("serve_start", slots=2, max_len=64, block_size=8,
                num_blocks=17, prefix_cache=True)
        h.pulse(phase="serve", step=0, active=0, queue=0)
        rt.event("replica_ready", replica=idx)
        # a couple of engine ticks so the stream has span records
        for i in range(2):
            with t.span("serve_tick", step=i) as sp:
                adv(0.005)
                sp.set(active=0)

    def leg(t, rid, trace, tick, qw, slot, *, resumed=False,
            finish=True, decode_s=0.05, replay_wait=0.0):
        """One replica-side request leg with an exact decomposition."""
        prefill_s, cw_s = 0.020, 0.002
        t.event("request_admitted", request=rid, prompt_len=16,
                max_new_tokens=8, deadline_s=None, trace=trace)
        adv(qw)
        t.event("request_scheduled", request=rid, tick=tick,
                resumed=resumed, queue_wait_s=0.0 if resumed else qw,
                gate_wait_s=0.0,
                replay_wait_s=qw if resumed else 0.0)
        with t.span("serve_prefill", step=tick) as sp:
            adv(prefill_s)
            sp.set(request=rid, slot=slot, prompt_len=16,
                   cached_tokens=0, bucket=16, resumed=resumed)
        t.event("request_first_token", request=rid, tick=tick,
                ttft_s=round(qw + prefill_s, 6),
                queue_wait_s=0.0 if resumed else qw, gate_wait_s=0.0,
                prefill_s=prefill_s, trace=trace)
        adv(decode_s)
        if not finish:
            return None
        adv(cw_s + 0.001)
        e2e = round(qw + prefill_s + decode_s + cw_s + 0.001, 6)
        t.event("request_finished", request=rid, tick=tick + 1,
                reason="budget", prompt_len=16, n_tokens=8, preempts=0,
                e2e_s=e2e, ttft_s=round(qw + prefill_s, 6),
                queue_wait_s=0.0 if resumed else qw, gate_wait_s=0.0,
                prefill_s=prefill_s, decode_s=decode_s,
                preempt_replay_s=qw if resumed else 0.0,
                client_write_s=cw_s, trace=trace)
        return e2e

    # ---- f0: the clean path (router_overhead + dispatch_gap + phases)
    sub = wall.t
    adv(0.002)                                       # router overhead
    tr = {"id": "f0", "hop": 0, "attempt": 0, "router_life": 0}
    rt.event("route_dispatch", request="f0", replica=0, affinity=False,
             redispatch=0, trace=tr)
    adv(0.004)                                       # wire: dispatch gap
    leg(t0, "f0", tr, tick=2, qw=0.05, slot=0)
    h0.beat(step=3, phase="serve", active=0, queue=0)
    adv(0.003)                                       # terminal on wire
    rt.event("route_complete", request="f0", replica=0, status="done",
             tokens=8, redispatches=0, e2e_s=round(wall.t - sub, 6),
             trace=tr)
    rhb.beat(step=1, phase="route", ready=2, dispatched=1)

    # ---- f1: mid-stream failover replica 1 -> replica 0
    sub = wall.t
    adv(0.002)
    tr = {"id": "f1", "hop": 0, "attempt": 0, "router_life": 0}
    rt.event("route_dispatch", request="f1", replica=1, affinity=False,
             redispatch=0, trace=tr)
    adv(0.004)
    leg(t1, "f1", tr, tick=2, qw=0.06, slot=0, finish=False,
        decode_s=0.020)                              # dies mid-decode
    # replica 1's stream ends here; its heartbeat freezes in "serve"
    t1.flush()
    t1.close()
    adv(0.010)                                       # death detected
    rt.event("route_redispatch", request="f1", from_replica=1,
             reason="replica_lost", delivered=3, trace=tr)
    rt.event("replica_ejected", replica=1, reason="stream_lost",
             restarts=1)
    adv(0.002)
    tr = {"id": "f1", "hop": 1, "attempt": 1, "router_life": 0}
    rt.event("route_dispatch", request="f1", replica=0, affinity=False,
             redispatch=1, trace=tr)
    adv(0.300)                    # restart + connect retries: the gap
    #    — big on purpose: failover_gap must dominate the fixture's
    #    p99 e2e so the doctor's named fleet incident has a golden case
    leg(t0, "f1", tr, tick=4, qw=0.03, slot=0)
    h0.beat(step=5, phase="serve", active=0, queue=0)
    adv(0.003)
    rt.event("route_complete", request="f1", replica=0, status="done",
             tokens=8, redispatches=1, e2e_s=round(wall.t - sub, 6),
             trace=tr)
    rhb.beat(step=2, phase="route", ready=1, dispatched=2)

    # ---- f2: client disconnect mid-stream, then a resume relay whose
    # wire id is the suffixed `f2~r1` — the id-folding case
    adv(0.002)
    tr = {"id": "f2", "hop": 0, "attempt": 0, "router_life": 0}
    rt.event("route_dispatch", request="f2", replica=0, affinity=False,
             redispatch=0, trace=tr)
    adv(0.004)
    leg(t0, "f2", tr, tick=6, qw=0.04, slot=1, finish=False,
        decode_s=0.030)
    t0.event("client_disconnected", request="f2", generated=4,
             trace=tr)
    rt.event("client_disconnected", request="f2", delivered=4)
    adv(0.250)                            # the client is away
    sub = wall.t
    rt.event("route_resume", request="f2", next_index=4, router_life=0)
    adv(0.002)
    tr = {"id": "f2", "hop": 1, "attempt": 0, "router_life": 0}
    rt.event("route_dispatch", request="f2", replica=0, affinity=True,
             redispatch=0, trace=tr)
    adv(0.005)                            # resume admit gap
    leg(t0, "f2~r1", tr, tick=8, qw=0.015, slot=1, resumed=True,
        decode_s=0.040)
    h0.beat(step=9, phase="serve", active=0, queue=0)
    adv(0.003)
    rt.event("route_complete", request="f2", replica=0, status="done",
             tokens=8, redispatches=0, e2e_s=round(wall.t - sub, 6),
             trace=tr)
    rhb.beat(step=3, phase="route", ready=1, dispatched=3)

    rt.event("router_end", dispatched=3, completed=3, redispatched=1,
             resumed=1, rejected=0)
    rhb.close(phase="done", ready=1, dispatched=3)
    rt.close()
    t0.event("serve_end", ticks=10, completed=3, rejected=0,
             timed_out=0, tokens=24, prefix_hits=0, preempted=0)
    h0.close(phase="done", tokens=24, active=0, queue=0)
    t0.close()
    # replica 1's heartbeat stays frozen mid-"serve": h1 is NOT closed
    # (the dead-replica evidence `obs doctor` keys off), but its last
    # beat must exist for the heartbeat contract
    h1.pulse(phase="serve", step=2, active=1, queue=0)


def sim():
    """Golden flight-simulator stream: a small pinned failover scenario
    (4 replicas, 150 requests, half the fleet killed at t=60) played on
    the REAL discrete-event harness (serve/simulate.py). Everything is
    virtual-clocked off the same _WALL0 base the other fixtures use, so
    regeneration is byte-stable. The stream carries the simulator's own
    vocabulary (`sim_scenario`, `sim_report`) alongside the standard
    router/serve events — the contract tests pin that `obs doctor` and
    `obs diff` consume it with no sim-specific code paths."""
    from hyperion_tpu.serve import simulate as sim_mod

    d = _OUT / "sim"
    d.mkdir(parents=True, exist_ok=True)
    (d / "telemetry.jsonl").unlink(missing_ok=True)
    scn = dict(sim_mod.SCENARIOS["failover"])
    scn.update(replicas=4, requests=150, duration_s=90.0)
    # asserts rescaled to the fixture's size (half of 4 = 2 deaths);
    # the fixture must be a PASSING run — its sim_report pins ok=true
    scn["assert"] = {"completed_rate": {"min": 0.80},
                     "duplicate_tokens": {"max": 0},
                     "ejections": {"min": 2},
                     "readmits": {"min": 2}}
    res = sim_mod.run_scenario(scn, out=str(d))
    assert res["ok"], res["asserts"]


def main() -> int:
    from unittest import mock

    # Heartbeat stamps os.getpid() and host_rss_mb() (ru_maxrss — varies
    # run to run) into heartbeat.json; pin both so regeneration really
    # is byte-stable (the clocks already are)
    with mock.patch("os.getpid", return_value=4242), \
            mock.patch("hyperion_tpu.obs.heartbeat.host_rss_mb",
                       return_value=20.5):
        for fn in (healthy, nan, stalled, hung, crashed, serve, slo,
                   fleet, sim):
            fn()
            print(f"wrote {fn.__name__}/")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
