#!/usr/bin/env python
"""Regenerate the golden telemetry fixtures (run from the repo root):

    python tests/data/telemetry/gen_fixtures.py

Four run directories, one per failure mode `obs doctor` must classify
(tests/test_obs_doctor.py asserts the verdicts; the schema contract
test asserts the record fields stay stable):

    healthy/  — full run, terminal `train_end`, heartbeat phase "done"
    nan/      — loss goes NaN mid-run; real HealthMonitor under the
                `abort` policy emits the `health` event + abort trail
    stalled/  — tail steps ~50x slower than the run's own p50; no
                terminal event. Classified "stalled" only from a fresh
                vantage (`--now` near its heartbeat — the loop is alive
                and degrading); against real time the same stream is
                "hung", staleness outranking the stall pattern
    crashed/  — stream ends mid-record (the killed-process signature);
                heartbeat frozen in phase "train"

Everything is driven by fake clocks pinned to _WALL0 so the files are
byte-stable across regenerations (no real time leaks in). The committed
wall timestamps are intentionally in the past: doctor's staleness rules
must hold against real `time.time()` too, which is exactly how the
tier-1 smoke test runs it.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[3]))

from hyperion_tpu.obs.health import HealthConfig, HealthMonitor  # noqa: E402
from hyperion_tpu.obs.heartbeat import Heartbeat  # noqa: E402
from hyperion_tpu.obs.registry import MetricsRegistry  # noqa: E402
from hyperion_tpu.obs.trace import Tracer  # noqa: E402

_WALL0 = 1754000000.0  # 2026-07-31T21:33:20Z — fixed so fixtures are stable
_OUT = Path(__file__).resolve().parent


class Clock:
    def __init__(self, t: float):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def _setup(name: str, run: str):
    d = _OUT / name
    d.mkdir(parents=True, exist_ok=True)
    (d / "telemetry.jsonl").unlink(missing_ok=True)
    clk, wall = Clock(100.0), Clock(_WALL0)
    t = Tracer(d / "telemetry.jsonl", run=run, proc=0, clock=clk, wall=wall)
    hb = Heartbeat(d / "heartbeat.json", run=run, proc=0, every=1,
                   clock=clk, wall=wall)
    return d, t, hb, clk, wall


def _snapshot(t: Tracer, step: int, tokens_per_s: float = 4096.0):
    reg = MetricsRegistry()
    reg.counter("steps").inc(step)
    reg.gauge("tokens_per_s").set(tokens_per_s)
    reg.gauge("step_time_ema_ms").set(10.0)
    reg.gauge("mfu").set(0.31)
    reg.gauge("hbm_peak_mb").set(900.0)
    reg.histogram("step_time_ms").observe(10.0)
    reg.set_label("mfu_peak_source", "nominal")
    t.snapshot(reg, step=step, epoch=1)


def _steps(t: Tracer, hb: Heartbeat, clk, wall, durs_ms, start=0):
    for i, ms in enumerate(durs_ms, start):
        with t.span("train_step", step=i):
            clk.advance(ms / 1e3)
            wall.advance(ms / 1e3)
        hb.beat(step=i, phase="train", epoch=1)


def healthy():
    d, t, hb, clk, wall = _setup("healthy", "fix_healthy")
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    with t.span("epoch", step=0) as ep:
        _steps(t, hb, clk, wall, [10.0] * 8)
        ep.set(epoch=1, steps=8)
    _snapshot(t, 8)
    with t.span("checkpoint", epoch=1):
        clk.advance(0.2)
        wall.advance(0.2)
    hb.pulse(step=8, phase="checkpoint", epoch=1)
    t.event("train_end", preempted=False, epochs_run=1)
    hb.close(phase="done")
    t.close()


def nan():
    d, t, hb, clk, wall = _setup("nan", "fix_nan")
    mon = HealthMonitor(HealthConfig(policy="abort"), tracer=t)
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    losses = [4.0, 3.8, 3.7, 3.6, 3.9, float("nan")]
    aborted_at = None
    with t.span("epoch", step=0) as ep:
        for i, loss in enumerate(losses):
            with t.span("train_step", step=i):
                clk.advance(0.010)
                wall.advance(0.010)
            hb.beat(step=i, phase="train", epoch=1)
            action = mon.observe_step(i, loss=loss, grad_norm=1.0,
                                      step_time_s=0.010)
            if action == "abort":
                aborted_at = i
                break
        ep.set(epoch=1, steps=aborted_at + 1)
    assert aborted_at is not None, "fixture must abort on the NaN"
    t.event("health_abort", epoch=1, steps_done=aborted_at + 1,
            **mon.summary())
    t.event("train_end", preempted="health_abort", epochs_run=0)
    hb.close(phase="aborted")
    t.close()


def stalled():
    d, t, hb, clk, wall = _setup("stalled", "fix_stalled")
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    # the epoch span never closes: the run was still inside it
    t._stack.append("epoch")
    _steps(t, hb, clk, wall, [10.0] * 8 + [500.0, 520.0, 540.0])
    t.flush()
    t.close()


def hung():
    d, t, hb, clk, wall = _setup("hung", "fix_hung")
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    t._stack.append("epoch")
    _steps(t, hb, clk, wall, [10.0] * 6)
    t.flush()
    t.close()
    # the heartbeat froze in phase "train" — wall-clock staleness (vs a
    # real `now`) is the only evidence, which is the point of the file


def crashed():
    d, t, hb, clk, wall = _setup("crashed", "fix_crashed")
    t.event("train_start", job="language_ddp", n_devices=8, epochs=1)
    t._stack.append("epoch")
    _steps(t, hb, clk, wall, [10.0] * 5)
    t.flush()
    t.close()
    # SIGKILL mid-record: the stream's last line is a fragment a reader
    # must survive AND a doctor must recognize as the crash signature
    with (d / "telemetry.jsonl").open("a", encoding="utf-8") as f:
        f.write('{"v":1,"kind":"span","name":"train_step","run":"fix_crash')


def main() -> int:
    for fn in (healthy, nan, stalled, hung, crashed):
        fn()
        print(f"wrote {fn.__name__}/")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
