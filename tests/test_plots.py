"""Plot layer + profiler capture (SURVEY §5.1 / reference PNG artifacts)."""

from pathlib import Path

import pytest

from hyperion_tpu.metrics.plots import (
    plot_bandwidth,
    plot_baseline_models,
    plot_batch_scaling,
    plot_compile_tiers,
    plot_matmul_tflops,
    try_plot,
)


class TestPlots:
    def test_compile_tiers(self, tmp_path):
        rows = [
            {"model": "lm", "variant": "op_by_op", "median_ms": 100.0},
            {"model": "lm", "variant": "jit", "median_ms": 10.0},
            {"model": "lm", "variant": "jit_pallas", "median_ms": 8.0},
            {"model": "rn", "variant": "jit", "median_ms": 5.0},
            {"model": "rn", "variant": "jit_pallas", "median_ms": float("nan")},
        ]
        p = plot_compile_tiers(rows, tmp_path / "c.png")
        assert p.exists() and p.stat().st_size > 1000

    def test_matmul_and_bandwidth(self, tmp_path):
        rows = [
            {"size": 1024, "dtype": "bfloat16", "tflops": 50.0,
             "peak_tflops": 197.0},
            {"size": 8192, "dtype": "bfloat16", "tflops": 172.0,
             "peak_tflops": 197.0},
            {"size": 8192, "dtype": "float32", "tflops": 30.0,
             "peak_tflops": 197.0},
        ]
        assert plot_matmul_tflops(rows, tmp_path / "m.png").exists()
        bw = [
            {"elements": 10_000_000, "gb_per_s": 7000.0,
             "note": "cache_resident_not_hbm"},
            {"elements": 100_000_000, "gb_per_s": 690.0, "note": ""},
            {"elements": 500_000_000, "gb_per_s": 683.0, "note": ""},
        ]
        assert plot_bandwidth(bw, tmp_path / "b.png").exists()

    def test_baseline_panels(self, tmp_path):
        rows = [
            {"model": "resnet50", "forward_ms": 10, "backward_ms": 20,
             "optimizer_ms": 2, "peak_memory_mb": 3000, "samples_per_s": 500},
            {"model": "vit_b16", "forward_ms": 2, "backward_ms": 3,
             "optimizer_ms": 0.5, "peak_memory_mb": 500, "samples_per_s": 5000},
        ]
        assert plot_baseline_models(rows, tmp_path / "bl.png").exists()
        sweeps = {"resnet50": [
            {"batch_size": 1, "samples_per_s": 40, "peak_memory_mb": 600},
            {"batch_size": 32, "samples_per_s": 550, "peak_memory_mb": 3200},
        ]}
        assert plot_batch_scaling(sweeps, tmp_path / "sc.png").exists()

    def test_try_plot_swallows_errors(self, capsys):
        assert try_plot(plot_compile_tiers, None, "/nonexistent/x.png") is None
        assert "skipped" in capsys.readouterr().out


class TestProfiling:
    def test_capture_writes_trace(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from hyperion_tpu.utils import profiling

        with profiling.capture(tmp_path / "trace"):
            with profiling.annotate("matmul_region"):
                x = jnp.ones((64, 64))
                jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
        files = list(Path(tmp_path / "trace").rglob("*"))
        assert any(f.is_file() for f in files), files

    def test_capture_none_is_noop(self):
        from hyperion_tpu.utils import profiling

        with profiling.capture(None) as d:
            assert d is None
        with profiling.capture("") as d:
            assert d is None
