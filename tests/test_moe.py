"""MoE routing, dispatch algebra, expert parallelism, and the MoE LM.
Runs on the simulated 8-device CPU mesh."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.models.moe_lm import MoELM, MoELMConfig
from hyperion_tpu.models.transformer_lm import simple_lm_config
from hyperion_tpu.ops.moe import (
    MoEConfig, init_moe_params, moe_ffn, top_k_routing,
)
from hyperion_tpu.runtime.mesh import (
    AxisName, MeshSpec, activate_mesh, make_mesh,
)

D = 16


def moe_cfg(**kw):
    base = dict(n_experts=4, top_k=2, capacity_factor=2.0, d_model=D,
                ff_dim=32)
    base.update(kw)
    return MoEConfig(**base)


class TestRouting:
    def test_dispatch_combine_shapes_and_mass(self):
        cfg = moe_cfg()
        probs = jax.nn.softmax(
            jax.random.normal(jax.random.key(0), (24, cfg.n_experts)), -1
        )
        C = cfg.capacity(24)
        dispatch, combine = top_k_routing(probs, cfg, C)
        assert dispatch.shape == (24, cfg.n_experts, C)
        # every token occupies exactly top_k slots (capacity is ample)
        np.testing.assert_allclose(
            np.asarray(dispatch.sum(axis=(1, 2))), cfg.top_k, atol=1e-6
        )
        # combine weights renormalize to 1 per token
        np.testing.assert_allclose(
            np.asarray(combine.sum(axis=(1, 2))), 1.0, atol=1e-5
        )
        # no expert slot double-booked
        assert float(dispatch.sum(axis=0).max()) <= 1.0 + 1e-6

    def test_capacity_drops_overflow(self):
        cfg = moe_cfg(top_k=1, capacity_factor=1.0)
        # all tokens want expert 0 → only `capacity` survive
        probs = jnp.tile(jnp.asarray([[0.97, 0.01, 0.01, 0.01]]), (16, 1))
        C = 2
        dispatch, combine = top_k_routing(probs, cfg, C)
        assert float(dispatch.sum()) == C  # exactly capacity kept
        assert float(combine[C:].sum()) == 0.0  # later tokens dropped

    def test_top1_vs_top2_gate_normalization(self):
        cfg1, cfg2 = moe_cfg(top_k=1), moe_cfg(top_k=2)
        probs = jax.nn.softmax(
            jax.random.normal(jax.random.key(1), (12, 4)), -1
        )
        _, c1 = top_k_routing(probs, cfg1, 12)
        _, c2 = top_k_routing(probs, cfg2, 12)
        np.testing.assert_allclose(np.asarray(c1.sum((1, 2))), 1.0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(c2.sum((1, 2))), 1.0, atol=1e-5)


class TestRoutingValidation:
    def test_top_k_exceeding_experts_raises(self):
        with pytest.raises(ValueError, match="top_k"):
            moe_cfg(n_experts=1, top_k=2)

    def test_padding_consumes_no_capacity(self):
        """Pads must not steal slots: with capacity exactly the real
        count, every real token survives when pads are masked out."""
        cfg = moe_cfg(top_k=1, capacity_factor=1.0)
        N = 16
        # everyone wants expert 0; first half of tokens are padding
        probs = jnp.tile(jnp.asarray([[0.97, 0.01, 0.01, 0.01]]), (N, 1))
        valid = jnp.concatenate([jnp.zeros(8), jnp.ones(8)])
        dispatch, combine = top_k_routing(probs, cfg, 8, valid)
        # all 8 real tokens kept (pads would have filled the slots)
        assert float(dispatch[8:].sum()) == 8.0
        # pads dispatched nowhere, zero combine weight
        assert float(dispatch[:8].sum()) == 0.0
        assert float(combine[:8].sum()) == 0.0


class TestMoEFFN:
    def test_output_finite_and_shaped(self):
        cfg = moe_cfg()
        params = init_moe_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(1), (2, 8, D), jnp.float32)
        y, aux = moe_ffn(params, x, cfg)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert np.isfinite(float(aux))

    def test_aux_loss_balanced_near_one(self):
        """Uniform routing ⇒ GShard aux ≈ 1; collapsed routing ⇒ ≈ E."""
        cfg = moe_cfg(top_k=1)
        E = cfg.n_experts
        N = 64
        uniform = jnp.full((N, E), 1.0 / E)
        # break argmax ties round-robin to emulate balanced top-1 counts
        uniform = uniform + jax.nn.one_hot(jnp.arange(N) % E, E) * 1e-6
        top1 = jax.nn.one_hot(jnp.argmax(uniform, -1), E)
        aux_u = E * float(jnp.sum(top1.mean(0) * uniform.mean(0)))
        assert abs(aux_u - 1.0) < 1e-3
        collapsed = jax.nn.one_hot(jnp.zeros(N, jnp.int32), E) * 0.99 + 0.0025
        top1c = jax.nn.one_hot(jnp.argmax(collapsed, -1), E)
        aux_c = E * float(jnp.sum(top1c.mean(0) * collapsed.mean(0)))
        assert aux_c > 3.0

    def test_expert_parallel_matches_unsharded(self):
        """The expert-sharded run is GSPMD layout only — outputs must
        match the meshless run exactly (up to fp tolerance)."""
        cfg = moe_cfg()
        params = init_moe_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(1), (2, 16, D), jnp.float32)
        ref, aux_ref = moe_ffn(params, x, cfg)
        mesh = make_mesh(MeshSpec(data=2, expert=4))
        with activate_mesh(mesh):
            out, aux = jax.jit(lambda p, x: moe_ffn(p, x, cfg))(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )
        assert abs(float(aux) - float(aux_ref)) < 1e-5

    def test_padded_tokens_pass_through_as_zero(self):
        cfg = moe_cfg()
        params = init_moe_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(1), (2, 8, D), jnp.float32)
        mask = np.ones((2, 8), np.int8)
        mask[:, 6:] = 0
        y, aux = moe_ffn(params, x, cfg, padding_mask=jnp.asarray(mask))
        # pad positions produce exactly zero (residual carries them)
        assert float(jnp.abs(y[:, 6:]).max()) == 0.0
        assert float(jnp.abs(y[:, :6]).max()) > 0.0
        assert np.isfinite(float(aux))

    def test_grouping_keeps_dispatch_linear(self):
        """Dispatch memory per group is [g, E, C(g)]: doubling the batch
        doubles G, not C — total stays linear in tokens."""
        cfg = moe_cfg()
        # capacity is a function of GROUP size, linear in it — not of
        # the total batch token count
        assert cfg.capacity(16) == 2 * cfg.capacity(8)
        p1 = init_moe_params(jax.random.key(0), cfg)
        x1 = jax.random.normal(jax.random.key(1), (1, 8, D), jnp.float32)
        x2 = jnp.concatenate([x1, x1], axis=0)  # two identical rows
        y1, _ = moe_ffn(p1, x1, cfg)
        y2, _ = moe_ffn(p1, x2, cfg)
        # per-row grouping ⇒ each row routes independently: identical
        # rows give identical outputs regardless of batch size
        np.testing.assert_allclose(
            np.asarray(y2[0]), np.asarray(y1[0]), atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(y2[1]), np.asarray(y1[0]), atol=1e-6
        )

    @pytest.mark.slow
    def test_grads_flow_to_all_experts(self):
        cfg = moe_cfg(capacity_factor=4.0)
        params = init_moe_params(jax.random.key(0), cfg)
        x = jax.random.normal(jax.random.key(2), (4, 16, D), jnp.float32)

        def loss(p):
            y, aux = moe_ffn(p, x, cfg)
            return jnp.mean(y**2) + 0.01 * aux

        g = jax.grad(loss)(params)
        # with 64 tokens over 4 experts every expert sees traffic
        per_expert = np.asarray(jnp.abs(g["experts"]["wi"]).sum(axis=(1, 2)))
        assert (per_expert > 0).all(), per_expert
        assert np.abs(np.asarray(g["router"]["kernel"])).sum() > 0


class TestMoELM:
    def _model(self):
        base = simple_lm_config(
            vocab_size=64, d_model=D, n_heads=4, n_layers=2, ff_dim=32,
            max_len=8, dropout=0.0,
        )
        return MoELM(MoELMConfig(base=base, moe=moe_cfg(), moe_every=2))

    def test_forward_and_aux(self):
        model = self._model()
        params = model.init_params(jax.random.key(0))
        ids = jnp.zeros((2, 8), jnp.int32)
        logits, aux = model.apply_with_aux({"params": params}, ids)
        assert logits.shape == (2, 8, 64)
        assert logits.dtype == jnp.float32
        assert float(aux) > 0  # one MoE layer sowed its loss

    @pytest.mark.slow
    def test_remat_matches_and_grads(self):
        """cfg.base.remat must reach both dense and sparse blocks (the
        TransformerLM scaffold is shared; regression for the dropped
        wrapping)."""
        import dataclasses as dc

        model = self._model()
        params = model.init_params(jax.random.key(0))
        cfg_r = dc.replace(
            model.cfg, base=dc.replace(model.cfg.base, remat="full")
        )
        model_r = MoELM(cfg_r)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 8)), jnp.int32
        )

        def loss(m, p):
            logits, aux = m.apply_with_aux({"params": p}, ids)
            return jnp.mean(logits**2) + aux

        g = jax.grad(lambda p: loss(model, p))(params)
        g_r = jax.grad(lambda p: loss(model_r, p))(params)
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_r)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
            )

    def test_expert_leaves_get_expert_axis(self):
        from flax import traverse_util
        from jax.sharding import PartitionSpec

        from hyperion_tpu.parallel.partition import partition_specs

        model = self._model()
        params = jax.eval_shape(
            lambda r: model.init_params(r), jax.random.key(0)
        )
        mesh = make_mesh(MeshSpec(data=2, expert=4))
        specs = traverse_util.flatten_dict(
            partition_specs(params, mesh, fsdp=False), sep="/",
            is_leaf=lambda _, v: isinstance(v, PartitionSpec),
        )
        expert_specs = {k: v for k, v in specs.items() if "/experts/" in k}
        assert expert_specs
        for k, v in expert_specs.items():
            assert v and v[0] == AxisName.EXPERT, (k, v)

    @pytest.mark.slow
    def test_train_step_decreases_loss(self):
        import optax

        from hyperion_tpu.runtime.mesh import batch_sharding
        from hyperion_tpu.train import (
            create_train_state, make_optimizer, make_train_step,
            next_token_loss,
        )

        model = self._model()
        mesh = make_mesh(MeshSpec(data=2, expert=4))
        opt = make_optimizer(1e-2)
        with activate_mesh(mesh):
            state, sharding = create_train_state(
                lambda r: {"params": model.init_params(r)}, opt, mesh,
                jax.random.key(0), policy="fp32", fsdp=False,
            )

            def loss_fn(params, batch_stats, batch, rngs):
                logits, aux = model.apply_with_aux(
                    {"params": params}, batch["input_ids"],
                    padding_mask=batch["attention_mask"],
                )
                loss = next_token_loss(
                    logits, batch["input_ids"], batch["attention_mask"]
                ) + aux
                return loss, ({"loss": loss}, batch_stats)

            step = make_train_step(loss_fn, opt, sharding)
            ids = np.random.default_rng(0).integers(0, 64, (8, 8))
            sh = batch_sharding(mesh)
            batch = {
                "input_ids": jax.device_put(ids.astype(np.int32), sh),
                "attention_mask": jax.device_put(np.ones((8, 8), np.int8), sh),
            }
            losses = []
            rng = jax.random.key(1)
            for i in range(5):
                state, metrics = step(state, batch, rng)
                losses.append(float(metrics["loss"]))
            assert losses[-1] < losses[0], losses
