import numpy as np
import pytest

from hyperion_tpu.data import (
    ShardedBatches,
    load_cifar10,
    load_wikitext2,
    synthetic_cifar_split,
    synthetic_lm_split,
)
from hyperion_tpu.data.text import (
    GPT2_EOS_ID,
    GPT2_VOCAB_SIZE,
    TextSplit,
    load_token_file,
    save_token_file,
)


class TestTextPipeline:
    def test_synthetic_shapes_and_determinism(self):
        a = synthetic_lm_split(64, seq_len=32, seed=1)
        b = synthetic_lm_split(64, seq_len=32, seed=1)
        np.testing.assert_array_equal(a.input_ids, b.input_ids)
        assert a.input_ids.shape == (64, 32)
        assert a.input_ids.dtype == np.int32
        a.verify()

    def test_eos_padding_matches_mask(self):
        s = synthetic_lm_split(32, seq_len=16, seed=0)
        assert (s.input_ids[s.attention_mask == 0] == GPT2_EOS_ID).all()

    def test_verify_catches_bad_ids(self):
        s = synthetic_lm_split(8, seq_len=8)
        s.input_ids[0, 0] = GPT2_VOCAB_SIZE + 5
        with pytest.raises(ValueError, match="token ids"):
            s.verify()

    def test_verify_catches_non_prefix_mask(self):
        s = synthetic_lm_split(8, seq_len=8)
        s.attention_mask[0] = np.array([1, 0, 1, 0, 1, 0, 1, 0], np.int8)
        with pytest.raises(ValueError, match="right-padded"):
            s.verify()

    def test_npz_roundtrip(self, tmp_path):
        s = synthetic_lm_split(16, seq_len=8)
        save_token_file(s, tmp_path / "train.npz")
        r = load_token_file(tmp_path / "train.npz")
        np.testing.assert_array_equal(s.input_ids, r.input_ids)

    def test_load_wikitext2_fallback_and_npz_preference(self, tmp_path):
        # no data on disk -> synthetic
        d = load_wikitext2(tmp_path, splits=("train",), synthetic_sizes={"train": 32}, seq_len=16)
        assert d["train"].source == "synthetic"
        # our npz format present -> preferred over synthetic
        base = tmp_path / "wikitext2_tokenized"
        base.mkdir()
        save_token_file(synthetic_lm_split(8, seq_len=16, seed=9), base / "train.npz")
        d2 = load_wikitext2(tmp_path, splits=("train",))
        assert d2["train"].source.startswith("npz:")
        assert len(d2["train"]) == 8

    def test_synthetic_seed_to_corpus_mapping_is_pinned(self):
        """The inverse-CDF sampler must keep the exact draw the old
        `rng.choice(..., p=probs)` produced (numpy's Generator builds
        the same renormalized cdf + side='right' search internally) —
        this pins the seed -> corpus mapping so any future sampler
        change that silently reshuffles every fixture fails HERE."""
        s = synthetic_lm_split(4, seq_len=8, seed=42)
        np.testing.assert_array_equal(
            s.input_ids[0],
            np.array([994, 19, 3633, 350, 50256, 50256, 50256, 50256],
                     np.int32),
        )
        assert int(s.input_ids.sum()) == 658217

    def test_ragged_arrow_scatter_matches_per_row_reference(self, tmp_path):
        """Variable-length list columns (no padding on disk): the
        vectorized mask scatter must reproduce the old per-row copy
        loop byte for byte, including the zero right-fill."""
        import pyarrow as pa
        import pyarrow.ipc as ipc

        rng = np.random.default_rng(5)
        ids = [rng.integers(0, 1000, size=n).tolist()
               for n in (3, 7, 1, 5, 7, 2)]
        mask = [[1] * len(row) for row in ids]
        table = pa.table({
            "input_ids": pa.array(ids, type=pa.list_(pa.int32())),
            "attention_mask": pa.array(mask, type=pa.list_(pa.int8())),
        })
        split_dir = tmp_path / "ragged"
        split_dir.mkdir(parents=True)
        with ipc.new_stream(str(split_dir / "data-00000-of-00001.arrow"),
                            table.schema) as w:
            w.write_table(table)
        from hyperion_tpu.data.text import load_arrow_split

        s = load_arrow_split(split_dir)
        width = max(len(r) for r in ids)
        expected = np.zeros((len(ids), width), np.int32)
        for i, row in enumerate(ids):  # the old loop, as the oracle
            expected[i, : len(row)] = row
        np.testing.assert_array_equal(s.input_ids, expected)
        expected_mask = np.zeros((len(ids), width), np.int8)
        for i, row in enumerate(mask):
            expected_mask[i, : len(row)] = row
        np.testing.assert_array_equal(s.attention_mask, expected_mask)

    def test_arrow_reader_against_reference_format(self, tmp_path):
        # Write an HF-datasets-style arrow stream file and read it back.
        import pyarrow as pa
        import pyarrow.ipc as ipc

        ids = [[1, 2, 3, GPT2_EOS_ID], [4, 5, GPT2_EOS_ID, GPT2_EOS_ID]]
        mask = [[1, 1, 1, 0], [1, 1, 0, 0]]
        table = pa.table(
            {
                "input_ids": pa.array(ids, type=pa.list_(pa.int32())),
                "attention_mask": pa.array(mask, type=pa.list_(pa.int8())),
            }
        )
        split_dir = tmp_path / "wikitext2_tokenized" / "train"
        split_dir.mkdir(parents=True)
        with ipc.new_stream(str(split_dir / "data-00000-of-00001.arrow"), table.schema) as w:
            w.write_table(table)
        d = load_wikitext2(tmp_path, splits=("train",))
        assert d["train"].source.startswith("arrow:")
        np.testing.assert_array_equal(d["train"].input_ids, np.asarray(ids, np.int32))


class TestVisionPipeline:
    def test_synthetic_learnable_structure(self):
        s = synthetic_cifar_split(256, seed=0)
        s.verify()
        assert s.images.shape == (256, 32, 32, 3)  # NHWC
        # class templates must be distinguishable: nearest-template
        # classification on clean means should beat chance easily
        means = np.stack([s.images[s.labels == c].mean(0) for c in range(10)])
        d = ((s.images[:, None] - means[None]) ** 2).reshape(256, 10, -1).sum(-1)
        acc = (d.argmin(1) == s.labels).mean()
        assert acc > 0.5, f"synthetic classes not separable (acc={acc})"

    def test_load_fallback(self, tmp_path):
        d = load_cifar10(tmp_path, synthetic_sizes={"train": 64, "test": 32})
        assert len(d["train"]) == 64 and len(d["test"]) == 32

    def test_pickle_batch_reader(self, tmp_path):
        import pickle

        d = tmp_path / "cifar-10-batches-py"
        d.mkdir()
        rng = np.random.default_rng(0)
        for i in range(1, 6):
            batch = {
                b"data": rng.integers(0, 256, size=(20, 3072), dtype=np.uint8),
                b"labels": rng.integers(0, 10, size=20).tolist(),
            }
            (d / f"data_batch_{i}").write_bytes(pickle.dumps(batch))
        (d / "test_batch").write_bytes(
            pickle.dumps(
                {
                    b"data": rng.integers(0, 256, size=(10, 3072), dtype=np.uint8),
                    b"labels": rng.integers(0, 10, size=10).tolist(),
                }
            )
        )
        out = load_cifar10(tmp_path)
        assert out["train"].images.shape == (100, 32, 32, 3)
        assert out["test"].images.shape == (10, 32, 32, 3)
        assert out["train"].images.max() <= 1.0 and out["train"].images.min() >= -1.0


class TestShardedBatches:
    def test_shards_over_mesh(self, mesh8):
        s = synthetic_lm_split(40, seq_len=8)
        it = ShardedBatches(s.arrays(), global_batch=16, mesh=mesh8, seed=3)
        assert len(it) == 2  # 40 // 16, tail dropped
        batches = list(it.epoch(0))
        assert len(batches) == 2
        b = batches[0]["input_ids"]
        assert b.shape == (16, 8)
        # batch split over data(2) x fsdp(4) = 8 shards of 2 rows
        assert b.addressable_shards[0].data.shape == (2, 8)

    def test_epoch_shuffle_deterministic_and_distinct(self, mesh8):
        s = synthetic_lm_split(32, seq_len=4)
        it = ShardedBatches(s.arrays(), 32, mesh8, seed=7)
        a = np.asarray(next(it.epoch(0))["input_ids"])
        a2 = np.asarray(next(it.epoch(0))["input_ids"])
        b = np.asarray(next(it.epoch(1))["input_ids"])
        np.testing.assert_array_equal(a, a2)  # set_epoch determinism
        assert not np.array_equal(a, b)  # different epoch, different order

    def test_no_shuffle_preserves_order(self, mesh8):
        s = synthetic_lm_split(16, seq_len=4)
        it = ShardedBatches(s.arrays(), 8, mesh8, shuffle=False)
        b = np.asarray(next(it.epoch(0))["input_ids"])
        np.testing.assert_array_equal(b, s.input_ids[:8])

    def test_ragged_raises(self, mesh8):
        with pytest.raises(ValueError, match="ragged"):
            ShardedBatches(
                {"a": np.zeros((10, 2)), "b": np.zeros((11, 2))}, 2, mesh8
            )

    def test_batch_too_big_raises(self, mesh8):
        s = synthetic_lm_split(8, seq_len=4)
        with pytest.raises(ValueError, match="global_batch"):
            ShardedBatches(s.arrays(), 16, mesh8)


class TestPrepareCifar:
    def test_cifar_prepare_roundtrip(self, tmp_path):
        from hyperion_tpu.data.prepare import prepare_cifar
        from hyperion_tpu.data.vision import load_cifar10

        prepare_cifar(tmp_path, verbose=False)
        assert (tmp_path / "cifar10_prepared" / "train.images.rio").exists()
        # loader must now prefer the recordio output
        splits = load_cifar10(tmp_path, synthetic_sizes={"train": 64})
        assert splits["train"].source.startswith("recordio")
        assert len(splits["train"]) == 5000  # the prepared (full) split
        splits["train"].verify()
