import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.precision import Policy, apply_remat, get_policy
from hyperion_tpu.precision.policy import POLICIES


class TestPolicy:
    def test_registry(self):
        for name in ("fp32", "bf16", "bf16_full"):
            assert get_policy(name).name == name
        with pytest.raises(ValueError):
            get_policy("fp16_scaled")

    def test_bf16_casts_compute_keeps_master_fp32(self):
        p = get_policy("bf16")
        tree = {"w": jnp.ones((2, 2), jnp.float32), "step": jnp.array(3, jnp.int32)}
        c = p.cast_to_compute(tree)
        assert c["w"].dtype == jnp.bfloat16
        assert c["step"].dtype == jnp.int32  # non-float leaves untouched
        assert p.cast_to_param(c)["w"].dtype == jnp.float32

    def test_bf16_full_matches_fsdp_mixed_precision(self):
        p = get_policy("bf16_full")
        assert p.param_dtype == p.compute_dtype == p.reduce_dtype == jnp.bfloat16

    def test_identity_passthrough(self):
        assert isinstance(get_policy(POLICIES["fp32"]), Policy)


class TestRemat:
    def test_grad_equivalence(self):
        def f(x):
            for _ in range(3):
                x = jnp.tanh(x @ x)
            return x.sum()

        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)), jnp.float32)
        g_plain = jax.grad(f)(x)
        for policy in ("full", "dots", "dots_no_batch"):
            g_remat = jax.grad(apply_remat(f, policy))(x)
            np.testing.assert_allclose(g_plain, g_remat, rtol=1e-5)

    def test_none_is_identity(self):
        f = lambda x: x * 2
        assert apply_remat(f, "none") is f

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            apply_remat(lambda x: x, "everything")
