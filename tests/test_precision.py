import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.precision import Policy, apply_remat, get_policy
from hyperion_tpu.precision.policy import POLICIES


class TestPolicy:
    def test_registry(self):
        for name in ("fp32", "bf16", "bf16_full"):
            assert get_policy(name).name == name
        with pytest.raises(ValueError):
            get_policy("fp16_scaled")

    def test_bf16_casts_compute_keeps_master_fp32(self):
        p = get_policy("bf16")
        tree = {"w": jnp.ones((2, 2), jnp.float32), "step": jnp.array(3, jnp.int32)}
        c = p.cast_to_compute(tree)
        assert c["w"].dtype == jnp.bfloat16
        assert c["step"].dtype == jnp.int32  # non-float leaves untouched
        assert p.cast_to_param(c)["w"].dtype == jnp.float32

    def test_bf16_full_matches_fsdp_mixed_precision(self):
        p = get_policy("bf16_full")
        assert p.param_dtype == p.compute_dtype == p.reduce_dtype == jnp.bfloat16

    def test_identity_passthrough(self):
        assert isinstance(get_policy(POLICIES["fp32"]), Policy)


class TestRemat:
    def test_grad_equivalence(self):
        def f(x):
            for _ in range(3):
                x = jnp.tanh(x @ x)
            return x.sum()

        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)), jnp.float32)
        g_plain = jax.grad(f)(x)
        for policy in ("full", "dots", "dots_no_batch"):
            g_remat = jax.grad(apply_remat(f, policy))(x)
            # rtol 2e-5, not 1e-5: remat recomputes the forward in a
            # differently-fused program, so fp32 reassociation legally
            # moves single elements by ~1 ulp of the operand scale
            # (observed 1.03e-5 relative on this CPU backend — a flake
            # at 1e-5, not a remat bug; equivalence here means "same
            # math", not "same instruction order")
            np.testing.assert_allclose(g_plain, g_remat, rtol=2e-5)

    def test_none_is_identity(self):
        f = lambda x: x * 2
        assert apply_remat(f, "none") is f

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            apply_remat(lambda x: x, "everything")


class TestRematMemory:
    """VERDICT r2 item 7: the remat policies must demonstrably change
    what the compiler keeps vs recomputes on the GPT-2-shaped LM.

    CPU XLA's buffer assignment barely reflects remat in
    `memory_analysis` (its scheduler keeps similar peaks), so the load-
    bearing assertion is structural: full remat must RE-EXECUTE the
    forward matmuls inside the backward (strictly more `dot` ops in the
    compiled HLO), while the `dots` policy saves matmul outputs (same
    dot count as no-remat). Temp memory is asserted not to regress.
    """

    @staticmethod
    def _compiled(remat):
        from hyperion_tpu.models.transformer_lm import TransformerLM, gpt2_lm_config

        cfg = gpt2_lm_config(
            vocab_size=512, max_len=128, dropout=0.0, remat=remat,
            n_layers=2)
        model = TransformerLM(cfg)
        params = model.init_params(jax.random.key(0), batch=1)
        ids = jnp.zeros((2, 128), jnp.int32)

        def loss(p):
            return model.apply({"params": p}, ids).mean()

        return jax.jit(jax.grad(loss)).lower(params).compile()

    @staticmethod
    def _dot_count(compiled) -> int:
        txt = compiled.as_text()
        return txt.count(" dot(") + txt.count(" dot.")

    @pytest.mark.slow
    def test_full_remat_recomputes_matmuls_in_backward(self):
        plain = self._compiled(False)
        full = self._compiled("full")
        assert self._dot_count(full) > self._dot_count(plain)
        # and recomputation must not cost extra live memory
        assert (full.memory_analysis().temp_size_in_bytes
                <= 1.05 * plain.memory_analysis().temp_size_in_bytes)

    @pytest.mark.slow
    def test_dots_policy_saves_matmul_outputs(self):
        plain = self._compiled(False)
        dots = self._compiled("dots")
        full = self._compiled("full")
        # matmul outputs saved -> no recomputed dots
        assert self._dot_count(dots) == self._dot_count(plain)
        assert self._dot_count(dots) < self._dot_count(full)
