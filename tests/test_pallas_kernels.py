"""Pallas kernel correctness vs the XLA reference formulation.

Runs in interpret mode on the CPU backend (the kernels detect non-TPU
backends themselves), so the same tests validate the real kernels on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.ops.attention import (
    dot_product_attention,
    select_attention_impl,
)
from hyperion_tpu.ops.pallas.flash_attention import (
    default_blocks,
    flash_attention,
)
from hyperion_tpu.ops.pallas.fused_norm import fused_layernorm, fused_rmsnorm


def qkv(shape=(2, 64, 4, 16), seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    return [jax.random.normal(k, shape, dtype) for k in ks]


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_xla(self, causal):
        q, k, v = qkv()
        ref = dot_product_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_kv=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_padding_mask(self):
        q, k, v = qkv()
        mask = np.ones((2, 64), np.int8)
        mask[:, 48:] = 0
        ref = dot_product_attention(q, k, v, causal=True,
                                    padding_mask=jnp.asarray(mask))
        out = flash_attention(q, k, v, causal=True,
                              padding_mask=jnp.asarray(mask),
                              block_q=32, block_kv=32)
        # only compare non-pad query rows (pad rows are don't-care)
        np.testing.assert_allclose(np.asarray(out)[:, :48],
                                   np.asarray(ref)[:, :48],
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_xla(self):
        q, k, v = qkv(shape=(1, 32, 2, 8))

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           block_q=16, block_kv=16) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)

    def test_grad_with_mask_does_not_crash(self):
        q, k, v = qkv(shape=(1, 32, 2, 8))
        mask = jnp.asarray(np.ones((1, 32), np.int8))

        def loss(q):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           padding_mask=mask,
                                           block_q=16, block_kv=16))

        g = jax.grad(loss)(q)
        assert bool(jnp.isfinite(g).all())

    def test_gradients_match_xla_with_padding(self):
        """Padded positions excluded from the loss (as any masked LM
        loss does) — gradients must match the XLA reference."""
        q, k, v = qkv(shape=(2, 32, 2, 8))
        mask_np = np.ones((2, 32), np.int8)
        mask_np[0, 24:] = 0
        mask_np[1, 16:] = 0
        mask = jnp.asarray(mask_np)
        w = jnp.asarray(mask_np, jnp.float32)[:, :, None, None]

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=True, padding_mask=mask,
                                  block_q=16, block_kv=16)
            return jnp.sum((out * w) ** 2)

        def loss_ref(q, k, v):
            out = dot_product_attention(q, k, v, causal=True,
                                        padding_mask=mask)
            return jnp.sum((out * w) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)

    def test_gradients_multiblock_long_seq(self):
        """Causality and accumulation across many kv/q tiles (8x8 grid
        of blocks) — the streaming path the VMEM design exists for."""
        q, k, v = qkv(shape=(1, 256, 2, 16), seed=3)

        def loss_flash(q, k, v):
            out = flash_attention(q, k, v, causal=True,
                                  block_q=32, block_kv=32)
            return jnp.sum(out ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        np.testing.assert_allclose(
            float(loss_flash(q, k, v)), float(loss_ref(q, k, v)), rtol=1e-5
        )
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)

    def test_bf16_grads_finite(self):
        q, k, v = qkv(shape=(1, 64, 2, 16), dtype=jnp.bfloat16)

        def loss(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True,
                                block_q=32, block_kv=32).astype(jnp.float32)
            )

        gs = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g in gs:
            assert g.dtype == jnp.bfloat16
            assert bool(jnp.isfinite(g.astype(jnp.float32)).all())

    def test_model_integration(self):
        """attention_impl='pallas' must be numerically equivalent."""
        from hyperion_tpu.models.transformer_lm import TransformerLM, simple_lm_config

        kw = dict(vocab_size=128, d_model=32, n_heads=2, n_layers=1,
                  ff_dim=64, max_len=32, dropout=0.0)
        xla = TransformerLM(simple_lm_config(attention_impl="xla", **kw))
        pls = TransformerLM(simple_lm_config(attention_impl="pallas", **kw))
        params = xla.init_params(jax.random.key(0))
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 32)),
                          jnp.int32)
        a = xla.apply({"params": params}, ids)
        b = pls.apply({"params": params}, ids)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)

    def test_indivisible_block_falls_back(self):
        # seq 48 doesn't divide the requested 32: _pick_block falls back
        # to a legal tiling (here one 48-wide tile) instead of raising
        q, k, v = qkv(shape=(1, 48, 2, 8))
        out = flash_attention(q, k, v, block_q=32, block_kv=32)
        ref = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, rtol=3e-5)

    def test_wide_single_tile_fallback_warns(self):
        # an indivisible mid-length sequence still runs, but no longer
        # silently: one 1100-wide fp32 logits tile is near the 2048^2
        # VMEM edge the module documents (ADVICE r4)
        from hyperion_tpu.ops.pallas.flash_attention import _pick_block

        with pytest.warns(UserWarning, match="1100-wide tile"):
            assert _pick_block(1100, 1024) == 1100
        # short fallbacks stay silent
        assert _pick_block(48, 32) == 48

    def test_mixed_dtypes_reconciled_to_q(self):
        # bf16 q with fp32 k/v (e.g. a half-converted cache) computes in
        # q's dtype instead of raising — parity with the XLA impl's
        # q-dtype compute (ADVICE r4)
        q, k, v = qkv(shape=(1, 32, 2, 8))
        out = flash_attention(q.astype(jnp.bfloat16), k, v,
                              block_q=16, block_kv=16)
        assert out.dtype == jnp.bfloat16
        ref = flash_attention(q.astype(jnp.bfloat16),
                              k.astype(jnp.bfloat16),
                              v.astype(jnp.bfloat16),
                              block_q=16, block_kv=16)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   atol=1e-5, rtol=1e-5)

    def test_default_blocks_head_dim_aware(self):
        # D=64 keeps the swept 1024x1024; D=128 (Llama) halves block_kv
        # until an on-chip D=128 sweep validates wider (ADVICE r4)
        assert default_blocks(64) == (1024, 1024)
        assert default_blocks(128) == (1024, 512)


class TestAttentionImplAutoSelect:
    """Geometry-aware impl="auto" resolution (VERDICT r4 item 6)."""

    def test_short_seq_keeps_xla(self):
        assert select_attention_impl(128, 64) == "xla"
        assert select_attention_impl(2048, 64) == "xla"

    def test_long_train_gets_pallas(self):
        assert select_attention_impl(4096, 64) == "pallas"
        assert select_attention_impl(16384, 128) == "pallas"

    def test_fwd_mode_crossover_is_higher(self):
        assert select_attention_impl(4096, 64, mode="fwd") == "xla"
        assert select_attention_impl(8192, 64, mode="fwd") == "pallas"

    def test_unprobed_geometry_stays_xla(self):
        assert select_attention_impl(4096, 256) == "xla"       # big head
        assert select_attention_impl(4100, 64) == "xla"        # not 128-mult

    def test_auto_dispatches_through_attention(self):
        # short seq through impl="auto" matches the xla path exactly
        q, k, v = qkv(shape=(1, 32, 2, 8))
        auto = dot_product_attention(q, k, v, causal=True, impl="auto")
        ref = dot_product_attention(q, k, v, causal=True, impl="xla")
        np.testing.assert_allclose(np.asarray(auto), np.asarray(ref))

    def test_tier_default_is_auto(self):
        from hyperion_tpu.config import Config
        from hyperion_tpu.train.trainer import _tier_impls

        cfg = Config()
        cfg.optimization.compile_tier = "jit+pallas"
        assert _tier_impls(cfg)["attention_impl"] == "auto"
        cfg.optimization.attention_impl = "pallas"  # explicit wins
        assert _tier_impls(cfg)["attention_impl"] == "pallas"


class TestFusedLayerNorm:
    def test_matches_lax_layernorm(self):
        x = jax.random.normal(jax.random.key(0), (4, 16, 32))
        w = jax.random.normal(jax.random.key(1), (32,)) + 1.0
        b = jax.random.normal(jax.random.key(2), (32,))
        out = fused_layernorm(x, w, b)
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mean) / jnp.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_residual_fusion(self):
        x = jax.random.normal(jax.random.key(0), (8, 32))
        r = jax.random.normal(jax.random.key(1), (8, 32))
        w = jnp.ones(32)
        b = jnp.zeros(32)
        out = fused_layernorm(x, w, b, residual=r)
        ref = fused_layernorm(x + r, w, b)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_gradients(self):
        x = jax.random.normal(jax.random.key(0), (8, 16))
        w = jnp.ones(16)
        b = jnp.zeros(16)

        def loss(x, w, b):
            return jnp.sum(fused_layernorm(x, w, b) ** 2)

        def ref_loss(x, w, b):
            mean = x.mean(-1, keepdims=True)
            var = x.var(-1, keepdims=True)
            return jnp.sum(((x - mean) / jnp.sqrt(var + 1e-5) * w + b) ** 2)

        ga = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)
        gb = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
        for a, b_ in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=1e-4, rtol=1e-4)

    def test_rmsnorm_matches_reference(self):
        x = jax.random.normal(jax.random.key(0), (4, 16, 32))
        w = jax.random.normal(jax.random.key(1), (32,)) + 1.0
        out = fused_rmsnorm(x, w, eps=1e-5)
        ref = x * jax.lax.rsqrt(jnp.mean(x**2, -1, keepdims=True) + 1e-5) * w
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_rmsnorm_gradients(self):
        x = jax.random.normal(jax.random.key(0), (8, 16))
        w = jnp.ones(16) * 1.5

        def loss(x, w):
            return jnp.sum(fused_rmsnorm(x, w) ** 2)

        def ref_loss(x, w):
            y = x * jax.lax.rsqrt(jnp.mean(x**2, -1, keepdims=True) + 1e-5) * w
            return jnp.sum(y ** 2)

        ga = jax.grad(loss, argnums=(0, 1))(x, w)
        gb = jax.grad(ref_loss, argnums=(0, 1))(x, w)
        for a, b in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_llama_norm_impl_equivalence(self):
        """norm_impl='pallas' must match the XLA RMSNorm in-model."""
        from hyperion_tpu.models.llama import Llama, llama_tiny_config

        xla = Llama(llama_tiny_config(norm_impl="xla"))
        pls = Llama(llama_tiny_config(norm_impl="pallas"))
        params = xla.init_params(jax.random.key(0), seq=32)
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 32)),
                          jnp.int32)
        a = xla.apply({"params": params}, ids)
        b = pls.apply({"params": params}, ids)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)

    def test_lm_full_pallas_tier_equivalence(self):
        """attention_impl + norm_impl both pallas ≡ both xla."""
        from hyperion_tpu.models.transformer_lm import TransformerLM, simple_lm_config

        kw = dict(vocab_size=128, d_model=32, n_heads=2, n_layers=2,
                  ff_dim=64, max_len=32, dropout=0.0)
        xla = TransformerLM(simple_lm_config(**kw))
        pls = TransformerLM(simple_lm_config(
            attention_impl="pallas", norm_impl="pallas", **kw))
        params = xla.init_params(jax.random.key(0))
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 32)),
                          jnp.int32)
        a = xla.apply({"params": params}, ids)
        b = pls.apply({"params": params}, ids)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)

    def test_bf16_stats_in_fp32(self):
        x = (jax.random.normal(jax.random.key(0), (4, 64)) * 100).astype(jnp.bfloat16)
        out = fused_layernorm(x, jnp.ones(64), jnp.zeros(64))
        assert out.dtype == jnp.bfloat16
        # normalized rows: mean ~0, std ~1 even for large-magnitude input
        f = np.asarray(out, np.float32)
        assert abs(f.mean()) < 0.1
        assert abs(f.std() - 1.0) < 0.1


class TestFusedCrossEntropy:
    """fused_softmax_xent vs optax: values, grads, padding, dtypes."""

    def _data(self, n=12, v=300, seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        logits = jnp.asarray(rng.normal(size=(n, v)) * 3, dtype)
        targets = jnp.asarray(rng.integers(0, v, n), jnp.int32)
        return logits, targets

    def test_matches_optax(self):
        import optax

        from hyperion_tpu.ops.pallas.fused_ce import fused_softmax_xent

        logits, targets = self._data()
        ref = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets
        )
        out = fused_softmax_xent(logits, targets)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_odd_shapes_pad_correctly(self):
        """N and V far from tile multiples: padding columns (NEG_INF)
        and rows must not change values."""
        import optax

        from hyperion_tpu.ops.pallas.fused_ce import fused_softmax_xent

        logits, targets = self._data(n=7, v=131)
        ref = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        out = fused_softmax_xent(logits, targets, 4, 64)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_grads_match_optax(self):
        import optax

        from hyperion_tpu.ops.pallas.fused_ce import fused_softmax_xent

        logits, targets = self._data(n=9, v=200)
        w = jnp.asarray(np.random.default_rng(1).random(9), jnp.float32)

        def loss_f(fn):
            return lambda lg: jnp.sum(fn(lg, targets) * w)

        g_ref = jax.grad(loss_f(
            lambda lg, t: optax.softmax_cross_entropy_with_integer_labels(lg, t)
        ))(logits)
        g = jax.grad(loss_f(
            lambda lg, t: fused_softmax_xent(lg, t, 4, 64)
        ))(logits)
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(g_ref), atol=1e-5, rtol=1e-4
        )

    def test_bf16_logits_finite(self):
        from hyperion_tpu.ops.pallas.fused_ce import fused_softmax_xent

        logits, targets = self._data(dtype=jnp.bfloat16)
        out = fused_softmax_xent(logits, targets)
        assert out.dtype == jnp.float32
        assert np.isfinite(np.asarray(out)).all()
        g = jax.grad(lambda lg: fused_softmax_xent(lg, targets).sum())(logits)
        assert g.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(g, np.float32)).all()

    def test_next_token_loss_impl_parity(self):
        from hyperion_tpu.train.losses import next_token_loss

        rng = np.random.default_rng(2)
        logits = jnp.asarray(rng.normal(size=(2, 10, 257)), jnp.float32)
        ids = jnp.asarray(rng.integers(0, 257, (2, 10)), jnp.int32)
        mask = jnp.asarray(rng.random((2, 10)) > 0.2, jnp.int8)
        ref = next_token_loss(logits, ids, mask)
        out = next_token_loss(logits, ids, mask, impl="pallas")
        np.testing.assert_allclose(float(out), float(ref), atol=1e-5, rtol=1e-5)


class TestPagedAttention:
    """Paged decode kernel (ops/pallas/paged_attention) vs the gather
    path it replaces: the kernel walks the [S, MB] block table in-kernel
    via scalar prefetch; the oracle gathers pool[bt] into the contiguous
    view and runs the same masked grouped attention the model uses. The
    online softmax reorders the fp reduction, so parity is
    pinned-tolerance (fp32: 2e-5; observed ~2e-7 at op level), not
    bit-exact — the bound the kernel docstring documents."""

    def _ref(self, q, kp, vp, bt, base):
        # the llama.py gather read, shape-for-shape
        from hyperion_tpu.models.llama import _grouped_cache_attention

        B, T, H, D = q.shape
        Hkv, bs, MB = kp.shape[2], kp.shape[1], bt.shape[1]
        L = MB * bs
        vk = kp[bt].reshape(B, L, Hkv, D)
        vv = vp[bt].reshape(B, L, Hkv, D)
        kv_pos = jax.lax.broadcasted_iota(jnp.int32, (T, L), 1)
        q_pos = base[:, None, None] + \
            jax.lax.broadcasted_iota(jnp.int32, (T, L), 0)[None]
        return _grouped_cache_attention(q, vk, vv, kv_pos[None] <= q_pos,
                                        H // Hkv)

    def _geometry(self, B, T, H, Hkv, D=16, bs=4, MB=8, seed=0,
                  share_prefix=False):
        """Pools + per-row block chains at random depths; unmapped tail
        entries stay 0 (the null block), exactly as serve/blocks.py
        hands them to the model."""
        NB = B * MB + 1
        ks = jax.random.split(jax.random.key(seed), 3)
        q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
        kp = jax.random.normal(ks[1], (NB, bs, Hkv, D), jnp.float32)
        vp = jax.random.normal(ks[2], (NB, bs, Hkv, D), jnp.float32)
        rng = np.random.default_rng(seed)
        bt = np.zeros((B, MB), np.int32)
        base = rng.integers(0, MB * bs - T + 1, B).astype(np.int32)
        for b in range(B):
            n = (int(base[b]) + T + bs - 1) // bs
            bt[b, :n] = rng.permutation(np.arange(1, NB))[:n]
        if share_prefix:
            # COW-shared prefix: every row's first block is the SAME
            # physical block (a radix-cache hit before any divergence)
            bt[:, 0] = bt[0, 0]
        return q, kp, vp, jnp.asarray(bt), jnp.asarray(base)

    def _check(self, *geo, **kw):
        from hyperion_tpu.ops.pallas.paged_attention import paged_attention

        q, kp, vp, bt, base = self._geometry(*geo, **kw)
        out = paged_attention(q, kp, vp, bt, base)
        ref = self._ref(q, kp, vp, bt, base)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_sequential_decode(self):        # [S, 1]
        self._check(3, 1, 4, 4)

    def test_speculative_verify(self):       # [S, k+1]
        self._check(3, 5, 4, 4, seed=1)

    def test_chunked_prefill(self):          # [1, C] at a mid-chain base
        self._check(1, 16, 4, 4, seed=2)

    def test_gqa_groups(self):               # rep = 4: 8 q heads, 2 kv
        self._check(2, 3, 8, 2, seed=3)

    def test_prefix_shared_chain(self):
        self._check(3, 2, 4, 4, seed=4, share_prefix=True)

    def test_null_block_garbage_never_leaks(self):
        """Poison the null block with huge garbage: outputs must be
        BIT-identical to a zeroed null block — masked positions
        underflow to exactly 0 weight (finite NEG_INF), and blocks past
        the frontier are skipped outright."""
        from hyperion_tpu.ops.pallas.paged_attention import paged_attention

        q, kp, vp, bt, base = self._geometry(3, 2, 4, 4, seed=5)
        assert int(np.asarray(bt == 0).sum()) > 0  # unmapped tails exist
        clean = paged_attention(q, kp, vp, bt, base)
        poisoned = paged_attention(
            q, kp.at[0].set(1e4), vp.at[0].set(-1e4), bt, base)
        np.testing.assert_array_equal(np.asarray(clean),
                                      np.asarray(poisoned))

    def test_model_level_matches_gather(self):
        """Full Llama tiny (GQA rep 2) through all three engine window
        shapes, caches threaded forward per impl: chunked prefill
        [1, C], speculative verify [S, k+1] at per-row depths, then
        sequential decode [S, 1]. Logits agree to the pinned fp32
        bound at every step; caches agree to the same bound (layer 0's
        scatter is shared code bit-for-bit, but deeper layers' K/V
        projections consume the previous layer's attention output,
        which carries the online-softmax reordering delta)."""
        import dataclasses

        from hyperion_tpu.models.llama import (
            Llama, init_paged_cache, llama_tiny_config)

        cfg = llama_tiny_config(n_kv_heads=2, max_len=16)
        bs, B = 4, 2
        MB = cfg.max_len // bs
        m_g = Llama(cfg)
        m_p = Llama(dataclasses.replace(cfg, paged_attn_impl="pallas"))
        params = m_g.init(jax.random.key(0),
                          jnp.zeros((1, 4), jnp.int32))["params"]
        caches = {"gather": init_paged_cache(cfg, B * MB + 1, bs),
                  "pallas": init_paged_cache(cfg, B * MB + 1, bs)}
        rng = np.random.default_rng(0)
        bt = np.zeros((B, MB), np.int32)
        bt[:] = rng.permutation(np.arange(1, B * MB + 1)).reshape(B, MB)
        bt = jnp.asarray(bt)

        def step(ids, index, tables):
            outs = {}
            for name, model in (("gather", m_g), ("pallas", m_p)):
                logits, caches[name] = model.apply(
                    {"params": params}, ids, cache=caches[name],
                    cache_index=index, block_tables=tables)
                outs[name] = logits
            np.testing.assert_allclose(
                np.asarray(outs["pallas"]), np.asarray(outs["gather"]),
                atol=2e-5, rtol=2e-5)
            for lg, lp in zip(caches["gather"], caches["pallas"]):
                np.testing.assert_allclose(np.asarray(lg["k"]),
                                           np.asarray(lp["k"]),
                                           atol=2e-5, rtol=2e-5)
                np.testing.assert_allclose(np.asarray(lg["v"]),
                                           np.asarray(lp["v"]),
                                           atol=2e-5, rtol=2e-5)

        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 6)),
                          jnp.int32)
        step(ids, 0, bt[:1])                              # [1, C] chunk
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, 3)),
                          jnp.int32)
        step(ids, jnp.asarray([6, 0], jnp.int32), bt)     # [S, k+1]
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, 1)),
                          jnp.int32)
        step(ids, jnp.asarray([9, 3], jnp.int32), bt)     # [S, 1]
