"""bench.py driver-harness logic tests (no subprocesses, no backend).

The headline bench is the ONE number the round driver records; its
probe/retry/deadline chain (VERDICT r4 item 4) must behave under every
tunnel condition. These tests monkeypatch the child-runner and the
clock, so each scenario runs in microseconds and asserts on the single
JSON line main() prints.
"""

from __future__ import annotations

import importlib.util
import json
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture()
def bench(monkeypatch):
    """Fresh bench module (repo-root bench.py is not a package member).

    _last_committed is stubbed out: it shells out to git, and the real
    subprocess wait loop calls time.sleep — which these tests patch to
    advance the FAKE clock, corrupting the wall-time accounting."""
    spec = importlib.util.spec_from_file_location("bench_r5", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod._last_committed = lambda: None
    return mod


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def monotonic(self):
        return self.t

    def sleep(self, s):
        self.t += s


@pytest.fixture()
def clock(monkeypatch):
    c = FakeClock()
    monkeypatch.setattr(time, "monotonic", c.monotonic)
    monkeypatch.setattr(time, "sleep", c.sleep)
    return c


def run_main(bench, capsys) -> dict:
    try:
        bench.main()
    except SystemExit as e:
        assert e.code == 0  # a parseable failure line beats a nonzero rc
    lines = capsys.readouterr().out.strip().splitlines()
    return json.loads(lines[-1])


GOOD_PROBE = {"ok": True, "platform": "tpu", "device_kind": "v5e"}
CPU_PROBE = {"ok": False, "platform": "cpu", "device_kind": "cpu"}
GOOD_PIPELINE = {"sync_batches_per_s": 300.0,
                 "prefetch_batches_per_s": 360.0, "speedup": 1.2}
GOOD_SERVING = {"tokens_per_s": 650.0, "ttft_p50_ms": 12.0,
                "ttft_p99_ms": 40.0, "reject_rate": 0.0,
                "completed": 32, "rejected": 0,
                # tiered KV cache (PR 20): the @rehit dimension's tier
                # keys ride the serving row top-level (the ON point's
                # values) plus the off/host sub-rows
                "tier_hits_device": 20, "tier_hits_host": 6,
                "tier_miss": 6, "tier_hit_rate_host": 0.1875,
                "restore_bytes_per_s": 5.0e6, "host_cache_mb": 8,
                "rehit": {"off": {"tier_hits_host": 0,
                                  "prefill_tokens_saved": 448},
                          "host": {"tier_hits_host": 6,
                                   "prefill_tokens_saved": 832}}}
GOOD_SCALE = {"replicas": 2, "tokens_per_s_1r": 400.0,
              "tokens_per_s": 700.0, "scaleup": 1.75,
              "request_share": {"0": 0.5, "1": 0.5}, "fairness": 1.0,
              "affinity_hit_rate": 0.6, "completed": 16,
              "router_overhead_p99_ms": 3.5, "failover_gap_p99_ms": 0.0}
GOOD_FLEET_SIM = {"sim_herd_shed_rate": 0.2,
                  "sim_herd_completed_rate": 0.7,
                  "sim_herd_interactive_ttft_p99_ms": 400.0,
                  "sim_herd_alerts_raised": 3.0,
                  "sim_herd_duplicate_tokens": 0.0,
                  "sim_herd_ok": True, "sim_herd_wall_s": 5.0,
                  "sim_failover_completed_rate": 1.0,
                  "sim_failover_interactive_ttft_p99_ms": 250.0,
                  "sim_failover_gap_p99_ms": 1200.0,
                  "sim_failover_steer_reversals": 0.0,
                  "sim_failover_duplicate_tokens": 0.0,
                  "sim_failover_ok": True, "sim_failover_wall_s": 3.0}
GOOD_DECODE_ATTN = {"decode_attn_tokens_per_s": 1500.0,
                    "decode_attn_gather_tokens_per_s": 23000.0,
                    "decode_attn_recompiles": 0,
                    "decode_attn_speedup": 0.065,
                    "decode_attn_max_abs_err": 1.3e-07,
                    "kernel_rev": 1}
GOOD_MEASUREMENT = {
    "tflops": 150.0, "per_iter_ms": 7.0, "amortized_ms": 7.0,
    "dispatch_overhead_ms": 60.0, "chain_lengths": [16, 48],
    "peak_tflops": 197.0, "mfu": 0.76, "scaling_ratio_vs_half_n": 7.9,
    "plausible": True, "checks": {}, "platform": "tpu", "device_kind": "v5e",
}


def make_runner(bench, clock, script):
    """script: mode-prefix -> (burn_seconds, result, err). Records calls."""
    calls = []

    def _run(mode, timeout_s, env=None):
        calls.append((mode, timeout_s))
        assert timeout_s > 0, f"non-positive child timeout for {mode}"
        burn, result, err = script[mode]
        clock.t += min(burn, timeout_s)
        if burn > timeout_s:
            return None, f"{mode} timed out after {timeout_s}s"
        return result, err

    return _run, calls


class TestBenchMain:
    def test_healthy_tunnel_publishes_live_value(self, bench, clock, capsys,
                                                 monkeypatch):
        runner, calls = make_runner(bench, clock, {
            "--child-probe": (30, GOOD_PROBE, ""),
            "--child-matmul": (200, GOOD_MEASUREMENT, ""),
            "--child-lm-step": (100, {"lm_step_ms": 30.0,
                                      "lm_tokens_per_s": 1e5}, ""),
            "--child-input-pipeline": (30, GOOD_PIPELINE, ""),
            "--child-serving": (30, GOOD_SERVING, ""),
            "--child-serving-scale": (40, GOOD_SCALE, ""),
            "--child-fleet-sim": (10, GOOD_FLEET_SIM, ""),
            "--child-decode-attention": (10, GOOD_DECODE_ATTN, ""),
        })
        monkeypatch.setattr(bench, "_run_child", runner)
        out = run_main(bench, capsys)
        assert out["value"] == 150.0
        assert out["platform"] == "tpu"
        assert "extra" in out and "lm_step_ms" in out["extra"]
        assert out["input_pipeline"]["speedup"] == 1.2
        assert out["serving"]["tokens_per_s"] == 650.0
        assert out["serving_scale"]["scaleup"] == 1.75
        assert out["serving_scale"]["fairness"] == 1.0
        # the cross-process keys `obs diff` gates must ride the row
        assert out["serving_scale"]["router_overhead_p99_ms"] == 3.5
        assert out["serving_scale"]["failover_gap_p99_ms"] == 0.0
        # the flight-simulator row rides under its canonical diff keys
        assert out["fleet_sim"]["sim_herd_completed_rate"] == 0.7
        assert out["fleet_sim"]["sim_failover_duplicate_tokens"] == 0.0
        # the paged-attention probe row too, canonical names included
        assert out["decode_attention"]["decode_attn_tokens_per_s"] == 1500.0
        assert out["decode_attention"]["decode_attn_recompiles"] == 0
        # tiered-KV tier keys (the @rehit dimension) ride the serving
        # row where obs diff's normalize() reads them
        assert out["serving"]["tier_hit_rate_host"] == 0.1875
        assert out["serving"]["rehit"]["host"]["tier_hits_host"] == 6

    def test_dead_tunnel_emits_failure_with_sanity(self, bench, clock,
                                                   capsys, monkeypatch):
        # every probe hangs to its timeout; the blind attempt hangs too;
        # the cpu sanity row still lands and the line still prints
        runner, calls = make_runner(bench, clock, {
            "--child-probe": (10_000, None, ""),
            "--child-matmul": (10_000, None, ""),
            "--child-cpu-sanity": (60, {"cpu_matmul_1024_tflops": 0.1}, ""),
            "--child-input-pipeline": (30, GOOD_PIPELINE, ""),
            "--child-serving": (30, GOOD_SERVING, ""),
            "--child-serving-scale": (40, GOOD_SCALE, ""),
            "--child-fleet-sim": (10, GOOD_FLEET_SIM, ""),
            "--child-decode-attention": (10, GOOD_DECODE_ATTN, ""),
        })
        monkeypatch.setattr(bench, "_run_child", runner)
        out = run_main(bench, capsys)
        assert out["value"] == 0.0
        # hung probes hand over to the blind attempt, whose (more
        # specific) timeout becomes the recorded error
        assert "timed out" in out["error"]
        assert out["cpu_sanity"]["cpu_matmul_1024_tflops"] == 0.1
        # the chip-free input-pipeline and serving rows ride the
        # failure line too, budget permitting — history stays
        # continuous on dead rounds
        assert "input_pipeline" in out
        assert "serving" in out
        assert "serving_scale" in out
        assert "decode_attention" in out
        # the tier keys ride the FAILURE line too — the tiered-KV
        # trajectory stays continuous across dead rounds
        assert out["serving"]["tier_hit_rate_host"] == 0.1875
        # total simulated wall time stayed inside the deadline
        assert clock.t - 1000.0 <= bench.DEADLINE_S

    def test_cpu_fallback_probe_blocks_measurement(self, bench, clock,
                                                   capsys, monkeypatch):
        # probes ANSWER but report platform=cpu: the blind attempt must
        # NOT run (it would measure the host), and the record says why
        runner, calls = make_runner(bench, clock, {
            "--child-probe": (20, CPU_PROBE, ""),
            "--child-cpu-sanity": (60, {"cpu_matmul_1024_tflops": 0.1}, ""),
            "--child-input-pipeline": (30, GOOD_PIPELINE, ""),
            "--child-serving": (30, GOOD_SERVING, ""),
            "--child-serving-scale": (40, GOOD_SCALE, ""),
            "--child-fleet-sim": (10, GOOD_FLEET_SIM, ""),
            "--child-decode-attention": (10, GOOD_DECODE_ATTN, ""),
        })
        monkeypatch.setattr(bench, "_run_child", runner)
        out = run_main(bench, capsys)
        assert out["value"] == 0.0
        assert not any(m == "--child-matmul" for m, _ in calls)
        assert out["probe"]["platform"] == "cpu"

    def test_slow_init_gets_blind_attempt(self, bench, clock, capsys,
                                          monkeypatch):
        # probes time out (init slower than the probe window) but the
        # direct measurement succeeds — the old pre-probe behavior that
        # must survive for live-but-slow tunnels
        state = {"n": 0}

        def _run(mode, timeout_s, env=None):
            assert timeout_s > 0
            if mode == "--child-probe":
                clock.t += timeout_s
                return None, f"{mode} timed out after {timeout_s}s"
            if mode == "--child-matmul":
                clock.t += 300
                return GOOD_MEASUREMENT, ""
            clock.t += 10
            return None, "skipped"

        monkeypatch.setattr(bench, "_run_child", _run)
        out = run_main(bench, capsys)
        assert out["value"] == 150.0

    def test_lifecycle_events_stream(self, bench, clock, capsys,
                                     monkeypatch, tmp_path):
        # with HYPERION_TELEMETRY pointed at a file, the probe/retry/
        # deadline chain streams obs events alongside the final JSON line
        tele = tmp_path / "telemetry.jsonl"
        monkeypatch.setenv("HYPERION_TELEMETRY", str(tele))
        runner, calls = make_runner(bench, clock, {
            "--child-probe": (30, GOOD_PROBE, ""),
            "--child-matmul": (200, GOOD_MEASUREMENT, ""),
            "--child-lm-step": (100, {"lm_step_ms": 30.0}, ""),
            "--child-input-pipeline": (30, GOOD_PIPELINE, ""),
            "--child-serving": (30, GOOD_SERVING, ""),
            "--child-serving-scale": (40, GOOD_SCALE, ""),
            "--child-fleet-sim": (10, GOOD_FLEET_SIM, ""),
            "--child-decode-attention": (10, GOOD_DECODE_ATTN, ""),
        })
        monkeypatch.setattr(bench, "_run_child", runner)
        out = run_main(bench, capsys)
        assert out["value"] == 150.0
        names = [json.loads(line)["name"]
                 for line in tele.read_text().splitlines()]
        assert names[0] == "bench_start"
        for expected in ("probe_attempt", "probe_result",
                         "measure_attempt", "measure_result",
                         "input_pipeline", "fleet_sim",
                         "decode_attention", "serving",
                         "publish"):
            assert expected in names, names
        publish = [json.loads(line)
                   for line in tele.read_text().splitlines()][-1]
        assert publish["value"] == 150.0 and publish["plausible"] is True

    def test_all_child_timeouts_positive_under_tight_deadline(
            self, bench, clock, capsys, monkeypatch):
        # shrink the deadline: every child timeout handed out must stay
        # positive (a 0/negative subprocess timeout raises immediately)
        monkeypatch.setattr(bench, "DEADLINE_S", 300)
        runner, calls = make_runner(bench, clock, {
            "--child-probe": (10_000, None, ""),
            "--child-matmul": (10_000, None, ""),
            "--child-cpu-sanity": (10_000, None, ""),
            "--child-input-pipeline": (10_000, None, ""),
            "--child-serving": (10_000, None, ""),
            "--child-serving-scale": (10_000, None, ""),
            "--child-fleet-sim": (10_000, None, ""),
            "--child-decode-attention": (10_000, None, ""),
        })
        monkeypatch.setattr(bench, "_run_child", runner)
        out = run_main(bench, capsys)
        assert out["value"] == 0.0
        assert all(t > 0 for _, t in calls)


class TestChildProbe:
    def test_fp32_checksum_passes_on_cpu(self, bench, capsys, monkeypatch):
        # the checksum must accumulate in fp32: a backend summing the
        # bf16 matmul output in bf16 rounds the 2^24-element reduction
        # and would mark a HEALTHY device ok=false (ADVICE.md). On the
        # CPU backend the allow-cpu escape hatch stands in for the
        # platform gate.
        monkeypatch.setenv("HYPERION_BENCH_ALLOW_CPU", "1")
        bench._child_probe()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["ok"] is True
        expected = 256.0 ** 3
        assert abs(out["checksum"] - expected) / expected < 1e-2
