"""Pipeline parallelism: gpipe schedule vs sequential reference, grads,
sharded train step. Runs on the simulated 8-device CPU mesh."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.models.pipeline_lm import PipelinedLM, PipelineLMConfig
from hyperion_tpu.models.transformer_lm import simple_lm_config
from hyperion_tpu.runtime.mesh import (
    AxisName, MeshSpec, activate_mesh, batch_sharding, make_mesh,
)

VOCAB, T, B = 64, 16, 8


def tiny_cfg(n_stages=4, n_micro=4, n_layers=4, dropout=0.0):
    return PipelineLMConfig(
        base=simple_lm_config(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=n_layers,
            ff_dim=64, max_len=T, dropout=dropout,
        ),
        n_stages=n_stages,
        n_microbatches=n_micro,
    )


@pytest.fixture(scope="module")
def mesh_pipe():
    return make_mesh(MeshSpec(data=2, pipe=4))


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    model = PipelinedLM(cfg)
    params = model.init_params(jax.random.key(0))
    ids = np.random.default_rng(0).integers(0, VOCAB, (B, T)).astype(np.int32)
    return model, {"params": params}, jnp.asarray(ids)


class TestGPipeForward:
    def test_matches_sequential(self, mesh_pipe, setup):
        model, variables, ids = setup
        ref = model.apply(variables, ids)  # no active mesh → sequential
        with activate_mesh(mesh_pipe):
            out = model.apply(variables, ids)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_matches_sequential_with_padding(self, mesh_pipe, setup):
        model, variables, ids = setup
        rng = np.random.default_rng(1)
        mask = (rng.random((B, T)) > 0.3).astype(np.int8)
        mask[:, 0] = 1  # never a fully-masked row
        ref = model.apply(variables, ids, padding_mask=mask)
        with activate_mesh(mesh_pipe):
            out = model.apply(variables, ids, padding_mask=mask)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_microbatch_count_independent(self, mesh_pipe, setup):
        model, variables, _ = setup
        ids = jnp.asarray(
            np.random.default_rng(3).integers(0, VOCAB, (16, T)), jnp.int32
        )
        with activate_mesh(mesh_pipe):
            out4 = model.apply(variables, ids)
            model8 = PipelinedLM(tiny_cfg(n_micro=8))
            out8 = model8.apply(variables, ids)
        np.testing.assert_allclose(
            np.asarray(out4), np.asarray(out8), atol=2e-5, rtol=2e-5
        )

    def test_undivisible_microbatch_raises(self, mesh_pipe, setup):
        model, variables, _ = setup
        ids = jnp.zeros((8, T), jnp.int32)
        model8 = PipelinedLM(tiny_cfg(n_micro=8))  # mb=1 < 2 batch shards
        with activate_mesh(mesh_pipe), pytest.raises(ValueError, match="microbatch"):
            model8.apply(variables, ids)

    def test_stage_mesh_mismatch_raises(self, mesh_pipe, setup):
        model2 = PipelinedLM(tiny_cfg(n_stages=2))
        params = model2.init_params(jax.random.key(0))
        ids = jnp.zeros((B, T), jnp.int32)
        with activate_mesh(mesh_pipe), pytest.raises(ValueError, match="stages"):
            model2.apply({"params": params}, ids)


class TestGPipeBackward:
    @pytest.mark.slow
    def test_grads_match_sequential(self, mesh_pipe, setup):
        model, variables, ids = setup

        def loss(params, pipelined):
            ctx = activate_mesh(mesh_pipe) if pipelined else _null()
            with ctx:
                logits = model.apply({"params": params}, ids)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        g_ref = jax.grad(lambda p: loss(p, False))(variables["params"])
        g_pipe = jax.grad(lambda p: loss(p, True))(variables["params"])
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pipe)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-5, rtol=3e-4
            )


class _null:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


class TestPipelineTrainStep:
    @pytest.mark.slow
    def test_full_train_step_sharded(self, mesh_pipe):
        from hyperion_tpu.train import (
            create_train_state, make_optimizer, make_train_step, next_token_loss,
        )

        cfg = tiny_cfg()
        model = PipelinedLM(cfg)
        opt = make_optimizer(1e-3, grad_clip_norm=1.0)
        with activate_mesh(mesh_pipe):
            state, sharding = create_train_state(
                lambda r: {"params": model.init_params(r)}, opt, mesh_pipe,
                jax.random.key(0), policy="fp32", fsdp=False,
            )
            # stacked stage leaves live on the pipe axis
            specs = jax.tree.map(
                lambda s: s.spec, sharding.params["stages"]
            )
            assert all(
                sp[0] == AxisName.PIPE for sp in jax.tree.leaves(
                    specs, is_leaf=lambda x: hasattr(x, "index")
                )
            )

            def loss_fn(params, batch_stats, batch, rngs):
                logits = model.apply(
                    {"params": params}, batch["input_ids"],
                    padding_mask=batch["attention_mask"],
                )
                loss = next_token_loss(
                    logits, batch["input_ids"], batch["attention_mask"]
                )
                return loss, ({"loss": loss}, batch_stats)

            step = make_train_step(loss_fn, opt, sharding)
            ids = np.random.default_rng(2).integers(0, VOCAB, (B, T))
            sh = batch_sharding(mesh_pipe)
            batch = {
                "input_ids": jax.device_put(ids.astype(np.int32), sh),
                "attention_mask": jax.device_put(np.ones((B, T), np.int8), sh),
            }
            state, metrics = step(state, batch, jax.random.key(1))
            assert np.isfinite(float(metrics["loss"]))


class TestPipelineDropout:
    """Per-tick RNG threading: dropout is live, deterministic per key,
    and key-sensitive under the rotating schedule."""

    def _setup(self):
        model = PipelinedLM(tiny_cfg(dropout=0.5))
        params = model.init_params(jax.random.key(0))
        ids = np.random.default_rng(9).integers(0, VOCAB, (B, T)).astype(np.int32)
        return model, {"params": params}, jnp.asarray(ids)

    def test_dropout_applied_and_deterministic(self, mesh_pipe):
        model, variables, ids = self._setup()
        rngs = {"dropout": jax.random.key(42)}
        with activate_mesh(mesh_pipe):
            det = model.apply(variables, ids)
            d1 = model.apply(variables, ids, deterministic=False, rngs=rngs)
            d2 = model.apply(variables, ids, deterministic=False, rngs=rngs)
            d3 = model.apply(
                variables, ids, deterministic=False,
                rngs={"dropout": jax.random.key(43)},
            )
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
        assert not np.allclose(np.asarray(d1), np.asarray(det)), (
            "dropout had no effect under the pipeline"
        )
        assert not np.allclose(np.asarray(d1), np.asarray(d3)), (
            "different dropout keys produced identical outputs"
        )

    def test_dropout_in_fsdp_layers_path(self):
        from hyperion_tpu.parallel.partition import partition_specs

        mesh = make_mesh(MeshSpec(data=1, fsdp=2, pipe=4))
        model, variables, ids = self._setup()
        specs = partition_specs(
            variables["params"], mesh, fsdp=True, fsdp_min_size=2**8
        )
        model.stage_specs = specs["stages"]
        rngs = {"dropout": jax.random.key(7)}
        with activate_mesh(mesh):
            det = model.apply(variables, ids)
            d1 = model.apply(variables, ids, deterministic=False, rngs=rngs)
            d2 = model.apply(variables, ids, deterministic=False, rngs=rngs)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
        assert not np.allclose(np.asarray(d1), np.asarray(det))

    def test_missing_rng_raises(self, mesh_pipe):
        model, variables, ids = self._setup()
        with activate_mesh(mesh_pipe), pytest.raises(ValueError, match="rngs"):
            model.apply(variables, ids, deterministic=False)


class TestGPipeLayersFsdp:
    """FSDP-within-stage (gpipe_apply_layers): stage params stay sharded
    through the shard_map boundary and each layer is gathered on use."""

    def _fsdp_setup(self):
        from hyperion_tpu.parallel.partition import partition_specs

        mesh = make_mesh(MeshSpec(data=1, fsdp=2, pipe=4))
        model = PipelinedLM(tiny_cfg())
        params = model.init_params(jax.random.key(0))
        specs = partition_specs(params, mesh, fsdp=True, fsdp_min_size=2**8)
        model.stage_specs = specs["stages"]
        ids = np.random.default_rng(7).integers(0, VOCAB, (B, T)).astype(np.int32)
        return mesh, model, {"params": params}, jnp.asarray(ids), specs

    def test_stage_specs_keep_fsdp_sharding(self):
        _, _, _, _, specs = self._fsdp_setup()
        flat = jax.tree.leaves(
            specs["stages"], is_leaf=lambda x: hasattr(x, "index")
        )
        assert any(AxisName.FSDP in sp for sp in flat), (
            "no stages leaf claimed the fsdp axis — per-layer gather has "
            "nothing to gather"
        )
        # the layer axis (dim 1) must stay whole for the per-layer scan
        assert all(len(sp) < 2 or sp[1] is None for sp in flat)

    def test_matches_sequential(self):
        mesh, model, variables, ids, _ = self._fsdp_setup()
        seq_model = PipelinedLM(tiny_cfg())  # stage_specs=None → sequential
        ref = seq_model.apply(variables, ids)
        with activate_mesh(mesh):
            out = model.apply(variables, ids)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    @pytest.mark.slow
    def test_grads_match_sequential(self):
        mesh, model, variables, ids, _ = self._fsdp_setup()
        seq_model = PipelinedLM(tiny_cfg())

        def loss(params, m, pipelined):
            ctx = activate_mesh(mesh) if pipelined else _null()
            with ctx:
                logits = m.apply({"params": params}, ids)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        g_ref = jax.grad(lambda p: loss(p, seq_model, False))(variables["params"])
        g_pipe = jax.grad(lambda p: loss(p, model, True))(variables["params"])
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pipe)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-5, rtol=3e-4
            )


class TestGPipeTP:
    """PP+TP stays on the classic whole-stage-gather path: TP-sharded
    stage leaves cannot ride the per-layer gather (the shard_map output
    does not vary over 'model'), so gpipe_apply_layers must refuse them
    with a clear error while plain gpipe_apply executes correctly."""

    def _tp_setup(self):
        from hyperion_tpu.parallel.partition import (
            TRANSFORMER_TP_RULES, partition_specs,
        )

        mesh = make_mesh(MeshSpec(data=2, model=2, pipe=2))
        model = PipelinedLM(tiny_cfg(n_stages=2, n_micro=2))
        params = model.init_params(jax.random.key(0))
        specs = partition_specs(
            params, mesh, tp_rules=TRANSFORMER_TP_RULES, fsdp=False
        )
        ids = np.random.default_rng(11).integers(0, VOCAB, (B, T)).astype(np.int32)
        return mesh, model, {"params": params}, jnp.asarray(ids), specs

    def test_pp_tp_executes_via_whole_stage_path(self):
        mesh, model, variables, ids, _ = self._tp_setup()
        assert model.stage_specs is None  # trainer keeps TP off this path
        seq_model = PipelinedLM(tiny_cfg(n_stages=2, n_micro=2))
        ref = seq_model.apply(variables, ids)
        with activate_mesh(mesh):
            out = model.apply(variables, ids)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_layers_path_rejects_tp_specs(self):
        mesh, model, variables, ids, specs = self._tp_setup()
        model.stage_specs = specs["stages"]
        with activate_mesh(mesh), pytest.raises(ValueError, match="whole-stage"):
            model.apply(variables, ids)


class TestPartitionSpecs:
    def test_stages_claim_pipe_axis(self, mesh_pipe):
        from hyperion_tpu.parallel.partition import partition_specs

        model = PipelinedLM(tiny_cfg())
        params = jax.eval_shape(
            lambda r: model.init_params(r), jax.random.key(0)
        )
        from flax import traverse_util
        from jax.sharding import PartitionSpec

        specs = partition_specs(params, mesh_pipe, fsdp=False)
        flat = traverse_util.flatten_dict(
            specs, sep="/", is_leaf=lambda _, v: isinstance(v, PartitionSpec)
        )
        # any stages leaf: first axis pipe; embeddings replicated
        stage_specs = [v for k, v in flat.items() if "stages/" in k]
        assert stage_specs and all(
            sp and sp[0] == AxisName.PIPE for sp in stage_specs
        )
        assert flat["tok_emb/embedding"] == PartitionSpec()

    def test_tp_rules_shift_past_stacking_dims(self):
        """PP+TP: TP templates anchor on the LAYER's dims, so on stacked
        [S, lps, ...] leaves they must shift right past stage/layer dims
        (regression: 'model' used to land on the stage axis)."""
        from hyperion_tpu.parallel.partition import (
            TRANSFORMER_TP_RULES, partition_specs,
        )

        mesh = make_mesh(MeshSpec(data=2, model=2, pipe=2))
        model = PipelinedLM(tiny_cfg(n_stages=2))
        params = jax.eval_shape(
            lambda r: model.init_params(r), jax.random.key(0)
        )
        specs = partition_specs(
            params, mesh, tp_rules=TRANSFORMER_TP_RULES, fsdp=False
        )
        from flax import traverse_util
        from jax.sharding import PartitionSpec

        flat = traverse_util.flatten_dict(
            specs, sep="/", is_leaf=lambda _, v: isinstance(v, PartitionSpec)
        )
        qk = flat["stages/attn/q_proj/kernel"]  # [S, lps, d, H, hd]
        assert qk[0] == AxisName.PIPE
        assert AxisName.MODEL in qk and qk.index(AxisName.MODEL) == 3
        qb = flat["stages/attn/q_proj/bias"]  # [S, lps, H, hd]
        assert qb[0] == AxisName.PIPE and qb[2] == AxisName.MODEL
