"""checkpoint/ — dedicated coverage for io.py + integrity.py.

The module had no test file of its own (round-trip coverage lived in
test_train.py); this one pins the verified-resume contract: every save
commits a manifest, restore walks back to the newest verified step
quarantining failures, prune never deletes the newest verified dir,
and the `health/` evidence subdir is invisible to root-level scans.
"""

import json

import jax
import numpy as np
import pytest

from hyperion_tpu import checkpoint as ckpt
from hyperion_tpu.checkpoint import integrity
from hyperion_tpu.checkpoint.integrity import MANIFEST_NAME, REASON_NAME
from hyperion_tpu.models.transformer_lm import TransformerLM, simple_lm_config
from hyperion_tpu.train.state import create_train_state, make_optimizer
from hyperion_tpu.utils import retry as retry_mod


@pytest.fixture(scope="module")
def state(mesh8):
    cfg = simple_lm_config(vocab_size=64, d_model=16, n_heads=2, n_layers=1,
                           ff_dim=32, max_len=8, dropout=0.0)
    model = TransformerLM(cfg)
    st, _ = create_train_state(
        lambda r: {"params": model.init_params(r)}, make_optimizer(1e-2),
        mesh8, jax.random.key(0), policy="fp32",
    )
    return st


def corrupt_payload(step_dir):
    """Truncate the largest non-manifest file — the partial-write shape
    a mid-save crash leaves."""
    payload = max(
        (p for p in step_dir.rglob("*")
         if p.is_file() and p.name != MANIFEST_NAME),
        key=lambda p: p.stat().st_size,
    )
    size = payload.stat().st_size
    with payload.open("r+b") as f:
        f.truncate(size // 2)
    return payload


class TestSaveRestore:
    def test_roundtrip(self, state, tmp_path):
        path = ckpt.save(tmp_path / "ck", state)
        assert path.exists()
        restored = ckpt.restore(tmp_path / "ck", state)
        assert int(restored.step) == int(state.step)
        for a, b in zip(jax.tree.leaves(restored.params),
                        jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))
        # sharding preserved: restore targets the template's layout
        assert restored.params["tok_emb"]["embedding"].sharding.spec == \
            state.params["tok_emb"]["embedding"].sharding.spec

    def test_restore_empty_dir_is_fresh_run(self, state, tmp_path):
        assert ckpt.restore(tmp_path / "nothing", state) is None

    def test_save_writes_committing_manifest(self, state, tmp_path):
        path = ckpt.save(tmp_path / "ck", state)
        m = json.loads((path / MANIFEST_NAME).read_text())
        assert m["step"] == int(state.step)
        assert m["kernel_rev"] is not None
        assert m["mesh_shape"]["data"] == 2 and m["mesh_shape"]["fsdp"] == 4
        listed = {f["path"] for f in m["files"]}
        on_disk = {p.relative_to(path).as_posix() for p in path.rglob("*")
                   if p.is_file() and p.name != MANIFEST_NAME}
        assert listed == on_disk and listed
        assert all(f["sha256"] and f["bytes"] >= 0 for f in m["files"])
        assert integrity.verify(path) == (True, "ok")

    def test_save_retries_transient_io(self, state, tmp_path):
        calls = {"n": 0}

        def flaky(tag):
            if tag == "ckpt_save":
                calls["n"] += 1
                if calls["n"] == 1:
                    raise OSError("first attempt eats a storage blip")

        retry_mod.set_fault_injector(flaky)
        try:
            path = ckpt.save(tmp_path / "ck", state)
        finally:
            retry_mod.set_fault_injector(None)
        assert calls["n"] == 2  # failed once, retried, committed
        assert integrity.verify(path)[0]


class TestAsyncSave:
    """save(wait=False): training-side overlap with a commit point that
    alone decides when a manifest (= verification) exists."""

    def test_manifest_lands_only_at_wait_pending(self, state, tmp_path):
        from hyperion_tpu.checkpoint import io

        path = ckpt.save(tmp_path / "ck", state, wait=False)
        # the dispatch returned; orbax is still staging in a tmp dir
        # (or just finished) — either way the commit point has not run,
        # so no manifest may exist yet
        assert not (path / MANIFEST_NAME).exists()
        committed = ckpt.wait_pending()
        assert committed == path
        assert integrity.verify(path) == (True, "ok")
        assert io._PENDING is None
        assert ckpt.wait_pending() is None  # idempotent

    def test_next_save_finalizes_previous(self, state, tmp_path):
        root = tmp_path / "ck"
        first = ckpt.save(root, state, wait=False)
        second = ckpt.save(root, state.replace(step=state.step + 5))
        assert integrity.verify(first)[0]   # committed by the 2nd save
        assert integrity.verify(second)[0]  # wait=True committed itself

    def test_restore_drains_inflight_save(self, state, tmp_path):
        root = tmp_path / "ck"
        ckpt.save(root, state, wait=False)
        restored = ckpt.restore(root, state)
        assert restored is not None and int(restored.step) == int(state.step)

    def test_async_manifest_records_mesh_provenance(self, state, tmp_path):
        """The pending record must not hold the state tree (donation),
        so provenance is captured at dispatch — and must still land."""
        path = ckpt.save(tmp_path / "ck", state, wait=False)
        ckpt.wait_pending()
        m = json.loads((path / MANIFEST_NAME).read_text())
        assert m["mesh_shape"]["data"] == 2 and m["mesh_shape"]["fsdp"] == 4

    def test_span_pair_emitted(self, state, tmp_path):
        from hyperion_tpu.obs.trace import Tracer

        tele = tmp_path / "t.jsonl"
        tracer = Tracer(tele, run="r", proc=0)
        ckpt.save(tmp_path / "ck", state, wait=False, tracer=tracer)
        ckpt.wait_pending(tracer=tracer)
        tracer.close()
        spans = [json.loads(line) for line in tele.open()]
        names = [s["name"] for s in spans if s.get("kind") == "span"]
        assert names == ["ckpt_dispatch", "ckpt_commit"]
        commit = [s for s in spans if s.get("name") == "ckpt_commit"][0]
        assert commit["overlap_s"] >= 0.0


class TestAsyncSaveKill:
    """Acceptance: SIGKILL during an in-flight async save never yields
    a manifest-verified corrupt checkpoint, and resume lands on a real
    state — the interrupted save either committed fully (orbax's
    atomic rename finished -> adopted via the commit marker) or is
    invisible/unverified and the walk-back falls back to the prior
    verified step. It can never be half-trusted."""

    CHILD = """
import os, signal, sys
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
from hyperion_tpu.checkpoint import io
from hyperion_tpu.models.transformer_lm import TransformerLM, simple_lm_config
from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh
from hyperion_tpu.train.state import create_train_state, make_optimizer

root = sys.argv[1]
mesh = make_mesh(MeshSpec(data=2, fsdp=4))
cfg = simple_lm_config(vocab_size=512, d_model=64, n_heads=2, n_layers=1,
                       ff_dim=256, max_len=8, dropout=0.0)
model = TransformerLM(cfg)
state, _ = create_train_state(
    lambda r: {"params": model.init_params(r)}, make_optimizer(1e-2),
    mesh, jax.random.key(0), policy="fp32",
)
io.save(root, state)  # step 0: committed + manifest (the fallback point)
io.save(root, state.replace(step=state.step + 5), wait=False)
print("DISPATCHED", flush=True)
os.kill(os.getpid(), signal.SIGKILL)  # dies inside the save window
"""

    def test_kill_during_async_save_never_verifies_corrupt(self, tmp_path):
        import os
        import subprocess
        import sys

        from pathlib import Path

        script = tmp_path / "child.py"
        script.write_text(self.CHILD)
        root = tmp_path / "ck"
        repo = Path(__file__).resolve().parents[1]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.pathsep.join(
                       [str(repo)] + ([os.environ["PYTHONPATH"]]
                                      if os.environ.get("PYTHONPATH")
                                      else [])))
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        r = subprocess.run(
            [sys.executable, str(script), str(root)],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert "DISPATCHED" in r.stdout, r.stderr[-2000:]
        assert r.returncode == -9  # really SIGKILLed mid-save

        # invariant 1: no manifest anywhere lies — every dir claiming
        # verification must deep-verify
        for p in root.iterdir():
            if (p / MANIFEST_NAME).exists():
                ok, reason = integrity.verify(p, deep=True)
                assert ok, f"{p.name}: manifest present but {reason}"
        # the committed fallback is intact
        assert integrity.verify(root / "step_00000000")[0]

        # invariant 2: restore lands on a real state — step 5 only if
        # the interrupted save actually completed (adoptable), else the
        # prior verified step 0
        import jax

        from hyperion_tpu.models.transformer_lm import (
            TransformerLM,
            simple_lm_config,
        )
        from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh
        from hyperion_tpu.train.state import create_train_state, make_optimizer

        mesh = make_mesh(MeshSpec(data=2, fsdp=4))
        cfg = simple_lm_config(vocab_size=512, d_model=64, n_heads=2,
                               n_layers=1, ff_dim=256, max_len=8, dropout=0.0)
        model = TransformerLM(cfg)
        template, _ = create_train_state(
            lambda r: {"params": model.init_params(r)}, make_optimizer(1e-2),
            mesh, jax.random.key(0), policy="fp32",
        )
        restored = ckpt.restore(root, template)
        assert restored is not None
        assert int(restored.step) in (0, 5)
        # the bytes are the seed-deterministic init either way, proving
        # the restored state is uncorrupted
        for a, b in zip(jax.tree.leaves(restored.params),
                        jax.tree.leaves(template.params)):
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))


class TestVerification:
    def test_missing_manifest_means_uncommitted(self, state, tmp_path):
        path = ckpt.save(tmp_path / "ck", state)
        (path / MANIFEST_NAME).unlink()
        ok, reason = integrity.verify(path)
        assert not ok and "missing manifest" in reason

    def test_size_and_hash_mismatches(self, state, tmp_path):
        path = ckpt.save(tmp_path / "ck", state)
        payload = corrupt_payload(path)
        ok, reason = integrity.verify(path, deep=False)
        assert not ok and "size mismatch" in reason
        # same size, different bytes: only the deep (hash) check sees it
        path2 = ckpt.save(tmp_path / "ck2", state)
        m = json.loads((path2 / MANIFEST_NAME).read_text())
        target = max(m["files"], key=lambda f: f["bytes"])
        p = path2 / target["path"]
        raw = bytearray(p.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        p.write_bytes(bytes(raw))
        assert integrity.verify(path2, deep=False)[0]
        ok, reason = integrity.verify(path2, deep=True)
        assert not ok and "checksum mismatch" in reason
        del payload


class TestWalkBack:
    def _save_at(self, root, state, step):
        return ckpt.save(root, state.replace(step=state.step + step))

    def test_corrupt_latest_falls_back_and_quarantines(self, state, tmp_path):
        root = tmp_path / "ck"
        self._save_at(root, state, 0)
        newest = self._save_at(root, state, 5)
        corrupt_payload(newest)
        restored = ckpt.restore(root, state)
        assert int(restored.step) == int(state.step)  # fell back to step 0
        corrupt = root / "step_00000005.corrupt"
        assert corrupt.is_dir() and not newest.exists()
        reason = (corrupt / REASON_NAME).read_text()
        assert "size mismatch" in reason

    def test_all_corrupt_returns_none(self, state, tmp_path):
        root = tmp_path / "ck"
        p = self._save_at(root, state, 0)
        # a true partial dir: neither our manifest nor orbax's own
        # commit marker — the save provably never finished, so the
        # legacy-adoption path must not even attempt a restore
        (p / MANIFEST_NAME).unlink()
        (p / "_CHECKPOINT_METADATA").unlink()
        assert ckpt.restore(root, state) is None
        corrupt = root / "step_00000000.corrupt"
        assert corrupt.is_dir()
        assert "partial save" in (corrupt / REASON_NAME).read_text()

    def test_legacy_checkpoint_without_manifest_is_adopted(
        self, state, tmp_path
    ):
        """Checkpoints written before manifests existed must survive the
        upgrade: a manifest-less dir that orbax restores cleanly is
        adopted (manifest backfilled), not quarantined."""
        root = tmp_path / "ck"
        p = self._save_at(root, state, 0)
        (p / MANIFEST_NAME).unlink()  # simulate a pre-manifest save
        restored = ckpt.restore(root, state)
        assert restored is not None and int(restored.step) == int(state.step)
        assert p.is_dir() and not (root / "step_00000000.corrupt").exists()
        assert integrity.verify(p)[0]  # backfilled manifest verifies

    def test_explicit_corrupt_step_raises(self, state, tmp_path):
        root = tmp_path / "ck"
        p = self._save_at(root, state, 3)
        corrupt_payload(p)
        with pytest.raises(ValueError, match="failed verification"):
            ckpt.restore(root, state, step=3)
        assert p.exists()  # explicit requests never quarantine


class TestLatestStepAndPrune:
    def _save_at(self, root, state, step):
        return ckpt.save(root, state.replace(step=state.step + step))

    def test_latest_step_ignores_corrupt_and_health(self, state, tmp_path):
        root = tmp_path / "ck"
        self._save_at(root, state, 2)
        newest = self._save_at(root, state, 7)
        corrupt_payload(newest)
        integrity.quarantine(newest, "test")
        # health evidence snapshots live in a subdir: never a resume point
        self._save_at(root / "health", state, 9)
        assert ckpt.latest_step(root) == 2
        assert ckpt.latest_step(root / "health") == 9

    def test_prune_skips_corrupt_and_protects_newest_verified(
        self, state, tmp_path
    ):
        root = tmp_path / "ck"
        self._save_at(root, state, 0)
        self._save_at(root, state, 5)
        newest = self._save_at(root, state, 9)
        (newest / MANIFEST_NAME).unlink()  # newest never committed
        quarantined = self._save_at(root, state, 7)
        corrupt_payload(quarantined)
        integrity.quarantine(quarantined, "test")
        ckpt.prune(root, keep=1)
        names = sorted(p.name for p in root.iterdir())
        # keep=1 keeps step_9 (newest); step_5 survives as the newest
        # VERIFIED dir; step_0 is deleted; the quarantine is untouched
        assert names == ["step_00000005", "step_00000007.corrupt",
                         "step_00000009"]
        # even keep=0 must not delete the last verified checkpoint
        ckpt.prune(root, keep=0)
        assert sorted(p.name for p in root.iterdir()) == [
            "step_00000005", "step_00000007.corrupt"]

    def test_prune_never_touches_health_subdir(self, state, tmp_path):
        root = tmp_path / "ck"
        self._save_at(root, state, 0)
        self._save_at(root, state, 4)
        self._save_at(root / "health", state, 2)
        ckpt.prune(root, keep=1)
        assert ckpt.latest_step(root / "health") == 2
        assert ckpt.latest_step(root) == 4


class TestGatheredExport:
    def test_roundtrip(self, state, tmp_path):
        p = ckpt.export_gathered(tmp_path / "full.npz", state.params)
        loaded = ckpt.load_gathered(p)
        np.testing.assert_array_equal(
            loaded["tok_emb"]["embedding"],
            np.asarray(state.params["tok_emb"]["embedding"]),
        )
        assert set(loaded) == set(state.params)
