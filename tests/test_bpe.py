"""In-tree byte-level BPE + dataset-prep pipeline (C18 equivalent).

Mirrors the reference's verify habits (dataset_preparation.ipynb:
reload-verify, split counts) as actual assertions.
"""

import numpy as np
import pytest

from hyperion_tpu.data.bpe import ByteBPE, bytes_to_unicode, train_bpe
from hyperion_tpu.data.prepare import encode_split, filter_nonempty, prepare

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick brown fox was here again and again",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump!",
    "the five boxing wizards jump quickly",
] * 20


def small_tok(vocab_size=400):
    return train_bpe(CORPUS, vocab_size=vocab_size)


class TestByteBPE:
    def test_byte_alphabet_covers_all_bytes(self):
        m = bytes_to_unicode()
        assert len(m) == 256
        assert len(set(m.values())) == 256  # invertible

    @pytest.mark.parametrize("text", [
        "the quick brown fox",
        "Hello, World!  multiple  spaces",
        "unicode: déjà vu — naïve café",
        "numbers 12345 and punct !?;:",
        "tabs\tand\nnewlines",
    ])
    def test_encode_decode_roundtrip(self, text):
        tok = small_tok()
        assert tok.decode(tok.encode(text)) == text

    def test_merges_actually_compress(self):
        tok = small_tok()
        ids = tok.encode("the quick brown fox")
        n_bytes = len("the quick brown fox".encode())
        assert len(ids) < n_bytes  # common words merged below byte count

    def test_training_deterministic(self):
        a, b = small_tok(), small_tok()
        assert a.merges == b.merges
        assert a.vocab == b.vocab

    def test_eos_reserved(self):
        tok = small_tok(vocab_size=300)
        assert tok.vocab_size <= 300
        assert tok.eos_id == tok.vocab_size - 1

    def test_save_load_gpt2_format(self, tmp_path):
        tok = small_tok()
        tok.save(tmp_path / "tok")
        assert (tmp_path / "tok" / "vocab.json").exists()
        assert (tmp_path / "tok" / "merges.txt").exists()
        tok2 = ByteBPE.load(tmp_path / "tok")
        text = "the quick brown fox jumps"
        assert tok.encode(text) == tok2.encode(text)

    def test_save_load_roundtrips_hash_merges(self):
        """Merges whose symbols start with '#' (markdown/code corpora)
        must survive save/load — only the '#version' header is special."""
        corpus = ["## heading one", "## heading two", "# code comment"] * 30
        tok = train_bpe(corpus, vocab_size=300)
        assert any(a.startswith("#") for a, b in tok.merges)
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            tok.save(d)
            tok2 = ByteBPE.load(d)
        assert tok.merges == tok2.merges
        text = "## heading one"
        assert tok.encode(text) == tok2.encode(text)

    def test_unseen_bytes_still_encode(self):
        tok = small_tok()
        text = "ünseen →  ☃ bytes"
        assert tok.decode(tok.encode(text)) == text


class TestPrepare:
    def test_filter_nonempty(self):
        lines = ["a", "", "  ", "b", "\t"]
        assert filter_nonempty(lines) == ["a", "b"]

    def test_encode_split_shapes_and_padding(self):
        tok = small_tok()
        split = encode_split(tok, CORPUS[:10], seq_len=32)
        assert split.input_ids.shape == (10, 32)
        split.verify(vocab_size=tok.vocab_size)
        # pad region is eos
        pad = split.input_ids[split.attention_mask == 0]
        assert (pad == tok.eos_id).all()

    def test_truncation(self):
        tok = small_tok()
        long_line = " ".join(CORPUS)
        split = encode_split(tok, [long_line], seq_len=16)
        assert split.input_ids.shape == (1, 16)
        assert split.attention_mask.all()

    def test_prepare_end_to_end_recordio(self, tmp_path):
        raw = {
            "train": CORPUS + ["", "   "],
            "validation": CORPUS[:7] + [""],
        }
        out = prepare(raw, base_dir=tmp_path, seq_len=32,
                      vocab_size=400, verbose=False)
        assert len(out["train"]) == len(CORPUS)  # empties filtered
        assert len(out["validation"]) == 7
        td = tmp_path / "wikitext2_tokenized"
        for s in ("train", "validation"):
            assert (td / f"{s}.ids.rio").exists()
            assert (td / f"{s}.mask.rio").exists()
        assert (tmp_path / "tokenizer" / "vocab.json").exists()

        # trainers consume the output: load -> verify -> batch
        from hyperion_tpu.data.text import load_wikitext2

        splits = load_wikitext2(tmp_path, splits=("train",), seq_len=32)
        assert splits["train"].source.startswith("recordio")
        np.testing.assert_array_equal(
            splits["train"].input_ids, out["train"].input_ids)

    def test_prepare_reuses_existing_tokenizer(self, tmp_path):
        raw = {"train": CORPUS}
        prepare(raw, base_dir=tmp_path, seq_len=32, vocab_size=400,
                verbose=False)
        v1 = (tmp_path / "tokenizer" / "vocab.json").read_text()
        # second run must load, not retrain (same file content)
        prepare(raw, base_dir=tmp_path, seq_len=32, vocab_size=999,
                verbose=False)
        assert (tmp_path / "tokenizer" / "vocab.json").read_text() == v1
