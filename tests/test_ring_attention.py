"""Ring attention vs full attention on the simulated mesh — the
correctness bar for the sequence-parallel path (SURVEY §5.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.ops.attention import dot_product_attention
from hyperion_tpu.ops.ring_attention import ring_attention, seq_sharding
from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def seq_mesh():
    # 2-way data, 4-way sequence parallelism
    return make_mesh(MeshSpec(data=2, fsdp=1, model=1, seq=4))


def qkv(shape=(2, 64, 2, 8), seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return [jax.random.normal(k, shape) for k in ks]


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, seq_mesh, causal):
        q, k, v = qkv()
        ref = dot_product_attention(q, k, v, causal=causal)

        sh = seq_sharding(seq_mesh)
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        out = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, seq_mesh, causal=causal)
        )(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_output_stays_seq_sharded(self, seq_mesh):
        q, k, v = qkv()
        sh = seq_sharding(seq_mesh)
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        out = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, seq_mesh, causal=True)
        )(qs, ks, vs)
        assert out.sharding.spec[1] == "seq"

    def test_grad_flows(self, seq_mesh):
        q, k, v = qkv(shape=(2, 32, 2, 8))
        sh = seq_sharding(seq_mesh)
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))

        def loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, seq_mesh, causal=True) ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(qs, ks, vs)
        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4)

    def test_indivisible_seq_raises(self, seq_mesh):
        q, k, v = qkv(shape=(2, 30, 2, 8))
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention(q, k, v, seq_mesh, causal=True)


class TestRingPadding:
    def test_padding_mask_matches_reference(self, seq_mesh):
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        ks = jax.random.split(jax.random.key(5), 3)
        q, k, v = [jax.random.normal(kk, (2, 32, 2, 8), jnp.float32)
                   for kk in ks]
        mask_np = np.ones((2, 32), np.int8)
        mask_np[0, 20:] = 0
        mask_np[1, 28:] = 0
        ref = dot_product_attention(q, k, v, causal=True,
                                    padding_mask=jnp.asarray(mask_np))
        sh = NamedSharding(seq_mesh, P("data", "seq"))
        qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))
        pad = jax.device_put(jnp.asarray(mask_np), sh)
        out = ring_attention(qs, ks_, vs, seq_mesh, causal=True,
                             padding_mask=pad)
        # compare only real-query rows (pad rows are don't-care)
        o, r = np.asarray(out), np.asarray(ref)
        np.testing.assert_allclose(o[0, :20], r[0, :20], atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(o[1, :28], r[1, :28], atol=2e-5, rtol=2e-5)
