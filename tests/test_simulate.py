"""Fleet flight simulator (serve/simulate.py) + the unified clock.

The simulator's promise is twofold and both halves are pinned here:

* it runs the REAL policy code (ServeQueue lanes, BrownoutGovernor,
  RouterPolicy dispatch/affinity/steering, FleetActions, SLO burn
  monitor) on a virtual clock — deterministically, at fleet scale, in
  seconds of wall time;
* everything it does lands on the standard telemetry stream, so the
  unmodified obs plane (`obs doctor`, `obs diff`, the golden-fixture
  contract) consumes a simulated fleet exactly like a live one.

Scenario soaks at design size run under `-m slow`; tier-1 keeps the
small pinned runs, the determinism pin, the seeded-regression demo,
and the obs-plane consumption tests.
"""

from __future__ import annotations

import json
import re
from pathlib import Path

import pytest

from hyperion_tpu.obs import doctor
from hyperion_tpu.obs import diff as obs_diff
from hyperion_tpu.serve import simulate
from hyperion_tpu.utils.clock import SYSTEM, Clock, VirtualClock

REPO = Path(__file__).resolve().parents[1]
FIXTURE = Path(__file__).parent / "data" / "telemetry" / "sim"


def small_failover(**kw) -> dict:
    """The gen_fixtures.py sim arm's scenario: failover scaled to 4
    replicas / 150 requests with asserts rescaled to match."""
    scn = dict(simulate.SCENARIOS["failover"])
    scn.update(replicas=4, requests=150, duration_s=90.0)
    scn["assert"] = {"completed_rate": {"min": 0.80},
                     "duplicate_tokens": {"max": 0},
                     "ejections": {"min": 2},
                     "readmits": {"min": 2}}
    scn.update(kw)
    return scn


# ----------------------------------------------------------------- clock


class TestClock:
    def test_system_clock_is_monotonic_and_walled(self):
        t0 = SYSTEM()
        assert SYSTEM() >= t0
        assert SYSTEM.wall() > 1_600_000_000.0  # a calendar time

    def test_virtual_clock_advances_both_accumulators(self):
        clk = VirtualClock(100.0, wall0=1_000.0)
        clk.advance(2.5)
        assert clk() == 102.5 and clk.wall() == 1_002.5

    def test_virtual_advance_to_never_rewinds(self):
        clk = VirtualClock(100.0)
        clk.advance_to(110.0)
        clk.advance_to(50.0)  # in the past: no-op
        assert clk() == 110.0

    def test_virtual_sleep_advances(self):
        clk = VirtualClock(100.0)
        clk.sleep(3.0)
        assert clk() == 103.0

    def test_virtual_is_a_clock(self):
        # every `clock=` site accepts either; the subtype relation is
        # what makes the injection seamless
        assert isinstance(VirtualClock(), Clock)


# ------------------------------------------------ simulator core promise


class TestSimulator:
    def test_small_failover_passes_its_asserts(self, tmp_path):
        res = simulate.run_scenario(small_failover(),
                                    out=str(tmp_path / "s"))
        assert res["ok"], res["asserts"]
        rep = res["report"]
        assert rep["duplicate_tokens"] == 0
        assert rep["ejections"] >= 2 and rep["readmits"] >= 2
        # the virtual run plays 90 virtual seconds; wall time must be
        # a tiny fraction of that (the whole point of the harness)
        assert res["virtual_s"] >= 89.0
        assert res["wall_s"] < res["virtual_s"]

    def test_same_seed_same_report(self, tmp_path):
        r1 = simulate.run_scenario(small_failover(),
                                   out=str(tmp_path / "a"))
        r2 = simulate.run_scenario(small_failover(),
                                   out=str(tmp_path / "b"))
        assert r1["report"] == r2["report"]
        assert r1["asserts"] == r2["asserts"]

    def test_different_seed_different_traffic(self, tmp_path):
        r1 = simulate.run_scenario(small_failover(),
                                   out=str(tmp_path / "a"))
        r2 = simulate.run_scenario(small_failover(seed=99),
                                   out=str(tmp_path / "b"))
        assert r1["report"] != r2["report"]

    def test_failover_never_duplicates_tokens(self, tmp_path):
        # the exactly-once promise under virtual failover: the REAL
        # StreamDedup replays the redispatched streams and counts
        # duplicate deliveries — the count must be exactly zero. The
        # denser request rate guarantees streams are IN FLIGHT on the
        # killed half, so redispatch actually exercises the replay.
        res = simulate.run_scenario(small_failover(requests=900),
                                    out=str(tmp_path / "s"))
        assert res["report"]["duplicate_tokens"] == 0
        assert res["report"]["redispatched"] >= 1  # failover happened

    def test_seeded_regression_demo_hysteresis_disabled_flaps(
            self, tmp_path):
        """THE acceptance demo: slow_burn passes with the production
        steer hysteresis and FAILS its reversal bound when hysteresis
        is disabled (steer_clear_sweeps=1) — the scenario harness
        catches a policy regression through exported obs metrics."""
        bad = simulate.run_scenario(
            "slow_burn", out=str(tmp_path / "bad"),
            router={"steer_clear_sweeps": 1})
        assert not bad["ok"]
        failed = [a for a in bad["asserts"] if not a["ok"]]
        assert any(a["key"] == "steer_reversals" for a in failed), failed
        assert bad["report"]["steer_reversals"] > 2

    @pytest.mark.slow
    def test_slow_burn_passes_with_production_hysteresis(self, tmp_path):
        good = simulate.run_scenario("slow_burn",
                                     out=str(tmp_path / "good"))
        assert good["ok"], good["asserts"]
        assert 1 <= good["report"]["steer_reversals"] <= 2

    @pytest.mark.slow
    @pytest.mark.parametrize("name", sorted(simulate.SCENARIOS))
    def test_design_size_scenario_asserts_hold(self, name, tmp_path):
        res = simulate.run_scenario(name, out=str(tmp_path / name))
        assert res["ok"], (name, res["asserts"])

    @pytest.mark.slow
    def test_herd_at_fleet_scale(self, tmp_path):
        """The scale acceptance: 10^5 requests over 200 replicas play
        in well under a minute of wall clock, zero jits."""
        res = simulate.run_scenario("herd", replicas=200,
                                    requests=100_000,
                                    out=str(tmp_path / "herd"))
        assert res["ok"], res["asserts"]
        assert res["wall_s"] < 60.0


# ------------------------------------------------- obs-plane consumption


class TestObsPlaneConsumption:
    def test_doctor_reads_fixture_unchanged(self):
        d = doctor.diagnose(FIXTURE)
        assert d["verdict"] == "healthy"
        assert d["sim"]["scenario"] == "failover"
        assert d["sim"]["ok"] is True
        assert d["sim"]["failed"] == 0
        assert d["sim"]["incident"] is None

    def test_doctor_names_failed_sim_assert(self, tmp_path):
        scn = small_failover()
        scn["assert"]["completed_rate"] = {"min": 1.01}  # impossible
        res = simulate.run_scenario(scn, out=str(tmp_path))
        assert not res["ok"]
        d = doctor.diagnose(tmp_path)
        assert d["sim"]["ok"] is False
        assert "completed_rate" in d["reason"] and "sim:" in d["reason"]
        md = doctor.render_markdown(d)
        assert "FAILED" in md and "completed_rate" in md

    def test_doctor_markdown_renders_passing_sim_row(self):
        md = doctor.render_markdown(doctor.diagnose(FIXTURE))
        assert "simulation `failover`" in md
        assert "assertion(s) held" in md

    def test_fixture_sim_report_event_contract(self):
        """Pin the simulator's own event vocabulary: the header and
        verdict records future tooling (and the doctor today) key on."""
        recs = [json.loads(line) for line in
                (FIXTURE / "telemetry.jsonl").read_text().splitlines()]
        (hdr,) = [r for r in recs if r["name"] == "sim_scenario"]
        assert hdr["scenario"] == "failover"
        for field in ("replicas", "requests", "duration_s", "seed",
                      "faults"):
            assert isinstance(hdr[field], (int, float)), field
        (rep,) = [r for r in recs if r["name"] == "sim_report"]
        assert rep["ok"] is True and rep["failed"] == 0
        assert isinstance(rep["report"], dict)
        for key in simulate.REPORT_KEYS:
            assert key in rep["report"], key
        # the standard router vocabulary rides the same stream
        names = {r["name"] for r in recs}
        assert {"router_start", "router_end", "replica_ready",
                "route_dispatch", "route_complete",
                "replica_ejected"} <= names

    def test_diff_normalizes_fleet_sim_row(self):
        doc = {"metric": "synthetic", "value": 1.0,
               "fleet_sim": {simulate.diff_key(s, k): 1.0
                             for s, keys in simulate.DIFF_GATED.items()
                             for k in keys}}
        out = obs_diff.normalize(doc)
        for s, keys in simulate.DIFF_GATED.items():
            for k in keys:
                assert simulate.diff_key(s, k) in out

    def test_diff_flags_simulated_policy_regression(self):
        """A duplicate delivery appearing in the sim row regresses the
        diff even from a zero base (ZERO_PINNED)."""
        base = {"label": "base", "metrics":
                {"sim_failover_duplicate_tokens": 0.0,
                 "sim_failover_completed_rate": 1.0}}
        cand = {"label": "cand", "metrics":
                {"sim_failover_duplicate_tokens": 2.0,
                 "sim_failover_completed_rate": 1.0}}
        d = obs_diff.diff(base, cand)
        row = {r["metric"]: r for r in d["rows"]}
        assert row["sim_failover_duplicate_tokens"]["regression"] is True
        assert "sim_failover_duplicate_tokens" in d["regressions"]

    def test_every_diff_gated_key_is_gated(self):
        for s, keys in simulate.DIFF_GATED.items():
            for k in keys:
                assert simulate.diff_key(s, k) in obs_diff.METRICS


# --------------------------------------------------------- CLI + guards


class TestCLI:
    def test_list_exits_zero(self, capsys):
        assert simulate.main(["--list"]) == 0
        out = capsys.readouterr().out
        for name in simulate.SCENARIOS:
            assert name in out

    def test_unknown_scenario_exits_two(self, capsys):
        assert simulate.main(["nope"]) == 2
        assert "unknown scenario" in capsys.readouterr().err

    def test_no_scenario_exits_two(self, capsys):
        assert simulate.main([]) == 2
        capsys.readouterr()

    def test_cli_main_dispatches_simulate(self, capsys):
        from hyperion_tpu.cli.main import main as cli_main

        assert cli_main(["simulate", "--list"]) == 0
        assert "herd" in capsys.readouterr().out


class TestClockInjectionGuard:
    """Satellite guard: the policy modules the simulator drives must
    never read real time directly — every read goes through the
    injected clock, or the virtual clock silently loses authority."""

    GUARDED = ("hyperion_tpu/serve/queue.py",
               "hyperion_tpu/serve/router.py",
               "hyperion_tpu/serve/simulate.py")

    @pytest.mark.parametrize("rel", GUARDED)
    def test_no_direct_time_reads(self, rel):
        src = (REPO / rel).read_text()
        # time.perf_counter is allowed: simulate.py reports its own
        # wall-clock cost with it (harness bookkeeping, not policy time)
        hits = re.findall(r"time\.(?:monotonic|time)\(", src)
        assert not hits, (rel, hits)
