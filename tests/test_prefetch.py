"""data/prefetch.py + the trainer's overlap wiring.

The tentpole contract under test: a prefetched run is batch-for-batch
(and final-state) IDENTICAL to the sync path, worker exceptions
propagate to the consumer, close() drains cleanly from any exit, and a
smoke training run's telemetry carries the `input_wait_s` gauge plus
the async-checkpoint `ckpt_dispatch`/`ckpt_commit` span pair.
"""

import json
import time

import numpy as np
import pytest

from hyperion_tpu.data.prefetch import Prefetcher
from hyperion_tpu.data.sharding import ShardedBatches
from hyperion_tpu.data.text import synthetic_lm_split


class TestPrefetcherUnit:
    def test_forwards_items_in_order(self):
        assert list(Prefetcher(iter(range(50)), depth=3)) == list(range(50))

    def test_depth_zero_is_threadless_passthrough(self):
        p = Prefetcher(iter(range(5)), depth=0)
        assert p._thread is None  # the one-switch sync fallback
        assert list(p) == list(range(5))
        assert p.wait_s >= 0.0  # the sync path is still timed

    def test_none_is_a_legal_item(self):
        assert list(Prefetcher(iter([None, 1, None]), depth=2)) == \
            [None, 1, None]

    def test_worker_exception_propagates_after_queued_items(self):
        """A fault mid-stream must surface in the CONSUMER thread, at
        the point the failed batch would have arrived — never die
        silently in the worker."""

        def gen():
            yield 1
            yield 2
            raise OSError("storage blip in the worker")

        got = []
        with pytest.raises(OSError, match="storage blip"):
            for x in Prefetcher(gen(), depth=1):
                got.append(x)
        assert got == [1, 2]

    def test_close_unblocks_a_worker_stuck_on_a_full_queue(self):
        produced = []

        def gen():
            for i in range(100_000):
                produced.append(i)
                yield i

        p = Prefetcher(gen(), depth=2)
        assert next(p) == 0
        deadline = time.monotonic() + 5.0
        while len(produced) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)  # let the worker fill the queue and block
        p.close()
        assert not p._thread.is_alive()
        assert len(produced) < 100_000  # stopped mid-stream, not drained
        p.close()  # idempotent

    def test_wait_s_accumulates_when_producer_is_slow(self):
        def slow_gen():
            for i in range(3):
                time.sleep(0.02)
                yield i

        p = Prefetcher(slow_gen(), depth=2)
        assert list(p) == [0, 1, 2]
        assert p.wait_s > 0.0

    def test_chaos_data_iter_fault_reaches_the_main_thread(self, mesh8):
        """The `fault_point("data_iter")` seam fires inside the WORKER
        once batches assemble ahead — the injected OSError must still
        reach the consuming loop."""
        from hyperion_tpu.testing import chaos
        from hyperion_tpu.utils import retry as retry_mod

        split = synthetic_lm_split(64, seq_len=8, seed=0)
        batches = ShardedBatches(split.arrays(), 16, mesh8, seed=0)
        plan = chaos.ChaosPlan(chaos.parse_plan("io_fail@p=1"))
        retry_mod.set_fault_injector(plan.io_fail)
        try:
            with pytest.raises(OSError, match="injected io_fail"):
                with Prefetcher(batches.epoch(0), depth=2) as feed:
                    list(feed)
        finally:
            retry_mod.set_fault_injector(None)

    def test_prefetched_epoch_identical_to_sync(self, mesh8):
        """Same seeded permutation, batch for batch — the
        semantics-neutrality half of the contract, at the data layer."""
        split = synthetic_lm_split(48, seq_len=8, seed=3)
        batches = ShardedBatches(split.arrays(), 16, mesh8, seed=7)
        sync = [np.asarray(b["input_ids"]) for b in batches.epoch(2)]
        with Prefetcher(batches.epoch(2), depth=3) as feed:
            prefetched = [np.asarray(b["input_ids"]) for b in feed]
        assert len(sync) == len(prefetched) == 3
        for a, b in zip(sync, prefetched):
            np.testing.assert_array_equal(a, b)


class TestTrainerOverlapE2E:
    """Acceptance: a prefetched training run is bit-identical to the
    sync run, and the telemetry stream carries the new overlap
    evidence."""

    def _run(self, base_dir, depth, telemetry=False):
        from hyperion_tpu.config import Config
        from hyperion_tpu.train.trainer import train_language_model

        cfg = Config()
        cfg.train.epochs = 2
        cfg.train.batch_size = 8
        cfg.train.seq_len = 16
        cfg.train.steps_per_epoch = 2
        cfg.train.learning_rate = 1e-3
        cfg.train.validate = False
        cfg.train.telemetry = telemetry
        cfg.train.prefetch_depth = depth
        cfg.train.base_dir = str(base_dir)
        return train_language_model(cfg)

    def test_prefetched_run_bit_identical_and_telemetry_complete(
        self, tmp_path, mesh_dp, monkeypatch
    ):
        monkeypatch.delenv("HYPERION_TELEMETRY", raising=False)
        sync = self._run(tmp_path / "sync", depth=0)
        pre = self._run(tmp_path / "pre", depth=2, telemetry=True)

        # batch-for-batch identical schedule => identical loss history
        # and a bit-identical final export
        assert [h.loss for h in sync.history] == [h.loss for h in pre.history]
        a = np.load(tmp_path / "sync" / "checkpoints"
                    / "language_ddp_final.npz")
        b = np.load(tmp_path / "pre" / "checkpoints"
                    / "language_ddp_final.npz")
        assert a.files == b.files
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k])

        # the async epoch-boundary saves all committed (manifest after
        # wait_until_finished) before the exports ran
        from hyperion_tpu import checkpoint as ckpt
        from hyperion_tpu.checkpoint import integrity

        job_dir = tmp_path / "pre" / "checkpoints" / "language_ddp_8dev"
        step = ckpt.latest_step(job_dir)
        assert step == 4  # 2 epochs x 2 steps
        assert integrity.verify(job_dir / f"step_{step:08d}")[0]

        # telemetry acceptance: input_wait_s gauge + the span pair
        records = [json.loads(line) for line in
                   (tmp_path / "pre" / "telemetry.jsonl").open()]
        gauges = [r["metrics"]["gauges"] for r in records
                  if r.get("kind") == "snapshot"]
        assert gauges and all("input_wait_s" in g for g in gauges)
        assert any(g.get("input_wait_frac") is not None for g in gauges)
        span_names = {r["name"] for r in records if r.get("kind") == "span"}
        assert {"ckpt_dispatch", "ckpt_commit"} <= span_names
        # the commit half carries the overlap evidence
        commits = [r for r in records if r.get("kind") == "span"
                   and r["name"] == "ckpt_commit"]
        assert all("overlap_s" in c for c in commits)
