"""Speculative decoding (`infer/speculative.py`).

The load-bearing property: greedy speculative output is token-for-token
IDENTICAL to plain greedy KV-cache decoding with the target alone, for
any draft model — good, bad, or the target itself."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperion_tpu.infer.generate import generate
from hyperion_tpu.infer.speculative import accept_draft, generate_speculative
from hyperion_tpu.models.llama import Llama, llama_tiny_config


def _model(seed: int, **kw):
    cfg = llama_tiny_config(**kw)
    model = Llama(cfg)
    params = model.init_params(jax.random.key(seed), batch=1, seq=8)
    return model, {"params": params}


@pytest.fixture(scope="module")
def target():
    return _model(0)


@pytest.fixture(scope="module")
def prompt():
    return jax.random.randint(jax.random.key(7), (1, 8), 1, 250, jnp.int32)


class TestEqualsGreedy:
    def _check(self, target, draft, prompt, n=12, k=4):
        model, variables = target
        dmodel, dvariables = draft
        ref = generate(model, variables, prompt, n)
        out = generate_speculative(
            model, variables, dmodel, dvariables, prompt, n, k=k
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_random_draft(self, target, prompt):
        # an unrelated draft: most proposals rejected, output unchanged
        self._check(target, _model(1), prompt)

    def test_draft_is_target(self, target, prompt):
        # perfect draft: every round fully accepts (exercises the
        # bonus-token path and the draft window re-feed after it)
        self._check(target, target, prompt)

    def test_smaller_draft_architecture(self, target, prompt):
        draft = _model(2, n_layers=1, d_model=32, n_heads=2, n_kv_heads=2,
                       ff_dim=64)
        self._check(target, draft, prompt)

    def test_k_one(self, target, prompt):
        self._check(target, target, prompt, k=1)

    def test_k_larger_than_needed(self, target, prompt):
        self._check(target, target, prompt, n=3, k=6)

    def test_eos_masking_matches(self, target, prompt):
        model, variables = target
        # force an eos the model actually emits: take the 3rd greedy
        # token as the "eos" id so masking kicks in mid-sequence
        ref = generate(model, variables, prompt, 10)
        eos = int(np.asarray(ref)[0, 2])
        ref_eos = generate(model, variables, prompt, 10, eos_id=eos,
                           pad_id=0)
        out = generate_speculative(
            model, variables, model, variables, prompt, 10, k=3,
            eos_id=eos, pad_id=0,
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref_eos))


class TestAcceptDraft:
    """The shared acceptance rule (`accept_draft`) — also the serve
    engine's verify step, so its contract is pinned here directly."""

    def test_partial_prefix_takes_correction(self):
        m, v = accept_draft(jnp.array([[5, 6, 7]]),
                            jnp.array([[5, 6, 9, 8]]))
        assert int(m[0]) == 2
        # accepted tokens are v[:m+1]: the agreeing prefix plus the
        # target's correction at the first disagreement
        np.testing.assert_array_equal(np.asarray(v)[0, :3], [5, 6, 9])

    def test_full_accept_takes_bonus(self):
        m, v = accept_draft(jnp.array([[5, 6, 7]]),
                            jnp.array([[5, 6, 7, 8]]))
        assert int(m[0]) == 3
        np.testing.assert_array_equal(np.asarray(v)[0], [5, 6, 7, 8])

    def test_immediate_miss(self):
        m, v = accept_draft(jnp.array([[9, 9]]), jnp.array([[5, 6, 7]]))
        assert int(m[0]) == 0
        assert int(np.asarray(v)[0, 0]) == 5

    def test_batched_rows_independent(self):
        draft = jnp.array([[5, 6], [1, 2]])
        target = jnp.array([[5, 6, 7], [3, 4, 5]])
        m, _ = accept_draft(draft, target)
        np.testing.assert_array_equal(np.asarray(m), [2, 0])


class TestBatched:
    """Batch lifting (PR 12): rows are independent vmapped lanes, and
    the batch-1 call bypasses vmap entirely so the original
    single-sequence output stays byte-identical."""

    def test_batched_rows_equal_greedy_and_solo(self, target):
        # one batched trace covers both pins: every row equals plain
        # greedy decoding, and row 0 equals the batch-1 (vmap-bypassed)
        # call — so batching changed scheduling, not numerics
        model, variables = target
        prompts = jax.random.randint(
            jax.random.key(11), (2, 8), 1, 250, jnp.int32)
        out = generate_speculative(
            model, variables, model, variables, prompts, 10, k=3)
        ref = generate(model, variables, prompts, 10)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        solo = generate_speculative(
            model, variables, model, variables, prompts[:1], 10, k=3)
        np.testing.assert_array_equal(
            np.asarray(out)[0], np.asarray(solo)[0])


class TestValidation:
    def test_empty_batch_rejected(self, target):
        model, variables = target
        ids = jnp.ones((0, 8), jnp.int32)
        with pytest.raises(ValueError, match="at least one row"):
            generate_speculative(model, variables, model, variables, ids, 4)

    def test_prompt_longer_than_k(self, target):
        model, variables = target
        ids = jnp.ones((1, 3), jnp.int32)
        with pytest.raises(ValueError, match="must exceed k"):
            generate_speculative(model, variables, model, variables, ids, 4,
                                 k=4)

    def test_vocab_mismatch(self, target, prompt):
        model, variables = target
        draft, dvars = _model(3, vocab_size=128)
        with pytest.raises(ValueError, match="vocab mismatch"):
            generate_speculative(model, variables, draft, dvars, prompt, 4)

    def test_length_guard(self, target, prompt):
        model, variables = target
        with pytest.raises(ValueError, match="exceeds max_len"):
            generate_speculative(model, variables, model, variables,
                                 prompt, 10_000)


class TestBreakevenAcceptance:
    """spec_breakeven_acceptance — the pure cost model the RESULTS.md
    pairing analysis uses (decode_bench.spec_breakeven_acceptance)."""

    def test_free_draft_needs_nothing(self):
        from hyperion_tpu.bench.decode_bench import spec_breakeven_acceptance

        # a zero-cost draft: any acceptance that yields >1 token/round
        # wins; breakeven is exactly "rounds emit 1 token" -> p=0
        assert spec_breakeven_acceptance(0.0, 10.0, k=4) == 0.0

    def test_equal_cost_draft_cannot_win(self):
        from hyperion_tpu.bench.decode_bench import spec_breakeven_acceptance

        # k drafts as expensive as the target: round costs (k+1)x, max
        # emission is k+1 tokens — total acceptance exactly TIES, which
        # does not beat plain decode, so the verdict is inf
        assert spec_breakeven_acceptance(10.0, 10.0, k=4) == float("inf")

    def test_overpriced_draft_is_inf(self):
        from hyperion_tpu.bench.decode_bench import spec_breakeven_acceptance

        assert spec_breakeven_acceptance(20.0, 10.0, k=4) == float("inf")

    def test_cheap_draft_breakeven_is_moderate(self):
        from hyperion_tpu.bench.decode_bench import spec_breakeven_acceptance

        # 10x-cheaper draft, k=4: round costs 1.4 target-forwards, so
        # E[tokens] must reach 1.4 -> p around 0.3-0.5
        p = spec_breakeven_acceptance(1.0, 10.0, k=4)
        assert 0.2 < p < 0.6
        # and the model is monotone: cheaper drafts need less agreement
        assert spec_breakeven_acceptance(0.5, 10.0, k=4) < p
