"""Ulysses (all-to-all) sequence parallelism vs full attention.

Same verification pattern as test_ring_attention: outputs on the
simulated mesh must match single-device full attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hyperion_tpu.ops.attention import dot_product_attention
from hyperion_tpu.ops.ulysses import ulysses_attention
from hyperion_tpu.runtime.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def mesh_seq():
    return make_mesh(MeshSpec(data=2, seq=4))


def qkv(shape=(2, 32, 4, 16), seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return [jax.random.normal(k, shape, jnp.float32) for k in ks]


def put(mesh, *arrays):
    sh = NamedSharding(mesh, P("data", "seq"))
    return [jax.device_put(a, sh) for a in arrays]


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh_seq, causal):
        q, k, v = qkv()
        ref = dot_product_attention(q, k, v, causal=causal)
        qs, ks, vs = put(mesh_seq, q, k, v)
        out = ulysses_attention(qs, ks, vs, mesh_seq, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_padding_mask(self, mesh_seq):
        q, k, v = qkv()
        mask = np.ones((2, 32), np.int8)
        mask[:, 24:] = 0
        ref = dot_product_attention(q, k, v, causal=True,
                                    padding_mask=jnp.asarray(mask))
        qs, ks, vs = put(mesh_seq, q, k, v)
        pad = jax.device_put(
            jnp.asarray(mask), NamedSharding(mesh_seq, P("data", "seq")))
        out = ulysses_attention(qs, ks, vs, mesh_seq, causal=True,
                                padding_mask=pad)
        np.testing.assert_allclose(np.asarray(out)[:, :24],
                                   np.asarray(ref)[:, :24],
                                   atol=2e-5, rtol=2e-5)

    def test_pallas_local_kernel(self, mesh_seq):
        """The flash kernel runs unmodified inside the head-sharded
        region — the advertised Ulysses advantage."""
        q, k, v = qkv(shape=(2, 64, 4, 16))
        ref = dot_product_attention(q, k, v, causal=True)
        qs, ks, vs = put(mesh_seq, q, k, v)
        out = ulysses_attention(qs, ks, vs, mesh_seq, causal=True,
                                impl="pallas")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    @pytest.mark.slow
    def test_grads_match_full_attention(self, mesh_seq):
        q, k, v = qkv()

        def loss_sharded(q, k, v):
            return jnp.sum(
                ulysses_attention(q, k, v, mesh_seq, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        qs, ks, vs = put(mesh_seq, q, k, v)
        gs = jax.grad(loss_sharded, argnums=(0, 1, 2))(qs, ks, vs)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gs, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)

    def test_head_cap_raises(self, mesh_seq):
        q, k, v = qkv(shape=(2, 32, 2, 16))  # H=2 < seq axis 4
        qs, ks, vs = put(mesh_seq, q, k, v)
        with pytest.raises(ValueError, match="capped by heads"):
            ulysses_attention(qs, ks, vs, mesh_seq)

    def test_indivisible_seq_raises(self, mesh_seq):
        q, k, v = qkv(shape=(2, 30, 4, 16))
        with pytest.raises(ValueError, match="not divisible"):
            ulysses_attention(q, k, v, mesh_seq)


class TestModelLevelSeqParallel:
    """attention_impl='ring'/'ulysses' as plain model config strings:
    the dispatcher pulls the active mesh, so a seq-sharded forward is
    numerically the xla forward."""

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_lm_forward_matches_xla(self, impl):
        from hyperion_tpu.models.transformer_lm import (
            TransformerLM, simple_lm_config,
        )
        from hyperion_tpu.runtime.mesh import MeshSpec, activate_mesh, make_mesh

        mesh = make_mesh(MeshSpec(data=2, seq=4))
        kw = dict(vocab_size=128, d_model=32, n_heads=4, n_layers=2,
                  ff_dim=64, max_len=32, dropout=0.0)
        xla = TransformerLM(simple_lm_config(**kw))
        par = TransformerLM(simple_lm_config(attention_impl=impl, **kw))
        params = xla.init_params(jax.random.key(0))
        ids_np = np.random.default_rng(0).integers(0, 128, (4, 32))
        mask_np = np.ones((4, 32), np.int8)
        mask_np[:, 28:] = 0
        ids = jnp.asarray(ids_np, jnp.int32)
        mask = jnp.asarray(mask_np)
        ref = xla.apply({"params": params}, ids, padding_mask=mask)

        sh = NamedSharding(mesh, P("data", "seq"))
        ids_s = jax.device_put(ids, sh)
        mask_s = jax.device_put(mask, sh)
        with activate_mesh(mesh):  # scoped: trainers register theirs
            out = jax.jit(
                lambda p, i, m: par.apply({"params": p}, i, padding_mask=m)
            )(params, ids_s, mask_s)
        np.testing.assert_allclose(
            np.asarray(out)[:, :28], np.asarray(ref)[:, :28],
            atol=5e-5, rtol=5e-5,
        )

    def test_no_active_mesh_raises(self):
        from hyperion_tpu.ops.attention import dot_product_attention
        from hyperion_tpu.runtime import mesh as mesh_mod

        prev = mesh_mod.active_mesh()
        mesh_mod.set_active_mesh(None)
        try:
            q = jnp.ones((1, 8, 2, 4))
            with pytest.raises(ValueError, match="active mesh"):
                dot_product_attention(q, q, q, impl="ring")
        finally:
            mesh_mod.set_active_mesh(prev)
