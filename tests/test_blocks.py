"""Paged KV-cache bookkeeping (serve/blocks.py): property-style
random-operation soak over the block manager + radix prefix cache,
plus targeted pins for the invariants the engine's correctness rides
on — no leaks, no double frees, refcounts that return the pool to its
initial free count, and copy-on-write forks that never alias a
writer's tail block.
"""

from __future__ import annotations

import numpy as np
import pytest

from hyperion_tpu.serve.blocks import (
    NULL_BLOCK,
    BlockError,
    BlockManager,
    RadixPrefixCache,
    SeqAlloc,
    blocks_for,
    fork_alloc,
)


class TestBlockManager:
    def test_alloc_is_all_or_nothing_and_deterministic(self):
        mgr = BlockManager(6, 4)
        assert mgr.capacity == 5
        a = mgr.alloc(3)
        assert a == [1, 2, 3]  # ascending, null block never handed out
        assert NULL_BLOCK not in a
        assert mgr.alloc(3) is None       # only 2 left: nothing granted
        assert mgr.num_free == 2          # ...and nothing leaked
        b = mgr.alloc(2)
        assert b == [4, 5]
        mgr.decref(a + b)
        assert mgr.num_free == mgr.capacity

    def test_double_free_and_bad_incref_raise(self):
        mgr = BlockManager(4, 4)
        (blk,) = mgr.alloc(1)
        mgr.decref([blk])
        with pytest.raises(BlockError):
            mgr.decref([blk])
        with pytest.raises(BlockError):
            mgr.incref([blk])

    def test_refcounts_gate_the_free_list(self):
        mgr = BlockManager(4, 4)
        (blk,) = mgr.alloc(1)
        mgr.incref([blk])                 # a second holder
        mgr.decref([blk])
        assert mgr.num_free == 2          # still held
        mgr.decref([blk])
        assert mgr.num_free == 3          # last holder frees

    def test_reservations_track_promises(self):
        mgr = BlockManager(8, 4)
        mgr.reserve(5)
        assert mgr.reserved == 5
        mgr.release(2)
        mgr.release(9)                    # over-release clamps at zero
        assert mgr.reserved == 0


class TestForkCow:
    def test_forked_then_diverged_never_aliases_writers_tail(self):
        """The COW acceptance property: after a fork at a mid-block
        frontier, the writer's tail block and the fork's tail block
        are different physical blocks, while full blocks stay shared."""
        mgr = BlockManager(16, 4)
        seq = SeqAlloc(blocks=mgr.alloc(3))   # covers up to 12 positions
        seq.n_filled = 10                     # mid-block frontier
        fork, copies = fork_alloc(mgr, seq, seq.n_filled)
        assert fork.blocks[:2] == seq.blocks[:2]       # full blocks shared
        assert fork.blocks[2] != seq.blocks[2]         # tail copied
        assert copies == [(seq.blocks[2], fork.blocks[2])]
        # both "write" (append) independently: their tails stay disjoint
        assert set(fork.blocks[2:]).isdisjoint(seq.blocks[2:])
        for b in seq.blocks[:2]:
            assert mgr.refcount(b) == 2
        mgr.decref(seq.blocks)
        mgr.decref(fork.blocks)
        assert mgr.num_free == mgr.capacity

    def test_block_aligned_fork_copies_nothing(self):
        mgr = BlockManager(16, 4)
        seq = SeqAlloc(blocks=mgr.alloc(2))
        fork, copies = fork_alloc(mgr, seq, 8)  # frontier on the boundary
        assert copies == [] and fork.blocks == seq.blocks
        mgr.decref(seq.blocks)
        mgr.decref(fork.blocks)
        assert mgr.num_free == mgr.capacity

    def test_fork_fails_clean_when_pool_dry(self):
        mgr = BlockManager(3, 4)
        seq = SeqAlloc(blocks=mgr.alloc(2))
        fork, copies = fork_alloc(mgr, seq, 6)  # needs a tail copy: no room
        assert fork is None and copies == []
        assert mgr.num_free == 0 and mgr.refcount(seq.blocks[0]) == 1


class TestRadixPrefixCache:
    def _toks(self, seed, n):
        return np.random.default_rng(seed).integers(1, 200, n)

    def test_full_block_match_and_cap(self):
        mgr = BlockManager(32, 4)
        trie = RadixPrefixCache(mgr)
        toks = self._toks(0, 12)
        seq = mgr.alloc(3)
        trie.insert(toks, seq)
        # identical prompt, capped at len-1: the last full chunk cannot
        # fully match (12 > 11), but the COW extension still reuses 3
        # of its 4 tokens via one block copy — 11 of 12 positions cached
        m = trie.lookup(toks, len(toks) - 1)
        assert m.blocks == seq[:2] and m.tokens == 11 and m.cow_src == seq[2]
        # an unrelated prompt matches nothing
        none = trie.lookup(self._toks(99, 12), 11)
        assert none.blocks == [] and none.tokens == 0 and none.cow_src is None

    def test_mid_block_divergence_yields_cow(self):
        mgr = BlockManager(32, 4)
        trie = RadixPrefixCache(mgr)
        toks = self._toks(1, 12)
        seq = mgr.alloc(3)
        trie.insert(toks, seq)
        other = np.concatenate([toks[:10], [199, 198, 197, 196]])
        m = trie.lookup(other, len(other) - 1)
        assert m.blocks == seq[:2]
        assert m.tokens == 10          # 8 full + 2 via COW extension
        assert m.cow_src == seq[2]

    def test_eviction_is_lru_and_refcount_gated(self):
        mgr = BlockManager(32, 4)
        trie = RadixPrefixCache(mgr)
        a, b = self._toks(2, 8), self._toks(3, 8)
        sa, sb = mgr.alloc(2), mgr.alloc(2)
        trie.insert(a, sa)
        trie.insert(b, sb)
        mgr.decref(sa + sb)            # sequences done: trie-only holds
        trie.lookup(a, 8)              # touch a — b becomes LRU
        free0 = mgr.num_free
        assert trie.evict(2) == 2      # frees b's chain, leaves a's
        assert mgr.num_free == free0 + 2
        assert trie.lookup(a, 8).blocks == sa
        assert trie.lookup(b, 8).blocks == []

    def test_shared_chain_is_not_evictable(self):
        mgr = BlockManager(32, 4)
        trie = RadixPrefixCache(mgr)
        toks = self._toks(4, 8)
        seq = mgr.alloc(2)
        trie.insert(toks, seq)         # seq still holds its refs
        assert trie.evictable() == 0
        assert trie.evict(2) == 0
        mgr.decref(seq)
        assert trie.evictable() == 2


class TestRandomOpSoak:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_alloc_fork_free_never_leaks(self, seed):
        """The property-style acceptance test: a random interleaving of
        admit (alloc + trie share), append (grow), fork (COW), free,
        and evict keeps every invariant, and tearing everything down
        returns the pool to its initial free count."""
        rng = np.random.default_rng(seed)
        bs = 4
        mgr = BlockManager(48, bs)
        trie = RadixPrefixCache(mgr)
        live: list[dict] = []          # {"seq", "toks"}
        corpus = [rng.integers(1, 50, int(rng.integers(2, 20)))
                  for _ in range(6)]

        def admit():
            base = corpus[rng.integers(0, len(corpus))]
            toks = np.concatenate(
                [base, rng.integers(1, 50, int(rng.integers(0, 6)))])
            P = len(toks)
            m = trie.lookup(toks, P - 1)
            pin = list(m.blocks) + (
                [m.cow_src] if m.cow_src is not None else [])
            mgr.incref(pin)
            need = blocks_for(P, bs) - len(m.blocks)
            fresh = mgr.alloc(need)
            if fresh is None and trie.evict(need - mgr.num_free):
                fresh = mgr.alloc(need)
            if fresh is None:
                mgr.decref(pin)
                return
            if m.cow_src is not None:
                mgr.decref([m.cow_src])
            seq = SeqAlloc(blocks=list(m.blocks) + fresh,
                           n_shared=len(m.blocks), n_filled=P)
            trie.insert(toks, seq.blocks)
            live.append({"seq": seq, "toks": toks})

        def append():
            if not live:
                return
            entry = live[rng.integers(0, len(live))]
            seq = entry["seq"]
            seq.n_filled += 1
            if seq.n_filled // bs + 1 > len(seq.blocks):
                got = mgr.alloc(1)
                if got is None and trie.evict(1):
                    got = mgr.alloc(1)
                if got is None:
                    seq.n_filled -= 1
                    return
                seq.blocks.extend(got)

        def fork():
            if not live:
                return
            entry = live[rng.integers(0, len(live))]
            seq = entry["seq"]
            f, copies = fork_alloc(mgr, seq, seq.n_filled)
            if f is None:
                return
            f.n_filled = seq.n_filled
            # diverge both: neither may ever touch the other's tail
            if copies:
                assert copies[0][1] != copies[0][0]
                assert f.blocks[-1] != seq.blocks[-1]
            live.append({"seq": f,
                         "toks": entry["toks"][:seq.n_filled]})

        def free():
            if not live:
                return
            entry = live.pop(rng.integers(0, len(live)))
            mgr.decref(entry["seq"].blocks)

        ops = [admit, append, append, fork, free]
        for _ in range(300):
            ops[rng.integers(0, len(ops))]()
            mgr.check()                # free/used partition + refcounts
            # no two live sequences share a TAIL (write-frontier) block
            tails = [e["seq"].blocks[-1] for e in live
                     if e["seq"].blocks
                     and e["seq"].n_filled % bs != 0]
            # a tail may be shared right after a block-aligned fork;
            # only mid-block frontiers are writers
            writers = [t for t in tails]
            assert len(writers) == len(set(writers)), (
                "two writers alias one tail block")

        while live:
            free()
        trie.clear()
        mgr.check()
        assert mgr.num_free == mgr.capacity, "pool leaked blocks"
        assert mgr.reserved == 0


# --------------------------------------------------- host spill tier


def _chain_payload(chain, bs):
    """Deterministic per-chain K/V stand-in: full blocks are immutable
    by the radix invariant, so the chain key fully determines the
    bytes — which makes every restore checkable for bit-identity."""
    import zlib

    seed = zlib.crc32(np.asarray(chain, np.int64).tobytes())
    return np.random.default_rng(seed).standard_normal(
        (2, 2, bs, 4), dtype=np.float32)


class TestHostBlockStore:
    def _store(self, bs=4):
        from hyperion_tpu.serve.hostcache import HostBlockStore

        return HostBlockStore(budget_mb=1, block_size=bs)

    def test_match_walks_consecutive_chain_keys(self):
        bs, store = 4, self._store()
        toks = list(np.random.default_rng(0).integers(1, 200, 12))
        for nblk in (1, 2, 3):
            store.put(toks[:nblk * bs],
                      _chain_payload(toks[:nblk * bs], bs))
        # limit=len-1 (the radix rule): the third block needs position
        # 12 <= 11 and stays un-matched even though the store holds it
        hits = store.match(toks, 0, len(toks) - 1)
        assert len(hits) == 2
        for i, h in enumerate(hits):
            ref = _chain_payload(toks[:(i + 1) * bs], bs)
            assert h.dtype == ref.dtype and np.array_equal(h, ref)
        # a device base of one full block: the walk starts past it
        assert len(store.match(toks, bs, len(toks) - 1)) == 1
        # a missing middle link stops the walk cold
        store.clear()
        store.put(toks[:bs], _chain_payload(toks[:bs], bs))
        store.put(toks[:3 * bs], _chain_payload(toks[:3 * bs], bs))
        assert len(store.match(toks, 0, len(toks))) == 1

    def test_lru_budget_evicts_oldest_and_match_refreshes(self):
        from hyperion_tpu.serve.hostcache import HostBlockStore

        bs = 4
        store = HostBlockStore(budget_mb=1, block_size=bs)
        # ~341 KB each: the fourth put must evict the LRU chain
        big = np.zeros((341, 256), np.float32)
        keys = [list(range(i * 100, i * 100 + bs)) for i in range(4)]
        for k in keys[:3]:
            assert store.put(k, big + sum(k))
        assert store.evictions == 0
        store.match(keys[0], 0, bs)        # touch 0 — key 1 becomes LRU
        assert store.put(keys[3], big)
        assert store.evictions == 1
        assert store.bytes_used <= store.budget_bytes
        assert store.match(keys[1], 0, bs) == []      # the LRU died
        assert len(store.match(keys[0], 0, bs)) == 1  # the touched lived
        # an oversize payload is refused (counted), never raised
        assert not store.put([900, 901, 902, 903],
                             np.zeros(2 ** 19, np.float64))
        assert store.rejected == 1

    def test_duplicate_put_refreshes_not_overwrites(self):
        bs, store = 4, self._store()
        key = [1, 2, 3, 4]
        first = _chain_payload(key, bs)
        assert store.put(key, first)
        assert store.put(key, np.zeros_like(first))  # immutable content
        assert store.bytes_used == first.nbytes      # no double count
        (got,) = store.match(key, 0, bs)
        assert np.array_equal(got, first)

    def test_save_load_roundtrip_bit_identical(self, tmp_path):
        from hyperion_tpu.serve.hostcache import HostBlockStore

        bs, store = 4, self._store()
        toks = list(np.random.default_rng(1).integers(1, 200, 8))
        for nblk in (1, 2):
            store.put(toks[:nblk * bs],
                      _chain_payload(toks[:nblk * bs], bs))
        store.save(str(tmp_path / "hostcache"))
        fresh = HostBlockStore(budget_mb=1, block_size=bs)
        assert fresh.load(str(tmp_path / "hostcache")) == 2
        hits = fresh.match(toks, 0, len(toks))
        assert len(hits) == 2
        for i, h in enumerate(hits):
            assert np.array_equal(
                h, _chain_payload(toks[:(i + 1) * bs], bs))
        # alien geometry loads nothing; a missing dir loads nothing
        alien = HostBlockStore(budget_mb=1, block_size=8)
        assert alien.load(str(tmp_path / "hostcache")) == 0
        assert fresh.load(str(tmp_path / "absent")) == 2 - 2 + 0


class TestRadixSpillSeam:
    def test_evict_demotes_chains_to_host(self):
        """Demote, not delete: every chain `evict` kills at refcount 1
        reaches the spill callback with its FULL token prefix, and the
        host store can then extend a cold device base over the whole
        evicted prefix."""
        from hyperion_tpu.serve.hostcache import HostBlockStore

        bs = 4
        mgr = BlockManager(32, bs)
        store = HostBlockStore(budget_mb=1, block_size=bs)
        spilled = []

        def spill(chain, blk):
            spilled.append((chain, blk))
            store.put(chain, _chain_payload(chain, bs))

        trie = RadixPrefixCache(mgr, spill=spill)
        toks = np.random.default_rng(5).integers(1, 200, 12)
        seq = mgr.alloc(3)
        trie.insert(toks, seq)
        mgr.decref(seq)
        assert trie.evict(3) == 3
        # leaves-first eviction: deepest chain dies first, and each key
        # is the root..block prefix with the block's own tokens last
        assert [len(c) for c, _ in spilled] == [12, 8, 4]
        assert [b for _, b in spilled] == [seq[2], seq[1], seq[0]]
        for chain, _ in spilled:
            assert chain == tuple(int(t) for t in toks[:len(chain)])
        hits = store.match(toks, 0, len(toks) - 1)
        assert len(hits) == 2      # 11-position cap: two full blocks
        assert np.array_equal(
            hits[0], _chain_payload(tuple(toks[:bs]), bs))

    def test_shared_chain_and_clear_never_spill(self):
        mgr = BlockManager(32, 4)
        spilled = []
        trie = RadixPrefixCache(mgr, spill=lambda c, b: spilled.append(c))
        toks = np.random.default_rng(6).integers(1, 200, 8)
        seq = mgr.alloc(2)
        trie.insert(toks, seq)
        assert trie.evict(2) == 0 and spilled == []  # seq still holds
        mgr.decref(seq)
        trie.clear()                # shutdown drops holds, no demotion
        assert spilled == []
        assert mgr.num_free == mgr.capacity


class TestHostSpillSoak:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_spill_restore_soak_never_leaks(self, seed, tmp_path):
        """The tier acceptance property: a random interleaving of admit
        (device + host lookup), free, and pressure-evict (demoting into
        the host store) keeps the device pool leak-free, keeps the host
        byte accounting exact and under budget, and hands back only
        bit-identical payloads — then a save/load survives with every
        chain intact."""
        from hyperion_tpu.serve.hostcache import HostBlockStore

        rng = np.random.default_rng(seed)
        bs = 4
        mgr = BlockManager(24, bs)      # small pool: real evict pressure
        store = HostBlockStore(budget_mb=1, block_size=bs)
        trie = RadixPrefixCache(
            mgr, spill=lambda chain, blk: store.put(
                chain, _chain_payload(chain, bs)))
        live: list[dict] = []
        corpus = [rng.integers(1, 50, int(rng.integers(4, 20)))
                  for _ in range(6)]

        def admit():
            base = corpus[rng.integers(0, len(corpus))]
            toks = np.concatenate(
                [base, rng.integers(1, 50, int(rng.integers(0, 6)))])
            P = len(toks)
            m = trie.lookup(toks, P - 1)
            pin = list(m.blocks) + (
                [m.cow_src] if m.cow_src is not None else [])
            mgr.incref(pin)
            # the host walk starts where device coverage ends — every
            # payload it returns must be byte-for-byte what was spilled
            for i, h in enumerate(store.match(
                    toks, len(m.blocks) * bs, P - 1)):
                chain = tuple(int(t)
                              for t in toks[:(len(m.blocks) + i + 1) * bs])
                ref = _chain_payload(chain, bs)
                assert h.dtype == ref.dtype and np.array_equal(h, ref)
            need = blocks_for(P, bs) - len(m.blocks)
            fresh = mgr.alloc(need)
            if fresh is None and trie.evict(need - mgr.num_free):
                fresh = mgr.alloc(need)
            if fresh is None:
                mgr.decref(pin)
                return
            if m.cow_src is not None:
                mgr.decref([m.cow_src])
            seq = SeqAlloc(blocks=list(m.blocks) + fresh,
                           n_shared=len(m.blocks), n_filled=P)
            trie.insert(toks, seq.blocks)
            live.append({"seq": seq, "toks": toks})

        def free():
            if not live:
                return
            entry = live.pop(rng.integers(0, len(live)))
            mgr.decref(entry["seq"].blocks)

        def pressure():
            trie.evict(2)

        ops = [admit, admit, free, pressure]
        for _ in range(300):
            ops[rng.integers(0, len(ops))]()
            mgr.check()
            assert store.bytes_used == sum(
                p.nbytes for p in store._chains.values())
            assert store.bytes_used <= store.budget_bytes

        while live:
            free()
        trie.clear()
        mgr.check()
        assert mgr.num_free == mgr.capacity, "pool leaked blocks"
        # the soak really demoted something, and persistence keeps it
        assert store.spills > 0
        snap = {k: v.copy() for k, v in store._chains.items()}
        store.save(str(tmp_path / "hc"))
        fresh = HostBlockStore(budget_mb=1, block_size=bs)
        assert fresh.load(str(tmp_path / "hc")) == len(snap)
        for k, v in snap.items():
            assert np.array_equal(fresh._chains[k], v)
